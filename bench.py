"""Benchmark: end-to-end pipeline wall-clock per simulated day.

North-star metric from BASELINE.json: the daily train -> serve -> generate ->
test loop, run in-process on the TPU. The reference publishes no end-to-end
number; the only defensible baseline quantity is its recorded live-scoring
cost — 8.22 ms/request x 1317 rows = 10.83 s for the stage-4 loop alone
(``notebooks/4-test-model-scoring-service.ipynb`` cell-10; BASELINE.md) —
which *understates* the reference's full day (it excludes train/generate/
deploy overhead), so ``vs_baseline`` = baseline_s / ours_s is conservative.

``--config N`` selects a BASELINE.json config (default 2):

1. single simulated day, in-process train+serve (includes first-compile)
2. jitted linear regressor, 7-day drift loop with daily retrain (default)
3. 3-layer MLP, 30-day drift loop with daily retrain + test
4. batched scoring: 1k-row requests through the data-parallel service
5. two concurrent A/B pipelines (linear vs MLP) sharing the pool

Protocol (configs 2/3/5): bootstrap a fresh store, run the multi-day
simulation, report the mean wall-clock of the steady-state days (day 1
pays one-time XLA compiles and is excluded). Config 4 reports mean seconds
per 1k-row scoring request; config 1 reports the single day.

Prints ONE JSON line to stdout; progress goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from datetime import date

BASELINE_DAY_S = 1317 * 0.00822  # reference stage-4 scoring loop, see above
BASELINE_REQUEST_S = 0.00822  # reference per-request scoring latency


def _steady_mean(results) -> float:
    steady = [r.wall_clock_s for r in results[1:]] or [results[0].wall_clock_s]
    return sum(steady) / len(steady)


def _run_sim(model_type: str, days: int, model_kwargs=None):
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline
    from bodywork_tpu.store import FilesystemStore

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-store-"))
    spec = default_pipeline(
        model_type=model_type, scoring_mode="batch", overlap_generate=True
    )
    if model_kwargs:
        spec.stages["stage-1-train-model"].args.update(model_kwargs)
    if model_type == "mlp":
        # the reference's 30 s batch budget (bodywork.yaml:20) is sized for
        # its sklearn OLS; the beyond-reference MLP's first-day XLA compile
        # on a cold process needs more headroom
        spec.stages["stage-1-train-model"].max_completion_time_s = 180.0
    runner = LocalRunner(spec, store)
    results = runner.run_simulation(date(2026, 1, 1), days)
    for r in results:
        print(f"  day {r.day}: {r.wall_clock_s:.3f}s", file=sys.stderr)
    return results


def bench_day_loop(model_type: str, days: int, model_kwargs=None) -> dict:
    value = _steady_mean(_run_sim(model_type, days, model_kwargs))
    return {
        "metric": "e2e_day_wallclock",
        "value": round(value, 4),
        "unit": "s/day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def bench_single_day() -> dict:
    results = _run_sim("linear", 1)
    value = results[0].wall_clock_s
    return {
        "metric": "e2e_single_day_wallclock",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def bench_batched_scoring(rows: int = 1000, requests: int = 20) -> dict:
    """Config 4: 1k-row predict requests through the (data-parallel when
    the pool allows) scoring service."""
    import jax
    import numpy as np

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-score-"))
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")
    n_dev = len(jax.devices())
    handle = serve_latest_model(
        store,
        host="127.0.0.1",
        port=0,
        block=False,
        mesh_data=n_dev if n_dev > 1 else None,
    )
    try:
        import requests as rq

        url = handle.url + "/batch"
        rng = np.random.default_rng(0)
        payload = {"X": [float(v) for v in rng.uniform(0, 100, rows)]}
        rq.post(url, json=payload, timeout=30)  # warm
        t0 = time.perf_counter()
        for _ in range(requests):
            resp = rq.post(url, json=payload, timeout=30)
            assert resp.ok and len(resp.json()["predictions"]) == rows
        value = (time.perf_counter() - t0) / requests
    finally:
        handle.stop()
    return {
        "metric": "batched_1k_request_latency",
        "value": round(value, 5),
        "unit": "s/request",
        # reference scores serially at 8.22 ms/row => 1k rows = 8.22 s
        "vs_baseline": round(rows * BASELINE_REQUEST_S / value, 2),
    }


def bench_ab(days: int = 5) -> dict:
    from bodywork_tpu.pipeline import run_ab_simulation, variants_from_model_types

    root = tempfile.mkdtemp(prefix="bench-ab-")
    variants = variants_from_model_types(["linear", "mlp"])
    t0 = time.perf_counter()
    results = run_ab_simulation(variants, root, date(2026, 1, 1), days)
    total = time.perf_counter() - t0
    for name, vr in results.items():
        if vr.error is not None:
            raise SystemExit(f"variant {name} failed: {vr.error!r}")
        print(f"  {name}: {_steady_mean(vr.results):.3f}s/day steady", file=sys.stderr)
    # N pipelines' days delivered per wall-clock second vs one reference day
    value = total / (len(variants) * days)
    return {
        "metric": "ab_day_wallclock_per_pipeline_day",
        "value": round(value, 4),
        "unit": "s/pipeline-day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=int, default=2, choices=[1, 2, 3, 4, 5])
    parser.add_argument(
        "--backend-timeout", type=float, default=240.0,
        help="seconds to wait for the device backend before aborting "
             "(a wedged TPU relay otherwise hangs jax.devices() forever)",
    )
    args = parser.parse_args()

    import os
    import threading

    # A wedged TPU relay blocks jax.devices() inside a C call, where
    # neither KeyboardInterrupt nor SIGALRM handlers can run — only a
    # watchdog thread calling os._exit can abort with a clear message.
    backend_up = threading.Event()

    def _backend_watchdog():
        if not backend_up.wait(args.backend_timeout):
            print(
                "bench: device backend unreachable "
                f"after {args.backend_timeout}s (TPU relay wedged?) — aborting",
                file=sys.stderr,
            )
            sys.stderr.flush()
            os._exit(3)

    if args.backend_timeout > 0:  # <= 0 disables the watchdog
        threading.Thread(target=_backend_watchdog, daemon=True).start()

    import jax

    from bodywork_tpu.utils.logging import configure_logger

    configure_logger(stream=sys.stderr)  # keep stdout = the one JSON line
    print(f"bench devices: {jax.devices()}", file=sys.stderr)
    backend_up.set()  # backend is up; the run itself is unbounded

    if args.config == 1:
        record = bench_single_day()
    elif args.config == 2:
        record = bench_day_loop("linear", days=7)
    elif args.config == 3:
        record = bench_day_loop(
            "mlp", days=30, model_kwargs={"hidden": [64, 64, 64]}
        )
    elif args.config == 4:
        record = bench_batched_scoring()
    else:
        record = bench_ab()
    record["config"] = args.config
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
