"""Benchmark: end-to-end pipeline wall-clock per simulated day.

North-star metric from BASELINE.json: the daily train -> serve -> generate ->
test loop, run in-process on the TPU. The reference publishes no end-to-end
number; the only defensible baseline quantity is its recorded live-scoring
cost — 8.22 ms/request x 1317 rows = 10.83 s for the stage-4 loop alone
(``notebooks/4-test-model-scoring-service.ipynb`` cell-10; BASELINE.md) —
which *understates* the reference's full day (it excludes train/generate/
deploy overhead), so ``vs_baseline`` = baseline_s / ours_s is conservative.

Protocol: bootstrap a fresh store, run a multi-day simulation with the
jitted linear regressor and batched scoring, report the mean wall-clock of
the steady-state days (day 1 pays one-time XLA compiles and is excluded).

Prints ONE JSON line to stdout; progress goes to stderr.
"""
from __future__ import annotations

import json
import sys
import tempfile
from datetime import date

BASELINE_DAY_S = 1317 * 0.00822  # reference stage-4 scoring loop, see above
SIM_DAYS = 5


def main() -> int:
    import jax

    from bodywork_tpu.utils.logging import configure_logger

    configure_logger(stream=sys.stderr)  # keep stdout = the one JSON line
    print(f"bench devices: {jax.devices()}", file=sys.stderr)

    from bodywork_tpu.pipeline import LocalRunner, default_pipeline
    from bodywork_tpu.store import FilesystemStore

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-store-"))
    runner = LocalRunner(
        default_pipeline(
            model_type="linear", scoring_mode="batch", overlap_generate=True
        ),
        store,
    )
    results = runner.run_simulation(date(2026, 1, 1), SIM_DAYS)
    for r in results:
        print(f"  day {r.day}: {r.wall_clock_s:.3f}s", file=sys.stderr)

    steady = [r.wall_clock_s for r in results[1:]] or [
        results[0].wall_clock_s
    ]
    value = sum(steady) / len(steady)
    print(
        json.dumps(
            {
                "metric": "e2e_day_wallclock",
                "value": round(value, 4),
                "unit": "s/day",
                "vs_baseline": round(BASELINE_DAY_S / value, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
