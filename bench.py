"""Benchmark: end-to-end pipeline wall-clock per simulated day.

North-star metric from BASELINE.json: the daily train -> serve -> generate ->
test loop, run in-process on the TPU. The reference publishes no end-to-end
number; the only defensible baseline quantity is its recorded live-scoring
cost — 8.22 ms/request x 1317 rows = 10.83 s for the stage-4 loop alone
(``notebooks/4-test-model-scoring-service.ipynb`` cell-10; BASELINE.md) —
which *understates* the reference's full day (it excludes train/generate/
deploy overhead), so ``vs_baseline`` = baseline_s / ours_s is conservative.

With no arguments, runs the five BASELINE.json configs plus the wide and
serving-concurrency configs and prints ONE JSON line whose top-level
metric is the north-star config-2 record, with every per-config record
under ``"configs"``. ``--config N`` runs a single config:

1. single simulated day, in-process train+serve (includes first-compile)
2. jitted linear regressor, 7-day drift loop with daily retrain
3. 3-layer MLP, 30-day drift loop with daily retrain + test
4. batched scoring: 1k-row requests through the data-parallel service
   (plus, on a real TPU, the fused Pallas-kernel engine as a sub-record,
   each with a device-side HTTP-free latency view)
5. two concurrent A/B pipelines (linear vs MLP) sharing the pool
6. the WIDE workload (beyond-reference): (1024,1024,1024) MLP, 32
   features, batch 8192 — single-device XLA train with an MFU estimate,
   dp x tp sharded train when the pool allows, device-side serving
   through both engines
7. single-row serving under concurrency: HTTP p50/p99 of one-row
   ``/score/v1`` requests against the reference's 8.22 ms/score, plus
   closed-loop concurrent throughput with the cross-request coalescer
   (``serve.batcher``) off vs on — the record that turns "serves heavy
   traffic" from a claim into a number
8. cold-path history load: ``load_all_datasets`` + train-stage wall time
   from a COLD process vs days of history, with the consolidated-history
   snapshot (``data/snapshot.py``) off vs on, realized store-GET counts
   in-record (read from the obs store-op counters). CPU-safe: the
   mechanism is round-trip elimination — O(days) GETs collapse to
   O(1 + tail) — not device speed; the in-record 67 ms/GET projection
   translates the counts onto the measured tunnel transport (PERF.md §1)
9. open-loop serving (``bodywork_tpu.traffic``): seeded arrival-rate
   load at 0.5x/1x/2x of each engine's measured closed-loop capacity,
   thread vs aio front-end — offered vs goodput rps, p50/p99/p99.9 on
   admitted responses (measured from scheduled arrival), shed fraction,
   plus an MMPP burst point and a cross-engine byte-identity check.
   CPU-safe: the mechanism is front-end queueing/admission control
10. incremental training (``train/incremental.py``): a >=90-day per-day
    TRAIN-wall series per mode (full refit vs incremental), linear and
    MLP — last-third/first-third flatness vs the measured 1.21
    full-refit baseline (SCALE_DEV_r05_cpu.json), rows touched per day,
    the linear coefficient-exactness check against an independent
    float64 full refit on the same per-day splits, and the MLP shadow
    quality check against the gate's promotion bound. CPU-safe: the
    mechanism is compute avoidance — O(tail) rows instead of O(history)
11. compiled serving core (``serve/predictor.py`` AOT executable
    cache): zero request-side compile stalls across a live hot swap
    (vs the measured cache-off compile-stall baseline), quantized
    (bf16/int8, shadow-gated) vs f32 single-replica open-loop capacity
    plus the HTTP-free device dispatch view, and an N-replica
    SO_REUSEPORT aio fleet behind ONE shared admission budget —
    capacity ramp, 2x-overload point, scale-out ratio. CPU-safe: the
    mechanisms are compile elimination, weight-byte reduction, and
    kernel connection balancing

Protocol (configs 2/3/5): bootstrap a fresh store, run the multi-day
simulation, report the mean wall-clock of the steady-state days (day 1
pays one-time XLA compiles and is excluded). Config 4 reports mean seconds
per 1k-row scoring request; config 1 reports the single day.

Driver-robustness layer (each defends against an observed failure mode):

- **Per-config subprocesses.** The parent runs every config in a fresh
  child process with a timeout, so a TPU relay that wedges mid-run (the
  round-3 failure: the probe passed, then the relay died) kills one
  config's child, not the whole capture.
- **Bounded re-probe with backoff.** The backend is probed in a throwaway
  subprocess before each config; a dead relay triggers a bounded
  backoff-and-retry cycle (shared budget), and a relay that recovers
  mid-run is picked up by the next config instead of the whole bench
  staying on CPU.
- **Per-config fallback.** Only configs the relay refuses run on CPU;
  each record carries its own ``backend`` field, and the top-level
  ``backend`` summarises ("tpu", "cpu", or "mixed").
- **Resume.** Completed records are staged in ``.bench_state/`` keyed by a
  source-tree fingerprint; a re-run reuses fresh TPU-backed records
  instead of discarding them (``--no-resume`` disables).
- **Compact stdout.** The driver archives a bounded tail of stdout and
  parses the last line; round 3's full record outgrew it and parsed as
  null. stdout now gets a compact summary line (headline + per-config
  one-liners), and the full record goes to ``bench_full.json``.

Prints ONE compact JSON line to stdout; progress goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from datetime import date

BASELINE_DAY_S = 1317 * 0.00822  # reference stage-4 scoring loop, see above
BASELINE_REQUEST_S = 0.00822  # reference per-request scoring latency

ALL_CONFIGS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18)
HEADLINE_CONFIG = 2  # the north-star day loop

#: config 11's padded-bucket sweep — pinned == serve.predictor.
#: DEFAULT_BUCKETS (the AOT-warmed executable set) by
#: tests/test_compiled.py::test_bucket_set_single_source_of_truth, so
#: the shapes the bench measures are exactly the shapes serving compiles
#: and warmup warms: one source of truth, three consumers
COMPILED_SWEEP_BUCKETS = (1, 8, 64, 512, 4096)

# -- config 6: the "wide" workload (no reference analogue) -------------------
# The BASELINE.json configs are all KB-scale (d=2 OLS, 64-wide MLP) — every
# matmul is sub-MXU-tile, so they measure round-trips, not the TPU-first
# design. Config 6 is the first workload where the MXU, the Pallas kernel's
# VMEM residency, and the dp x tp shardings can win or lose: a
# (1024, 1024, 1024) MLP over 32 features, batch 8192.
WIDE_HIDDEN = (1024, 1024, 1024)
WIDE_FEATURES = 32
WIDE_BATCH = 8192
WIDE_STEPS = 50
#: scan length of the MFU-timed training program: long enough that the
#: ~67 ms tunnel round-trip amortised over a group of back-to-back runs
#: is noise next to device time (the round-3 protocol timed 50 steps
#: through fit() — ~214 ms wall including 2+ RTTs and host staging, which
#: understated MFU by ~3x)
MFU_STEPS = 200
#: bf16 MXU peak of one v5e chip (~197 TFLOP/s) — the MFU denominator;
#: the timed program's matmul operands are bf16 (``compute_dtype``), so
#: the bf16 peak is the honest basis (it is also the *harder* denominator
#: for any f32 comparison record).
PEAK_FLOPS_V5E = 197e12


def _steady_days(results) -> list:
    """THE steady-state day slice, defined once for every config: day 1
    (XLA compiles) excluded whenever more than one day exists."""
    return list(results[1:]) or [results[0]]


def _steady_mean(results) -> float:
    steady = [r.wall_clock_s for r in _steady_days(results)]
    return sum(steady) / len(steady)


def _run_sim(model_type: str, days: int, model_kwargs=None):
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline
    from bodywork_tpu.store import FilesystemStore

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-store-"))
    spec = default_pipeline(
        model_type=model_type, scoring_mode="batch", overlap_generate=True
    )
    if model_kwargs:
        spec.stages["stage-1-train-model"].args.update(model_kwargs)
    if model_type == "mlp":
        # the reference's 30 s batch budget (bodywork.yaml:20) is sized for
        # its sklearn OLS; the beyond-reference MLP's first-day XLA compile
        # on a cold process needs more headroom
        spec.stages["stage-1-train-model"].max_completion_time_s = 180.0
    runner = LocalRunner(spec, store)
    results = runner.run_simulation(date(2026, 1, 1), days)
    for r in results:
        print(f"  day {r.day}: {r.wall_clock_s:.3f}s", file=sys.stderr)
    return results


def bench_day_loop(model_type: str, days: int, model_kwargs=None) -> dict:
    value = _steady_mean(_run_sim(model_type, days, model_kwargs))
    return {
        "metric": "e2e_day_wallclock",
        "value": round(value, 4),
        "unit": "s/day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def bench_scale_proof(days: int = 90, model_type: str = "mlp",
                      model_kwargs=None) -> dict:
    """VERDICT r4 item 10: the 90-day horizon proof that per-day cost is
    FLAT as history grows — the fix for the reference's O(days) hot loop
    (one S3 round-trip per historical file, every day —
    ``stage_1_train_model.py:68-71``) proven at 3x the demonstrated
    horizon. Two flatness views, both over steady days (day 1 carries the
    XLA compiles): the least-squares slope of wall-clock vs day index,
    and the last-third/first-third mean ratio (robust to one outlier
    day). A linear O(days) loop would show ratio ~2.3 over 90 days
    (mean history length 75 vs 15 days); the version-token parse cache
    (``data/io.py``) should hold it at ~1."""
    if model_kwargs is None and model_type == "mlp":
        model_kwargs = {"hidden": [64, 64, 64]}
    results = _run_sim(model_type, days, model_kwargs)
    per_day = [round(r.wall_clock_s, 4) for r in results]
    steady = per_day[1:] if len(per_day) > 1 else per_day
    n = len(steady)
    xs = range(n)
    mean_x = sum(xs) / n
    mean_y = sum(steady) / n
    var_x = sum((x - mean_x) ** 2 for x in xs)
    slope = (
        sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, steady)) / var_x
        if var_x else 0.0
    )
    third = max(n // 3, 1)
    first_third = sum(steady[:third]) / third
    last_third = sum(steady[-third:]) / third
    return {
        "metric": "day_wallclock_flatness",
        # headline: growth over the whole horizon as a fraction of the
        # mean day — ~0 is the done-criterion, ~1.3 would be the O(days)
        # signature at this horizon
        "value": round(slope * (n - 1) / mean_y, 4) if mean_y else None,
        "unit": "fractional growth over horizon",
        "days": days,
        "model": model_type,
        "steady_mean_s": round(mean_y, 4),
        "slope_s_per_day": round(slope, 6),
        "last_third_over_first_third": round(last_third / first_third, 4)
        if first_third else None,
        "per_day_s": per_day,
        "vs_baseline": None,
        "baseline_note": "flatness proof, not a speed headline; the "
                         "reference's loop is O(days) by construction",
    }


def bench_single_day() -> dict:
    results = _run_sim("linear", 1)
    value = results[0].wall_clock_s
    return {
        "metric": "e2e_single_day_wallclock",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def _time_requests(url: str, payload: dict, rows: int, requests: int) -> float:
    import requests as rq

    rq.post(url, json=payload, timeout=60)  # warm
    t0 = time.perf_counter()
    for _ in range(requests):
        resp = rq.post(url, json=payload, timeout=60)
        assert resp.ok and len(resp.json()["predictions"]) == rows
    return (time.perf_counter() - t0) / requests


def measure_sync_overhead(repeats: int = 5) -> float:
    """The fixed cost of one ``fence`` on an already-computed array: tiny
    derived-scalar dispatch + one host<->device round-trip + 4-byte fetch.
    Median over ``repeats``. Timed loops that end in one fence subtract
    this so the reported per-iteration time is device execution, not
    transport."""
    import statistics

    import jax.numpy as jnp

    from bodywork_tpu.utils.sync import fence

    ready = fence(jnp.arange(8, dtype=jnp.float32) + 1.0)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fence(ready)
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def time_device_batch(dispatch, X, iters: int = 30, repeats: int = 3,
                      sync_overhead_s: float | None = None) -> dict:
    """Device-side (HTTP-free) latency of one batch through ``dispatch``.

    The input is ``device_put`` once so no per-call host->device transfer is
    timed. Every synchronisation is a ``fence`` (derived-scalar
    ``device_get``), NOT ``block_until_ready`` — over the axon relay the
    latter can return before execution finishes (see
    ``bodywork_tpu.utils.sync``), which made round-4's first capture report
    engine times that were pure dispatch overhead. Two numbers, because on
    a tunnel-attached TPU they differ by the tunnel round-trip:

    - ``pipelined_s`` — N dispatches then ONE fence, divided by N: the
      round-trip amortises away, leaving per-batch device execution +
      dispatch cost. This is the number that isolates the serving engine
      (XLA vs Pallas) from the transport. The fence's own fixed cost
      (measured by ``measure_sync_overhead``) is subtracted from each
      pass before dividing; the raw passes are recorded alongside.
    - ``sync_s`` — mean of per-dispatch fences: what one isolated request
      would wait for the device, including one full host<->device
      round-trip per call (RTT-floor-bound over a tunnel). Not corrected —
      the round-trip is part of what it measures.

    Protocol: the pipelined measurement is the MIN over ``repeats``
    passes (each: N dispatches, one fence), run BEFORE the sync pass.
    Repeated passes through the tunnel are visibly bimodal — the same
    Pallas executable measured 4.0 ms on one pass and 1.9 ms on a later
    pass in the same process while XLA sat at ~3.5 ms throughout — so a
    single pass can report transport contamination as engine time; the
    min is the standard robust floor estimator for latency and every
    pass is recorded for transparency.
    """
    import statistics

    import jax

    from bodywork_tpu.utils.sync import fence

    if sync_overhead_s is None:
        sync_overhead_s = measure_sync_overhead()
    Xd = jax.device_put(jnp_float32(X))
    fence(dispatch(Xd))  # compile + warm
    raw_totals = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = dispatch(Xd)
        fence(out)
        raw_totals.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    for _ in range(iters):
        fence(dispatch(Xd))
    sync_s = (time.perf_counter() - t0) / iters
    passes = [max(t - sync_overhead_s, 0.0) / iters for t in raw_totals]
    return {
        "device_sync_s": round(sync_s, 6),
        "device_pipelined_s": round(min(passes), 6),
        # engine-vs-engine claims need more than the min of a bimodal
        # distribution: median + spread expose whether a "win" is one
        # outlier pass (the round-3 Pallas 2.5x rested on exactly that)
        "device_pipelined_median_s": round(statistics.median(passes), 6),
        "device_pipelined_spread_s": round(max(passes) - min(passes), 6),
        "device_pipelined_passes": [round(p, 6) for p in passes],
        "device_pipelined_raw_pass_totals": [round(t, 6) for t in raw_totals],
        "sync_overhead_s": round(sync_overhead_s, 6),
        "sync_method": "fence (derived-scalar device_get); "
                       "block_until_ready is unreliable over the relay",
        "iters": iters,
    }


def jnp_float32(X):
    import jax.numpy as jnp
    import numpy as np

    X = np.asarray(X, dtype=np.float32)
    if X.ndim == 1:
        X = X[:, None]
    return jnp.asarray(X)


def bench_batched_scoring(rows: int = 1000, requests: int = 20) -> dict:
    """Config 4: 1k-row predict requests through the (data-parallel when
    the pool allows) scoring service; on a real TPU also through the fused
    Pallas MLP kernel (``engine='pallas'``) for an engine-vs-engine record.

    Each engine sub-record additionally carries a device-side measurement
    (:func:`time_device_batch`) so the record separates what the tunnel
    costs (end-to-end HTTP value minus ``device_sync_s``) from what the
    engine costs (``device_pipelined_s``).
    """
    import jax
    import numpy as np

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    from functools import partial

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-score-"))
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    linear_result = train_on_history(store, "linear")
    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    request_rows = rng.uniform(0, 100, rows)
    payload = {"X": [float(v) for v in request_rows]}

    handle = serve_latest_model(
        store,
        host="127.0.0.1",
        port=0,
        block=False,
        mesh_data=n_dev if n_dev > 1 else None,
    )
    try:
        value = _time_requests(handle.url + "/batch", payload, rows, requests)
    finally:
        handle.stop()
    record = {
        "metric": "batched_1k_request_latency",
        "value": round(value, 5),
        "unit": "s/request",
        # reference scores serially at 8.22 ms/row => 1k rows = 8.22 s
        "vs_baseline": round(rows * BASELINE_REQUEST_S / value, 2),
    }
    # device-side view of the same batch, no HTTP: end-to-end minus
    # device_sync is what the transport (tunnel) costs
    linear_model = linear_result.model
    linear_apply = jax.jit(type(linear_model).apply)
    # ONE overhead sample shared by every device view in this config —
    # engines corrected with different overhead draws from the bimodal
    # tunnel would skew exactly the comparison the views exist for
    sync_overhead_s = measure_sync_overhead()
    record["device_batch_linear"] = time_device_batch(
        partial(linear_apply, linear_model.params), request_rows,
        sync_overhead_s=sync_overhead_s,
    )

    # Engine-vs-engine sub-records: the SAME MLP checkpoint timed through
    # the XLA apply and through the fused Pallas kernel, so the pair
    # isolates the serving engine (the main record above is the linear
    # model and is not comparable). Pallas is only meaningful on a real
    # TPU — elsewhere it runs in the interpreter, which benchmarks the
    # interpreter, not the kernel.
    if jax.devices()[0].platform == "tpu":
        # a sub-bench failure (e.g. the first real-TPU Mosaic compile)
        # must not discard the already-measured records above
        try:
            from bodywork_tpu.ops import make_pallas_mlp_apply

            mlp_result = train_on_history(
                store, "mlp", model_kwargs={"hidden": [64, 64, 64]}
            )
            mlp_model = mlp_result.model
            xla_apply = jax.jit(type(mlp_model).apply)
            # 10 passes: engine-vs-engine comparisons through the bimodal
            # tunnel need enough passes for min+median+spread to mean
            # something (3 passes let one outlier carry a 2.5x claim)
            device_views = {
                "xla": time_device_batch(
                    partial(xla_apply, mlp_model.params), request_rows,
                    repeats=10, sync_overhead_s=sync_overhead_s,
                ),
                "pallas": time_device_batch(
                    make_pallas_mlp_apply(mlp_model.params), request_rows,
                    repeats=10, sync_overhead_s=sync_overhead_s,
                ),
            }
            engine_values = {}
            for engine in ("xla", "pallas"):
                handle = serve_latest_model(
                    store, host="127.0.0.1", port=0, block=False, engine=engine
                )
                try:
                    engine_values[engine] = _time_requests(
                        handle.url + "/batch", payload, rows, requests
                    )
                finally:
                    handle.stop()
            for engine, value in engine_values.items():
                record[f"{engine}_engine_mlp"] = {
                    "metric": f"batched_1k_request_latency_mlp_{engine}",
                    "value": round(value, 5),
                    "unit": "s/request",
                    "vs_baseline": round(rows * BASELINE_REQUEST_S / value, 2),
                    # the engine-isolating number: device_pipelined_s is
                    # per-batch execution with the tunnel RTT amortised out
                    **device_views[engine],
                }
            # the bf16 engines in the narrow regime — device-side views
            # only (the HTTP path is transport-bound and measured twice
            # above), each in its OWN guard so a bf16 compile failure
            # cannot discard the f32 records already attached. The import
            # + dispatch construction get their own guard too: a failure
            # there must degrade to "bf16 skipped", not bubble to the
            # outer except and mislabel the whole engine sub-bench failed
            try:
                from bodywork_tpu.serve.predictor import bf16_mlp_apply

                bf16_dispatches = {
                    "xla_bf16": lambda: partial(bf16_mlp_apply(),
                                                mlp_model.params),
                    "pallas_bf16": lambda: make_pallas_mlp_apply(
                        mlp_model.params, compute_dtype="bfloat16"
                    ),
                }
            except Exception as exc:
                bf16_dispatches = {}
                record["bf16_engines"] = {
                    "error": f"{type(exc).__name__}: {exc}"
                }
                print(f"bench: bf16 engine setup FAILED: {exc!r}",
                      file=sys.stderr)
            for engine, make_dispatch in bf16_dispatches.items():
                try:
                    record[f"{engine}_engine_mlp"] = {
                        "metric": f"device_batch_latency_mlp_{engine}",
                        **time_device_batch(
                            make_dispatch(), request_rows,
                            repeats=10, sync_overhead_s=sync_overhead_s,
                        ),
                    }
                except Exception as exc:
                    record[f"{engine}_engine_mlp"] = {
                        "error": f"{type(exc).__name__}: {exc}"
                    }
                    print(f"bench: {engine} sub-bench FAILED: {exc!r}",
                          file=sys.stderr)
        except Exception as exc:
            record["pallas_engine"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
            print(f"bench: engine sub-bench FAILED: {exc!r}", file=sys.stderr)
    else:
        record["pallas_engine"] = {
            "skipped": f"non-tpu backend ({jax.devices()[0].platform}); "
            "the kernel would run in the interpreter"
        }
    return record


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of an ascending list (q in [0, 100])."""
    if not sorted_vals:
        return float("nan")
    idx = min(int(round(q / 100.0 * (len(sorted_vals) - 1))),
              len(sorted_vals) - 1)
    return sorted_vals[idx]


#: untimed warm-up requests before the sequential latency loop; also the
#: reconciliation term published as server_side.warmup_requests_included
#: (each closed-loop client adds one more), so the server-vs-client
#: cross-check stays exact if this is ever tuned
WARMUP_REQUESTS = 20


def _time_single_row_latencies(url: str, n: int,
                               warm: int = WARMUP_REQUESTS) -> list:
    """Per-request seconds of ``n`` sequential single-row ``/score/v1``
    posts over one keep-alive session (after ``warm`` untimed ones) —
    the closest HTTP analogue of the reference's recorded 8.22 ms/score
    loop (one client, one row per request)."""
    import requests as rq

    session = rq.Session()
    for _ in range(warm):
        assert session.post(url, json={"X": 50}, timeout=60).ok
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        resp = session.post(url, json={"X": 50}, timeout=60)
        times.append(time.perf_counter() - t0)
        assert resp.ok and "prediction" in resp.json()
    return times


def _closed_loop_throughput(url: str, clients: int,
                            requests_per_client: int) -> dict:
    """``clients`` closed-loop threads, each posting single-row requests
    back-to-back on its own session; returns aggregate requests/s and the
    client-observed latency spread. Closed-loop: each client's next
    request waits for its previous response, so offered load adapts to
    service speed instead of overrunning it."""
    import threading

    import requests as rq

    per_client_times: list[list] = [[] for _ in range(clients)]
    errors: list[str] = []
    start_barrier = threading.Barrier(clients + 1)

    def _client(i: int) -> None:
        session = rq.Session()
        try:
            session.post(url, json={"X": 50}, timeout=60)  # connect + warm
            start_barrier.wait()
            for _ in range(requests_per_client):
                t0 = time.perf_counter()
                resp = session.post(url, json={"X": 50}, timeout=60)
                per_client_times[i].append(time.perf_counter() - t0)
                if not resp.ok:
                    errors.append(f"HTTP {resp.status_code}")
        except Exception as exc:
            errors.append(repr(exc))
            # a client dying pre-barrier must break the barrier, not
            # strand everyone else (and the main thread) on it forever
            start_barrier.abort()

    threads = [
        threading.Thread(target=_client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    try:
        start_barrier.wait()
    except threading.BrokenBarrierError:
        raise RuntimeError(
            f"closed-loop client failed during warm-up: {errors[:3]}"
        )
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"closed-loop clients failed: {errors[:3]}")
    lat = sorted(t for times in per_client_times for t in times)
    total = len(lat)
    return {
        "clients": clients,
        "requests": total,
        "wall_s": round(wall_s, 4),
        "requests_per_s": round(total / wall_s, 2),
        "latency_p50_s": round(_percentile(lat, 50), 6),
        "latency_p99_s": round(_percentile(lat, 99), 6),
    }


def _server_side_phase_summary() -> dict:
    """count/sum/mean of the serving phase histograms
    (``bodywork_tpu.obs``) for the requests observed since the last
    registry reset — the server's own view of the latencies the bench's
    clients measure from outside."""
    from bodywork_tpu.obs import get_registry

    snap = get_registry().snapshot()

    def _hist(name):
        entry = snap.get(name)
        if not entry or not entry["samples"]:
            return None
        sample = entry["samples"][0]
        count = sample["count"]
        return {
            "count": count,
            "sum_s": round(sample["sum"], 6),
            "mean_s": round(sample["sum"] / count, 6) if count else None,
        }

    return {
        "scoring_latency": _hist("bodywork_tpu_scoring_latency_seconds"),
        "queue_wait": _hist("bodywork_tpu_queue_wait_seconds"),
        "device_dispatch": _hist("bodywork_tpu_device_dispatch_seconds"),
    }


def bench_single_row_scoring(
    latency_requests: int = 300,
    concurrency: int = 16,
    requests_per_client: int = 25,
    window_ms: float = 2.0,
    max_rows: int = 64,
) -> dict:
    """Config 7: single-row serving latency and concurrent throughput,
    with the cross-request coalescer (``serve.batcher``) off vs on.

    Two claims, one record:

    - **Latency**: sequential single-row HTTP p50/p99 against the
      reference's recorded 8.22 ms/score (BASELINE.md row 7 — its most
      quotable number, previously never measured here over HTTP). The
      headline ``value`` is the batcher-OFF p50: the honest
      like-for-like comparison. The batcher-ON sequential p50/p99 is
      recorded alongside — it carries the flush window, which is the
      latency cost the coalescer's throughput is bought with.
    - **Throughput**: ``concurrency`` closed-loop clients of single-row
      requests, coalescer off vs on, same service shape. With the
      coalescer on, the worker's device dispatches scale with bucket
      size instead of request count; ``coalescer_stats`` records the
      realised dispatch amortisation (rows per device call).

    Runs to completion on any backend (CPU included): the mechanism under
    test is request-path dispatch amortisation, not device speed.
    """
    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-row-"))
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")
    # bucket set sized to the workload: 1 covers the uncoalesced
    # single-row path, max_rows the largest coalesced flush, 16 the
    # typical partial flush under this concurrency
    buckets = tuple(sorted({1, 16, max_rows}))

    record: dict = {
        "metric": "single_row_http_latency",
        "unit": "s/request",
        "baseline_request_s": BASELINE_REQUEST_S,
        "protocol": (
            f"sequential keep-alive single-row /score/v1 x"
            f"{latency_requests} (p50/p99, nearest-rank), then "
            f"{concurrency} closed-loop clients x{requests_per_client} "
            "requests each, coalescer off vs on "
            f"(window {window_ms} ms, max_rows {max_rows})"
        ),
    }
    variants = {
        "batcher_off": {},
        "batcher_on": {
            "batch_window_ms": window_ms, "batch_max_rows": max_rows,
        },
        # the ISSUE 13 overhead row: the batcher-off shape with request
        # tracing at FULL head sampling (every request minted, sampled,
        # span-recorded, flight-recorder appended — the worst case;
        # production runs a fraction of this). Compared against
        # batcher_off, which runs tracing-off, in tracing_overhead below.
        "tracing_on": {},
    }
    from bodywork_tpu.obs.tracing import configure_tracing, get_tracer

    tracer = get_tracer()
    restore = (tracer.sample_fraction, tracer.seed)
    for name, kwargs in variants.items():
        # fresh registry per variant, so the server-side histograms below
        # cover exactly THIS variant's requests (the registry is
        # process-global and both variants run in this child process)
        from bodywork_tpu.obs import get_registry

        get_registry().reset()
        configure_tracing(1.0 if name == "tracing_on" else 0.0, seed=0)
        handle = serve_latest_model(
            store, host="127.0.0.1", port=0, block=False,
            buckets=buckets, **kwargs,
        )
        try:
            lat = sorted(_time_single_row_latencies(
                handle.url, latency_requests
            ))
            sub = {
                "p50_s": round(_percentile(lat, 50), 6),
                "p99_s": round(_percentile(lat, 99), 6),
                "requests": len(lat),
                "concurrent": _closed_loop_throughput(
                    handle.url, concurrency, requests_per_client
                ),
            }
            # Server-side phase histograms (obs.registry) next to the
            # client-measured numbers: scoring_latency.count must equal
            # every request that hit the service — the client-counted
            # ones PLUS the untimed warm-ups (20 sequential + 1 per
            # closed-loop client), recorded explicitly so the published
            # cross-check is exact — and the server-side mean bounds the
            # client p50 from below (the gap is HTTP + kernel time).
            sub["server_side"] = _server_side_phase_summary()
            sub["server_side"]["warmup_requests_included"] = (
                WARMUP_REQUESTS + concurrency
            )
            sub["server_side"]["client_counted_requests"] = (
                len(lat) + sub["concurrent"]["requests"]
            )
            batcher = handle.app.batcher
            if batcher is not None:
                stats = batcher.stats()
                sub["coalescer_stats"] = stats
                if stats["batches_dispatched"]:
                    sub["rows_per_device_dispatch"] = round(
                        stats["rows_dispatched"]
                        / stats["batches_dispatched"], 2,
                    )
            record[name] = sub
        finally:
            handle.stop()
    # restore whatever tracing config the process had (the bench child
    # may host further configs)
    configure_tracing(*restore)

    off, on = record["batcher_off"], record["batcher_on"]
    tracing = record["tracing_on"]
    # the overhead row the acceptance pins: tracing at full head
    # sampling vs tracing-off, identical serving shape — deltas should
    # sit within run-to-run noise (tracing costs two hashes + span
    # bookkeeping against an HTTP round trip)
    record["tracing_overhead"] = {
        "p50_delta_s": round(tracing["p50_s"] - off["p50_s"], 6),
        "p99_delta_s": round(tracing["p99_s"] - off["p99_s"], 6),
        "p50_ratio": round(tracing["p50_s"] / off["p50_s"], 3),
        "protocol": (
            "tracing_on = batcher-off shape with head sampling 1.0 "
            "(every request traced into the flight recorder); "
            "batcher_off runs tracing-off"
        ),
    }
    record["value"] = off["p50_s"]
    # reference scores one row per 8.22 ms; >1 means our single-row HTTP
    # p50 beats the reference's recorded per-score cost
    record["vs_baseline"] = round(BASELINE_REQUEST_S / off["p50_s"], 2)
    record["concurrent_speedup_on_vs_off"] = round(
        on["concurrent"]["requests_per_s"]
        / off["concurrent"]["requests_per_s"], 3,
    )
    record["window_latency_cost_p50_s"] = round(
        on["p50_s"] - off["p50_s"], 6
    )
    return record


def wide_train_flops_per_step(
    batch: int = WIDE_BATCH,
    d_in: int = WIDE_FEATURES,
    hidden: tuple = WIDE_HIDDEN,
) -> float:
    """Matmul FLOPs of one optimisation step of the wide MLP: forward
    2*b*sum(in_i*out_i) over the dense stack, backward ~2x forward (dL/dW
    and dL/dx matmuls), so ~3x forward per step. Elementwise/optimizer
    FLOPs are noise next to the matmuls and are ignored."""
    widths = (d_in, *hidden, 1)
    fwd = sum(2.0 * batch * a * b for a, b in zip(widths[:-1], widths[1:]))
    return 3.0 * fwd


def _wide_data(n_rows: int = 2 * WIDE_BATCH):
    """Synthetic 32-feature regression data (the drift generator is the
    1-feature parity workload; the wide config is beyond-reference)."""
    import numpy as np

    rng = np.random.default_rng(7)
    X = rng.uniform(-1.0, 1.0, (n_rows, WIDE_FEATURES)).astype(np.float32)
    w = rng.normal(size=WIDE_FEATURES).astype(np.float32)
    y = X @ w + 0.1 * rng.normal(size=n_rows).astype(np.float32)
    return X, y


#: the MXU saturation sweep around the flagship (8192, 1024x3) point:
#: batch scaling at fixed width, width scaling at fixed batch
MXU_SWEEP_POINTS = (
    (2048, (1024, 1024, 1024)),
    (32768, (1024, 1024, 1024)),
    (8192, (512, 512, 512)),
    (8192, (2048, 2048, 2048)),
)

#: serving-regime engine-crossover sweep (VERDICT r4 item 3): hidden
#: widths bracketing PALLAS_AUTO_MIN_WIDTH, each a 3-layer 1-feature MLP
#: serving one bucket-padded 1k-row request — the exact regime
#: ``resolve_engine("auto")`` decides in
SERVE_CROSSOVER_WIDTHS = (64, 128, 256, 512, 1024)


def serve_crossover_width(points: list) -> int | None:
    """The measured Pallas/XLA crossover: the smallest width from which
    the fused kernel's pipelined batch latency beats the XLA apply at
    EVERY larger measured width (a monotone winning suffix — one noisy
    mid-sweep win must not set the auto-engine cut). ``None`` when the
    kernel never sustains a win. Shared with the test that pins
    ``PALLAS_AUTO_MIN_WIDTH`` to the committed capture."""
    valid = []
    for p in points:
        if "error" in p:
            continue
        x = p.get("xla", {}).get("device_pipelined_s")
        k = p.get("pallas", {}).get("device_pipelined_s")
        if x and k and x > 0 and k > 0:
            valid.append((p["width"], x, k))
    valid.sort()
    crossover = None
    for w, x, k in reversed(valid):
        if k < x:
            crossover = w
        else:
            break
    return crossover


def bench_wide(
    steps: int | None = None,
    serve_iters: int | None = None,
    serve_repeats: int | None = None,
    mfu_steps: int | None = None,
    mfu_groups: int | None = None,
    mfu_runs_per_group: int | None = None,
    include_f32: bool | None = None,
    sweep_points: tuple = MXU_SWEEP_POINTS,
    sweep_steps: int = 100,
    force_sweep: bool = False,
    crossover_widths: tuple = SERVE_CROSSOVER_WIDTHS,
    crossover_batch: int = 1024,
    force_crossover: bool = False,
) -> dict:
    """Config 6: the wide MLP through (a) single-device training throughput
    at an explicit bf16 mixed-precision policy (with an f32 comparison
    record), (b) dp x tp sharded training when the pool has >1 device, and
    (c) batched serving device-side through both engines.

    Training throughput protocol (VERDICT r3 item 2): the timed object is
    the jitted ``lax.scan`` training program alone — data device-resident,
    no host staging, no result fetch — dispatched ``mfu_runs_per_group``
    times back-to-back with ONE block per group, min over ``mfu_groups``
    groups. Over a tunnel-attached TPU one blocked call pays a ~67 ms RTT,
    so short timed runs (the round-3 protocol: 50 steps through ``fit``)
    measure mostly transport; here the RTT is amortised to
    ``rtt / (runs * mfu_steps)`` per step. MFU methodology is recorded in
    the record itself.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bodywork_tpu.models.mlp import (
        MLPConfig,
        MLPRegressor,
        _scaled_splits,
        _train_core,
        init_mlp_params,
    )
    from bodywork_tpu.ops import make_pallas_mlp_apply

    from bodywork_tpu.utils.sync import fence

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    peak = PEAK_FLOPS_V5E if on_tpu else None
    # Unset lengths resolve per backend: full protocol on any accelerator;
    # on CPU specifically, the full MFU protocol (6+ dispatches of a
    # 200-step, ~105-GFLOP/step scan plus the f32 comparison) is hours of
    # host BLAS that would blow the child timeout — scale the timed
    # lengths down and say so in the record. Explicit arguments (tests,
    # callers) always win.
    on_cpu = platform == "cpu"
    scaled_defaults_used = False

    def _default(value, cpu_value, full_value):
        nonlocal scaled_defaults_used
        if value is not None:
            return value
        if on_cpu:
            scaled_defaults_used = True
            return cpu_value
        return full_value

    steps = _default(steps, 10, WIDE_STEPS)
    mfu_steps = _default(mfu_steps, 5, MFU_STEPS)
    mfu_groups = _default(mfu_groups, 1, 3)
    mfu_runs_per_group = _default(mfu_runs_per_group, 1, 2)
    include_f32 = _default(include_f32, False, True)
    serve_iters = _default(serve_iters, 3, 20)
    serve_repeats = _default(serve_repeats, 2, 10)
    X, y = _wide_data()
    flops_per_step = wide_train_flops_per_step()
    sizes = (WIDE_FEATURES, *WIDE_HIDDEN, 1)
    sync_overhead_s = measure_sync_overhead()

    # device-resident standardised dataset, shared by every timed path
    ones = jnp.ones(X.shape[0], jnp.float32)
    Xs, ys, _scaler = _scaled_splits(jnp.asarray(X), jnp.asarray(y), ones)
    fence((Xs, ys))

    def _throughput_record(per_step_s: float, n_chips: int,
                           compute_dtype: str | None,
                           group_times: list, timed_steps: int,
                           flops: float | None = None,
                           batch: int = WIDE_BATCH) -> dict:
        """seconds/step + model FLOP/s + MFU estimate — ONE definition for
        the single-device, sharded, and sweep records so they can't
        diverge. A physically impossible number (non-positive interval, or
        MFU above peak — exactly what the broken ``block_until_ready``
        produced) is flagged as ``timing_anomaly`` instead of being
        published as a result."""
        flops = flops_per_step if flops is None else flops
        rec = {
            "seconds_per_step": round(per_step_s, 6),
            "steps": timed_steps,
            "batch": batch,
            "compute_dtype": compute_dtype or "float32(default-precision)",
            "group_seconds": [round(t, 4) for t in group_times],
        }
        if per_step_s <= 0:
            rec["timing_anomaly"] = (
                "non-positive timed interval — the sync did not actually "
                "wait for the device; throughput not computed"
            )
            return rec
        flops_s = flops / per_step_s
        if peak and 100.0 * flops_s / (peak * n_chips) > 100.0:
            # withhold the impossible values entirely — a reader scanning
            # model_tflops_s must never see a number the flag disowns
            rec["timing_anomaly"] = (
                "MFU above hardware peak — timed interval too short "
                "to be a real execution; throughput not computed"
            )
            return rec
        rec["model_tflops_s"] = round(flops_s / 1e12, 2)
        if peak:
            rec["mfu_pct_est"] = round(100.0 * flops_s / (peak * n_chips), 2)
        return rec

    def _time_groups(dispatch_once, groups: int | None = None,
                     runs: int | None = None) -> tuple[float, list]:
        """min-over-groups of back-to-back dispatches, one fence/group;
        the fence's fixed transport cost is subtracted from each group
        before dividing by the runs it contains."""
        groups = mfu_groups if groups is None else groups
        runs = mfu_runs_per_group if runs is None else runs
        group_times = []
        for _ in range(groups):
            t0 = time.perf_counter()
            out = None
            for _ in range(runs):
                out = dispatch_once()
            fence(out)
            elapsed = time.perf_counter() - t0
            group_times.append(max(elapsed - sync_overhead_s, 0.0) / runs)
        return min(group_times), group_times

    train_nodonate = jax.jit(_train_core, static_argnames=("cfg",))

    def _single_device_record(compute_dtype: str | None,
                              hidden: tuple = WIDE_HIDDEN,
                              batch: int = WIDE_BATCH,
                              steps: int | None = None,
                              groups: int | None = None) -> dict:
        steps = mfu_steps if steps is None else steps
        cfg_t = MLPConfig(hidden=hidden, batch_size=batch,
                          n_steps=steps, learning_rate=1e-3,
                          compute_dtype=compute_dtype)
        pt_sizes = (WIDE_FEATURES, *hidden, 1)
        key = jax.random.PRNGKey(0)
        net0 = jax.jit(init_mlp_params, static_argnums=(1,))(key, pt_sizes)
        # compile + warm
        out = train_nodonate(net0, Xs, ys, ones, key, cfg_t)
        fence(out[1])
        best, groups_t = _time_groups(
            lambda: train_nodonate(net0, Xs, ys, ones, key, cfg_t)[1],
            groups=groups,
        )
        return _throughput_record(
            best / steps, 1, compute_dtype, groups_t, steps,
            flops=wide_train_flops_per_step(batch=batch, hidden=hidden),
            batch=batch,
        )

    record: dict = {
        "metric": "wide_mlp_1024x3",
        "hidden": list(WIDE_HIDDEN),
        "features": WIDE_FEATURES,
        "flops_per_step": flops_per_step,
        "mfu_methodology": {
            "peak_flops_per_chip": PEAK_FLOPS_V5E,
            "peak_basis": "v5e bf16 MXU peak per chip",
            "flops_counted": "dense matmuls only, bwd = 2x fwd (3x total); "
                             "elementwise/optimizer FLOPs ignored",
            "timing": f"min over {mfu_groups} groups of "
                      f"{mfu_runs_per_group} back-to-back dispatches of the "
                      f"{mfu_steps}-step jitted scan, one fence per group "
                      "(derived-scalar device_get; block_until_ready is "
                      "unreliable over the relay), fence overhead "
                      "subtracted; dataset device-resident",
            "sync_overhead_s": round(sync_overhead_s, 6),
        },
    }
    if scaled_defaults_used:
        record["cpu_scaled_protocol"] = (
            "timed lengths scaled down on the CPU backend (full protocol "
            "would be hours of host BLAS); structural record, not a "
            "throughput claim"
        )

    record["train_xla_single"] = _single_device_record("bfloat16")
    if include_f32:
        record["train_xla_single_f32"] = _single_device_record(None)

    # the MXU saturation sweep (VERDICT r3 item 2's "batch & width sweep"):
    # where does the flagship point sit on the batch and width scaling
    # curves? TPU-only — on CPU these shapes measure the host BLAS
    # (``force_sweep`` lets tests drive the loop with tiny points).
    if (on_tpu or force_sweep) and sweep_points:
        pts = []
        for b, h in sweep_points:
            try:
                r = _single_device_record("bfloat16", hidden=tuple(h),
                                          batch=b, steps=sweep_steps,
                                          groups=1)
            except Exception as exc:  # one OOM must not void the sweep
                r = {"error": f"{type(exc).__name__}: {exc}"}
            pts.append({"point": f"b{b}_h{h[0]}x{len(h)}", **r})
        record["mxu_sweep"] = {
            "points": pts,
            "note": "single group of back-to-back runs per point — a "
                    "scaling curve around the flagship, not a headline",
        }
    else:
        record["mxu_sweep"] = {
            "skipped": "non-tpu backend" if not on_tpu else "disabled"
        }

    # the round-3-style end-to-end fit (host staging + transfers + fetch
    # included) stays as a comparison record so the protocol change is
    # visible in the capture, not silently re-based
    cfg_fit = MLPConfig(hidden=WIDE_HIDDEN, batch_size=WIDE_BATCH,
                        n_steps=steps, learning_rate=1e-3,
                        compute_dtype="bfloat16")
    # compile-warm the fit AND the fence's own per-leaf getitem programs,
    # so neither trace lands inside the timed window below
    fence(MLPRegressor(cfg_fit).fit(X, y).params)
    t0 = time.perf_counter()
    model = MLPRegressor(cfg_fit).fit(X, y)
    fence(model.params)
    record["train_fit_e2e"] = {
        "seconds_per_step": round((time.perf_counter() - t0) / steps, 6),
        "steps": steps,
        "note": "whole fit() incl. host staging, transfers and final "
                "fetch — NOT an MFU basis; kept for protocol continuity",
    }

    n_dev = len(jax.devices())
    if n_dev >= 2:
        # a sub-bench failure must not discard the already-measured
        # single-device record above (same guard as config 4's engines)
        try:
            import optax

            from bodywork_tpu.parallel import make_mesh
            from bodywork_tpu.parallel.sharding import mlp_param_sharding
            from bodywork_tpu.parallel.train_step import _sharded_train_fn
            from jax.sharding import NamedSharding, PartitionSpec as P

            dp = n_dev // 2  # odd pools: use the largest even subset
            devices = jax.devices()[: dp * 2]
            mesh = make_mesh(data=dp, model=2, devices=devices)
            cfg_t = MLPConfig(hidden=WIDE_HIDDEN, batch_size=WIDE_BATCH,
                              n_steps=mfu_steps, learning_rate=1e-3,
                              compute_dtype="bfloat16")

            net_tmpl = jax.eval_shape(
                lambda k: init_mlp_params(k, sizes), jax.random.PRNGKey(0)
            )
            specs = mlp_param_sharding(mesh, {"net": net_tmpl, "scaler": {}})
            shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs["net"],
                is_leaf=lambda x: isinstance(x, P),
            )
            init_j = jax.jit(init_mlp_params, static_argnums=(1,),
                             out_shardings=shardings)
            opt_init_j = jax.jit(optax.adam(cfg_t.learning_rate).init)
            replicated = NamedSharding(mesh, P())
            t_stage = time.perf_counter()
            Xd = jax.device_put(np.asarray(Xs), replicated)
            yd = jax.device_put(np.asarray(ys), replicated)
            fence((Xd, yd))
            staging_s = time.perf_counter() - t_stage
            run = _sharded_train_fn(mesh, cfg_t)
            key = jax.random.PRNGKey(0)

            def _one_sharded_run():
                # fresh (sharded) net + opt state per run: the train fn
                # donates them; init is on-device and pipelines with the
                # scan, so no host round-trip sneaks into the group
                net = init_j(key, sizes)
                opt_state = opt_init_j(net)
                return run(net, opt_state, Xd, yd, key)[2]

            fence(_one_sharded_run())  # compile + warm
            best, groups = _time_groups(_one_sharded_run)
            sharded_rec = _throughput_record(
                best / mfu_steps, len(devices), "bfloat16", groups, mfu_steps
            )
            sharded_rec["dataset_staging_s"] = round(staging_s, 4)
            sharded_rec["mesh"] = f"{dp}x2"
            record["train_sharded_dp_tp"] = sharded_rec
        except Exception as exc:
            record["train_sharded_dp_tp"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
            print(f"bench: wide sharded sub-bench FAILED: {exc!r}",
                  file=sys.stderr)
    else:
        record["train_sharded_dp_tp"] = {
            "skipped": f"{n_dev} device(s); dp x tp needs >= 2"
        }

    # serving: one 8192x32 batch, device-side, engine vs engine
    Xb = X[:WIDE_BATCH]
    from functools import partial

    xla_apply = jax.jit(type(model).apply)
    record["serve_xla"] = time_device_batch(
        partial(xla_apply, model.params), Xb,
        iters=serve_iters, repeats=serve_repeats,
        sync_overhead_s=sync_overhead_s,
    )
    # the opt-in bf16 serving engine — timed through the SAME shared jit
    # the BF16MLPPredictor serves with, so the measured engine is the
    # served one
    from bodywork_tpu.serve.predictor import bf16_mlp_apply

    record["serve_xla_bf16"] = time_device_batch(
        partial(bf16_mlp_apply(), model.params), Xb,
        iters=serve_iters, repeats=serve_repeats,
        sync_overhead_s=sync_overhead_s,
    )
    if on_tpu:
        record["serve_pallas"] = time_device_batch(
            make_pallas_mlp_apply(model.params), Xb,
            iters=serve_iters, repeats=serve_repeats,
            sync_overhead_s=sync_overhead_s,
        )
        record["serve_pallas_bf16"] = time_device_batch(
            make_pallas_mlp_apply(model.params, compute_dtype="bfloat16"),
            Xb, iters=serve_iters, repeats=serve_repeats,
            sync_overhead_s=sync_overhead_s,
        )
    else:
        skip = {
            "skipped": "non-tpu backend; the kernel would run in the "
            "interpreter"
        }
        record["serve_pallas"] = dict(skip)
        record["serve_pallas_bf16"] = dict(skip)
    # rows/s through the fastest engine's pipelined path, for scale feel
    engine_views = {
        "xla": record["serve_xla"],
        "xla-bf16": record.get("serve_xla_bf16", {}),
        "pallas": record.get("serve_pallas", {}),
        "pallas-bf16": record.get("serve_pallas_bf16", {}),
    }
    timed = {
        name: v["device_pipelined_s"]
        for name, v in engine_views.items()
        if v.get("device_pipelined_s", 0) > 0
    }
    if timed:
        best_engine = min(timed, key=timed.get)
        record["serve_rows_per_s"] = round(WIDE_BATCH / timed[best_engine], 1)
        record["serve_fastest_engine"] = best_engine
    else:
        record["serve_rows_per_s"] = None

    # the serving-regime engine-crossover sweep (VERDICT r4 item 3): the
    # auto-engine cut PALLAS_AUTO_MIN_WIDTH previously interpolated
    # between two data points (width 64 and 1024); this measures every
    # bracketing width in the regime the cut actually decides in —
    # 1-feature 3-layer MLP, one bucket-padded 1k-row request — so the
    # constant can be pinned to a recorded crossover. Params are
    # He-initialised, not trained: batch latency depends on shapes, not
    # weight values, and skipping 5 train-program compiles keeps the
    # sweep inside the config budget.
    if (on_tpu or force_crossover) and crossover_widths:
        rng_c = np.random.default_rng(11)
        Xreq = rng_c.uniform(0.0, 100.0, (crossover_batch, 1)).astype(
            np.float32
        )
        identity_scaler = {
            "x_mean": jnp.zeros((1,), jnp.float32),
            "x_std": jnp.ones((1,), jnp.float32),
            "y_mean": jnp.asarray(0.0, jnp.float32),
            "y_std": jnp.asarray(1.0, jnp.float32),
        }
        cpts = []
        for wdt in crossover_widths:
            try:
                net_c = jax.jit(init_mlp_params, static_argnums=(1,))(
                    jax.random.PRNGKey(wdt), (1, wdt, wdt, wdt, 1)
                )
                m_c = MLPRegressor(
                    MLPConfig(hidden=(wdt, wdt, wdt)),
                    jax.device_put(
                        {"net": net_c, "scaler": identity_scaler}
                    ),
                )
                xla_view = time_device_batch(
                    partial(jax.jit(type(m_c).apply), m_c.params), Xreq,
                    iters=serve_iters, repeats=serve_repeats,
                    sync_overhead_s=sync_overhead_s,
                )
                pal_view = time_device_batch(
                    make_pallas_mlp_apply(m_c.params, interpret=not on_tpu),
                    Xreq,
                    iters=serve_iters, repeats=serve_repeats,
                    sync_overhead_s=sync_overhead_s,
                )
                cpts.append(
                    {"width": wdt, "xla": xla_view, "pallas": pal_view}
                )
            except Exception as exc:  # one width must not void the sweep
                cpts.append(
                    {"width": wdt, "error": f"{type(exc).__name__}: {exc}"}
                )
                print(f"bench: crossover width {wdt} FAILED: {exc!r}",
                      file=sys.stderr)
        record["serve_crossover"] = {
            "batch": crossover_batch,
            "points": cpts,
            "crossover_width": serve_crossover_width(cpts),
            "note": "pipelined per-batch device latency, XLA apply vs "
                    "fused Pallas kernel, per hidden width; "
                    "crossover_width = smallest width with a monotone "
                    "Pallas winning suffix — the measured source for "
                    "serve.server.PALLAS_AUTO_MIN_WIDTH",
        }
    else:
        record["serve_crossover"] = {
            "skipped": "non-tpu backend" if not on_tpu else "disabled"
        }
    _finalize_wide_anomalies(record)
    record["unit"] = "s/step"
    record["vs_baseline"] = None
    record["baseline_note"] = (
        "no reference analogue — beyond-reference workload; the reference's "
        "only model is d=2 OLS (SURVEY.md §2)"
    )
    return record


def _finalize_wide_anomalies(record: dict) -> None:
    """Set config 6's headline ``value`` with one anomaly policy: any
    impossible timing anywhere in the capture (flagship or sweep point)
    means the sync misbehaved in this process, so no number from it can be
    the headline — ``value`` goes null and a combined top-level
    ``timing_anomaly`` (which the resume filter refuses to pin) says what
    was tainted, losing neither message."""
    msgs = []
    flagship = record["train_xla_single"]
    if "timing_anomaly" in flagship:
        msgs.append(f"flagship: {flagship['timing_anomaly']}")
    sweep_pts = record.get("mxu_sweep", {}).get("points", [])
    tainted = [p["point"] for p in sweep_pts if "timing_anomaly" in p]
    if tainted:
        msgs.append(f"sweep point(s) {tainted} timed impossibly")
    if msgs:
        record["value"] = None
        record["timing_anomaly"] = (
            "; ".join(msgs) + " — sync unreliable in this capture"
        )
    else:
        record["value"] = flagship["seconds_per_step"]


def bench_ab(days: int = 5, model_types=("linear", "mlp")) -> dict:
    """Config 5: N concurrent A/B pipelines sharing the pool.

    Protocol now matches configs 2/3 (steady-state mean, day 1 excluded):
    the round-2 capture divided TOTAL wall-clock — including each
    variant's day-1 XLA compiles and store bootstrap — by pipeline-days,
    which is what produced the unexplained '7.4x config 2' number
    (VERDICT r2 item 3); the per-variant steady means (0.10-0.13 s/day on
    the same capture) only went to stderr. Here the headline is the mean
    of per-variant steady-state s/day, and the JSON carries the full
    attribution: per-variant steady mean, first-day cost, per-stage steady
    seconds, the untimed bootstrap overhead, and the total wall-clock the
    old protocol measured.

    Attribution note: ``run_simulation`` pays store bootstrap and the
    horizon's train-bucket compiles BEFORE its timed day loop, so
    ``day1_s`` is the first *timed* day (it still pays the serve-path
    compiles); the pre-loop cost appears as ``untimed_bootstrap_s``
    (total wall-clock minus the slowest variant's summed day times).
    """
    from bodywork_tpu.pipeline import run_ab_simulation, variants_from_model_types

    root = tempfile.mkdtemp(prefix="bench-ab-")
    variants = variants_from_model_types(list(model_types))
    t0 = time.perf_counter()
    results = run_ab_simulation(variants, root, date(2026, 1, 1), days)
    total = time.perf_counter() - t0

    variant_records = {}
    steady_means = []
    slowest_day_sum = 0.0
    for name, vr in results.items():
        if vr.error is not None:
            raise RuntimeError(f"variant {name} failed: {vr.error!r}")
        # ONE steady-day slice (shared with configs 2/3 via _steady_days)
        # for both the mean and the stage attribution, so the protocols
        # can never silently diverge again
        steady_days = _steady_days(vr.results)
        steady = sum(r.wall_clock_s for r in steady_days) / len(steady_days)
        steady_means.append(steady)
        slowest_day_sum = max(
            slowest_day_sum, sum(r.wall_clock_s for r in vr.results)
        )
        stage_means = {}
        for r in steady_days:
            for stage, secs in r.stage_seconds.items():
                stage_means.setdefault(stage, []).append(secs)
        variant_records[name] = {
            "steady_s_per_day": round(steady, 4),
            "day1_s": round(vr.results[0].wall_clock_s, 4),
            "stage_seconds_steady": {
                stage: round(sum(v) / len(v), 4)
                for stage, v in sorted(stage_means.items())
            },
        }
        print(f"  {name}: {steady:.3f}s/day steady", file=sys.stderr)

    value = sum(steady_means) / len(steady_means)
    return {
        "metric": "ab_day_wallclock_per_pipeline_day",
        "value": round(value, 4),
        "unit": "s/pipeline-day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
        "protocol": (
            "steady-state mean over variants, day 1 excluded "
            if days > 1
            else "SINGLE-day run: day 1 (serve-path compiles) IS the mean "
        )
        + "(same _steady_days slice as configs 2/3); day1_s is the first "
          "TIMED day — store bootstrap and horizon train-compile prewarm "
          "run before the timer and are untimed_bootstrap_s",
        "variants": variant_records,
        "total_wallclock_s": round(total, 2),
        "untimed_bootstrap_s": round(max(total - slowest_day_sum, 0.0), 2),
        "days": days,
    }


# -- config 8: cold-path history load (snapshot off/on) ----------------------
#: the measured tunnel round-trip floor (PERF.md §1 lower bound) used to
#: project realized GET counts onto remote-store transport — recorded
#: in-record as a PROJECTION, never mixed into measured seconds
COLD_HISTORY_RTT_S = 0.067
COLD_HISTORY_DAYS = (10, 30)
COLD_HISTORY_ROWS_PER_DAY = 500


def _fs_get_count() -> float:
    """Realized filesystem-backend GET count from the obs store-op
    counters — the same instrumentation a production scrape reads, so
    the bench's round-trip claims and /metrics can never diverge."""
    from bodywork_tpu.obs import get_registry

    return get_registry().counter("bodywork_tpu_store_ops_total").value(
        backend="filesystem", op="get_bytes"
    )


def bench_history_cold_start(
    days_series=COLD_HISTORY_DAYS,
    rows_per_day: int = COLD_HISTORY_ROWS_PER_DAY,
) -> dict:
    """Config 8: cold-process history reconstruction vs days of history.

    For each horizon: seed a fresh store with synthetic per-day CSVs
    (numpy-generated — the store path is the mechanism under test, not
    the device), then from a COLD store handle (fresh instance = empty
    caches, the per-day-pod regime) measure ``load_all_datasets`` wall
    time and realized GET count with the snapshot absent vs written, and
    the full train-stage wall time both ways. GET counts are in-record
    because on local disk a GET costs ~µs while the deployed transports
    pay ~67-200 ms each (PERF.md §1): the count IS the result, and the
    ``projected_remote_s`` fields translate it at the measured 67 ms
    floor. CPU-safe end to end.
    """
    from datetime import timedelta

    import numpy as np

    from bodywork_tpu.data.io import Dataset, load_all_datasets, persist_dataset
    from bodywork_tpu.data.snapshot import write_snapshot
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    def cold_load(root):
        store = FilesystemStore(root)  # fresh instance: cold caches
        g0 = _fs_get_count()
        t0 = time.perf_counter()
        ds = load_all_datasets(store)
        return time.perf_counter() - t0, int(_fs_get_count() - g0), len(ds)

    def cold_train(root):
        g0 = _fs_get_count()
        t0 = time.perf_counter()
        train_on_history(FilesystemStore(root), "linear")
        return time.perf_counter() - t0, int(_fs_get_count() - g0)

    points = []
    for days in days_series:
        root = tempfile.mkdtemp(prefix=f"bench-cold-{days}d-")
        store = FilesystemStore(root)
        rng = np.random.default_rng(days)
        for i in range(days):
            X = rng.uniform(0, 100, rows_per_day).astype(np.float32)
            y = (1.0 + 0.5 * X + rng.normal(0, 2, rows_per_day)).astype(
                np.float32
            )
            persist_dataset(store, Dataset(X, y, date(2026, 1, 1) + timedelta(days=i)))
        # warm the fit's XLA compile for this horizon's row bucket BEFORE
        # timing, so the off/on train pair differs only in data-plane
        # work, never in who paid the first compile
        cold_train(root)
        off_load_s, off_gets, rows = cold_load(root)
        off_train_s, off_train_gets = cold_train(root)
        write_snapshot(FilesystemStore(root))
        on_load_s, on_gets, rows_on = cold_load(root)
        on_train_s, on_train_gets = cold_train(root)
        assert rows_on == rows, "snapshot path returned a different dataset"
        point = {
            "days": days,
            "rows": rows,
            "snapshot_off": {
                "cold_load_s": round(off_load_s, 5),
                "cold_load_gets": off_gets,
                "train_stage_s": round(off_train_s, 4),
                "train_stage_gets": off_train_gets,
                "projected_remote_load_s": round(
                    off_gets * COLD_HISTORY_RTT_S, 3
                ),
            },
            "snapshot_on": {
                "cold_load_s": round(on_load_s, 5),
                "cold_load_gets": on_gets,
                "train_stage_s": round(on_train_s, 4),
                "train_stage_gets": on_train_gets,
                "projected_remote_load_s": round(
                    on_gets * COLD_HISTORY_RTT_S, 3
                ),
            },
            "get_elimination": round(off_gets / max(on_gets, 1), 2),
        }
        points.append(point)
        print(
            f"  {days}d: load {off_load_s * 1e3:.1f} -> {on_load_s * 1e3:.1f} ms, "
            f"GETs {off_gets} -> {on_gets}",
            file=sys.stderr,
        )
    flagship = points[-1]
    return {
        "metric": "cold_history_load",
        # headline: snapshot-ON cold load at the largest horizon — the
        # per-day-pod startup cost the layer exists to bound
        "value": flagship["snapshot_on"]["cold_load_s"],
        "unit": "s",
        "vs_baseline": None,
        "baseline_note": (
            "the reference re-downloads every day's CSV per training run "
            "(stage_1:68-71) but publishes no load-time number; the "
            "off/on records ARE the comparison, and GET counts project "
            "onto remote transport at the measured 67 ms floor"
        ),
        "rows_per_day": rows_per_day,
        "rtt_model_s": COLD_HISTORY_RTT_S,
        "points": points,
        "protocol": (
            "fresh FilesystemStore instance per measurement (cold caches "
            "= one-shot-pod regime); GET counts read from the obs "
            "bodywork_tpu_store_ops_total counter; seconds are local-disk "
            "wall time (GET counts carry the remote-transport result); "
            "each horizon's fit compile is warmed untimed first, so the "
            "off/on train pair differs only in data-plane work"
        ),
    }


#: open-loop sweep shape (config 9): offered-load multiples of the
#: measured closed-loop capacity. 0.5x shows the uncontended floor, 1x
#: the knee, 2x the overload regime where admission control either
#: holds goodput or the queue collapses.
OPEN_LOOP_FACTORS = (0.5, 1.0, 2.0)
#: offered-rate ceiling: keeps a fast machine's 2x point inside what
#: the single-event-loop driver can schedule faithfully (the record
#: carries send_lag_p99_s so a lagging driver is visible, not silent)
OPEN_LOOP_RATE_CAP_RPS = 2000.0
#: engines config 9 sweeps — pinned == serve.server.SERVER_ENGINES by
#: tests/test_aio.py (the sync guard): a front-end missing here ships
#: unmeasured
OPEN_LOOP_ENGINES = ("thread", "aio")


def _byte_identity_check(urls: dict) -> dict:
    """POST the same bodies to every engine and compare raw response
    bytes — the cross-engine contract (serve.app's shared payload
    builders make it true by construction; this measures it)."""
    import requests as rq

    cases = {
        "single": ("/score/v1", {"X": [50.0]}),
        "batch": ("/score/v1/batch", {"X": [1.0, 2.0, 3.0]}),
        "malformed": ("/score/v1", {"nope": 1}),
    }
    result: dict = {"identical": True, "cases": {}}
    for name, (route, body) in cases.items():
        bodies = {}
        for engine, base in urls.items():
            resp = rq.post(base + route, json=body, timeout=30)
            bodies[engine] = (resp.status_code, resp.content)
        statuses = {engine: b[0] for engine, b in bodies.items()}
        unique = {b for _s, b in bodies.values()}
        result["cases"][name] = {
            "statuses": statuses, "identical": len(unique) == 1,
        }
        if len(unique) != 1:
            result["identical"] = False
    return result


def _open_loop_capacity(url: str, rate_cap_rps: float,
                        window_s: float = 3.0,
                        start_rps: float = 100.0,
                        shards: int = 1) -> tuple[float, list]:
    # (window_s is plumbed through bench_open_loop_serving's
    # capacity_window_s so the tier-1 smoke can shrink the ramp)
    """Capacity estimation (docs/PERF.md §config 9): ramp the offered
    rate (doubling each window) and take the PEAK in-window goodput as
    the sustainable service rate — the top of the classic
    throughput-vs-offered-load curve. Past saturation, in-window
    goodput *under*-states capacity (arrivals near the window's end
    sit behind a queue and complete after it), so the peak — not the
    last window — is the estimate; the ramp stops once a window falls
    clearly past the peak. A closed-loop probe can't do this job here:
    its GIL-sharing client threads saturate the *client* long before
    the event-loop server, underestimating capacity so badly that
    "2x capacity" never overloads anything."""
    from bodywork_tpu.traffic import TrafficConfig, generate_request_log, run_open_loop

    def window(rate: float, seed: int):
        cfg = TrafficConfig(rate_rps=rate, duration_s=window_s, seed=seed)
        return run_open_loop(
            url, generate_request_log(cfg), timeout_s=15.0,
            duration_s=window_s, shards=shards,
        )

    ramp = []
    rate = start_rps
    best = 0.0
    while rate <= rate_cap_rps:
        report = window(rate, seed=89)
        saturated = report.goodput_in_window_rps < 0.9 * report.offered_rps
        if saturated and report.shed_fraction == 0.0:
            # an apparently-saturated window with ZERO sheds is ambiguous:
            # real saturation queues (and on the aio engine sheds), but a
            # host scheduling stall (CPU-quota throttle period, noisy
            # neighbour) produces the same goodput dip. Confirm with a
            # second independent window and keep the better of the two —
            # truncating the ramp on a blip underestimates capacity so
            # badly that the 2x "overload" point never overloads anything.
            retry = window(rate, seed=189)
            if retry.goodput_in_window_rps > report.goodput_in_window_rps:
                report = retry
            saturated = (
                report.goodput_in_window_rps < 0.9 * report.offered_rps
            )
        ramp.append({
            "offered_rps": report.offered_rps,
            "goodput_in_window_rps": report.goodput_in_window_rps,
            "shed_fraction": report.shed_fraction,
        })
        best = max(best, report.goodput_in_window_rps)
        past_peak = report.goodput_in_window_rps < 0.75 * best
        if saturated or past_peak:
            break
        rate *= 2.0
    return best, ramp


def _wait_healthy(base_url: str, proc, timeout_s: float = 90.0) -> None:
    import requests as rq

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve subprocess died during startup "
                f"(rc={proc.returncode})"
            )
        try:
            if rq.get(base_url + "/healthz", timeout=2).status_code == 200:
                return
        except Exception:
            pass
        time.sleep(0.25)
    proc.terminate()
    raise TimeoutError(f"serve subprocess not healthy within {timeout_s}s")


class _ServeTarget:
    """One scoring service under open-loop test — in its own OS process
    (default: the driver's event loop must not steal GIL time from the
    server it is measuring, or capacity collapses with offered load and
    the sweep measures the *bench*) or in-process (``isolate=False``:
    the tier-1 smoke, where rates are too low for contention to
    matter)."""

    def __init__(self, store_path: str, engine: str, window_ms: float | None,
                 max_rows: int | None, buckets, isolate: bool,
                 dtype: str = "float32", mesh_data: int | None = None,
                 env: dict | None = None, max_pending: int | None = None,
                 tuned_config: str | None = None,
                 frontends: int | None = None,
                 transport: str | None = None):
        # window_ms/max_rows/buckets left None are NOT passed (the
        # config-13 tuned servers boot that way so the tuned document —
        # not an explicit flag — supplies every knob)
        self.engine = engine
        self._proc = None
        self._handle = None
        if isolate:
            port = _free_port()
            self.base_url = f"http://127.0.0.1:{port}"
            cmd = [sys.executable, "-m", "bodywork_tpu.cli", "serve",
                   "--store", store_path, "--host", "127.0.0.1",
                   "--port", str(port), "--server-engine", engine,
                   "--reload-interval", "0"]
            if window_ms is not None:
                cmd += ["--batch-window-ms", str(window_ms)]
            if max_rows is not None:
                cmd += ["--batch-max-rows", str(max_rows)]
            if buckets is not None:
                cmd += ["--buckets", ",".join(str(b) for b in buckets)]
            if max_pending is not None:
                cmd += ["--max-pending", str(max_pending)]
            if tuned_config is not None:
                cmd += ["--tuned-config", tuned_config]
            if dtype != "float32":
                cmd += ["--dtype", dtype]
            if mesh_data and mesh_data > 1:
                cmd += ["--mesh-data", str(mesh_data)]
            if frontends is not None:
                cmd += ["--frontends", str(frontends)]
            if transport is not None:
                cmd += ["--transport", transport]
            self._proc = subprocess.Popen(
                cmd,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=env,
            )
            _wait_healthy(self.base_url, self._proc)
        else:
            if frontends is not None or transport is not None:
                raise ValueError(
                    "the disaggregated fleet is OS processes by "
                    "definition; use isolate=True"
                )
            from bodywork_tpu.serve import serve_latest_model
            from bodywork_tpu.store import FilesystemStore

            self._handle = serve_latest_model(
                FilesystemStore(store_path), host="127.0.0.1", port=0,
                block=False, buckets=buckets, batch_window_ms=window_ms,
                batch_max_rows=max_rows, server_engine=engine,
                dtype=dtype, mesh_data=mesh_data, max_pending=max_pending,
                tuned_config=tuned_config,
            )
            self.base_url = self._handle.url.replace("/score/v1", "")

    @property
    def url(self) -> str:
        return self.base_url + "/score/v1"

    def admission_state(self):
        """The /healthz admission block — the same numbers either way,
        read over HTTP so process isolation costs nothing."""
        import requests as rq

        return rq.get(self.base_url + "/healthz", timeout=10).json().get(
            "admission"
        )

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.stop()
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(timeout=10)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def bench_open_loop_serving(
    duration_s: float = 8.0,
    probe_clients: int = 16,
    probe_requests: int = 40,
    load_factors: tuple = OPEN_LOOP_FACTORS,
    window_ms: float = 2.0,
    max_rows: int = 64,
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    mmpp_point: bool = True,
    isolate: bool = True,
    capacity_window_s: float = 3.0,
) -> dict:
    """Config 9: open-loop serving — offered load vs goodput, tail
    latency, and shed fraction at 0.5x/1x/2x measured capacity, for
    both HTTP front-ends.

    Every earlier serving number (configs 4/7) is *closed-loop*: the
    clients wait for responses, so offered load can never exceed
    service rate and queueing collapse is invisible. This config drives
    arrival-rate load (``bodywork_tpu.traffic``, Poisson arrivals,
    seeded) that does NOT slow down when the server falls behind:

    - per engine, estimate capacity with a short closed-loop probe,
      then offer ``load_factors`` multiples of it for ``duration_s``
      each and record offered/goodput rps, p50/p99/p99.9 latency on
      admitted (200) responses measured from the SCHEDULED arrival
      (coordinated-omission-free), and the shed fraction;
    - the aio engine runs with its default admission control: at 2x it
      must shed the excess at the front door and keep goodput ≈
      capacity with bounded p99 — the acceptance claim. The threaded
      engine is the admit-everything contrast: same overload, queueing
      delay instead of sheds;
    - one MMPP (bursty) point at 1x mean rate for the aio engine:
      same offered load as the Poisson 1x point, delivered in squalls —
      burst tolerance, the regime autoscaling reacts too slowly for;
    - a byte-identity check pins that both engines answer the same
      requests with identical bytes (the cross-engine contract that
      makes ``--server-engine`` a pure operational choice).

    CPU-safe: the mechanism under test is front-end queueing/admission,
    not device speed (capacity is measured, not assumed).
    """
    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.traffic import TrafficConfig, generate_request_log, run_open_loop

    store_path = tempfile.mkdtemp(prefix="bench-openloop-")
    store = FilesystemStore(store_path)
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")
    buckets = tuple(sorted({1, 16, max_rows}))

    def start(engine):
        return _ServeTarget(store_path, engine, window_ms, max_rows,
                            buckets, isolate)

    # -- byte-identity across engines (both up at once) ---------------------
    targets = {engine: start(engine) for engine in OPEN_LOOP_ENGINES}
    try:
        identity = _byte_identity_check({
            engine: t.base_url for engine, t in targets.items()
        })
    finally:
        for t in targets.values():
            t.stop()

    # -- per-engine open-loop sweep -----------------------------------------
    engines: dict = {}
    for engine in OPEN_LOOP_ENGINES:
        target = start(engine)
        try:
            # closed-loop view for cross-reference with config 7 (it is
            # NOT the capacity estimate: its GIL-sharing client threads
            # bottleneck before the server does)
            closed_loop = _closed_loop_throughput(
                target.url, probe_clients, probe_requests
            )
            # untimed warm burst: absorbs the front-end's one-time
            # connection-path costs so the first sweep point isn't the
            # one that pays them
            warm_s = min(1.0, duration_s)
            warm_cfg = TrafficConfig(rate_rps=100.0, duration_s=warm_s,
                                     seed=88)
            run_open_loop(target.url, generate_request_log(warm_cfg),
                          timeout_s=15.0, duration_s=warm_s)
            capacity, ramp = _open_loop_capacity(
                target.url, rate_cap_rps, window_s=capacity_window_s
            )
            print(f"  {engine}: estimated capacity {capacity:.0f} rps "
                  f"({len(ramp)} ramp windows)", file=sys.stderr)
            sweep = []
            for i, factor in enumerate(load_factors):
                rate = min(factor * capacity, rate_cap_rps)
                log_cfg = TrafficConfig(
                    rate_rps=rate, duration_s=duration_s,
                    arrival="poisson", seed=90 + i,
                )
                report = run_open_loop(
                    target.url, generate_request_log(log_cfg),
                    timeout_s=30.0, duration_s=duration_s,
                )
                sweep.append({"load_factor": factor, **report.to_dict()})
                print(
                    f"  {engine} {factor}x: offered "
                    f"{report.offered_rps:.0f} -> goodput "
                    f"{report.goodput_in_window_rps:.0f} rps in-window, "
                    f"shed {report.shed_fraction:.1%}, p99 "
                    f"{report.latency['p99_s']}s",
                    file=sys.stderr,
                )
            entry = {
                "closed_loop_reference": closed_loop,
                "capacity_rps": capacity,
                "capacity_ramp": ramp,
                "sweep": sweep,
            }
            if mmpp_point and engine == "aio":
                mmpp_cfg = TrafficConfig(
                    rate_rps=min(capacity, rate_cap_rps),
                    duration_s=duration_s, arrival="mmpp", seed=97,
                )
                entry["mmpp_1x"] = run_open_loop(
                    target.url, generate_request_log(mmpp_cfg),
                    timeout_s=30.0, duration_s=duration_s,
                ).to_dict()
            admission = target.admission_state()
            if admission is not None:
                entry["admission"] = admission
            engines[engine] = entry
        finally:
            target.stop()

    def _point(engine, factor):
        for p in engines[engine]["sweep"]:
            if p["load_factor"] == factor:
                return p
        return None

    aio_1x, aio_2x = _point("aio", 1.0), _point("aio", 2.0)
    record = {
        "metric": "open_loop_goodput_retention",
        "unit": "goodput_2x/goodput_1x",
        "vs_baseline": None,
        "baseline_note": (
            "the reference (and configs 4/7) only ever measured "
            "closed-loop clients, which cannot overrun the server; "
            "there is no open-loop baseline number to compare against "
            "— the 2x-overload retention IS the new claim"
        ),
        "protocol": (
            "per engine: open-loop ramp capacity estimate (offered "
            "rate doubles per window until in-window goodput < 0.9x "
            "offered; capacity = saturated in-window goodput), then "
            "seeded Poisson arrival logs at "
            f"{'/'.join(str(f) + 'x' for f in load_factors)} of it for "
            f"{duration_s}s each (traffic.generator; latency measured "
            "from scheduled arrival; goodput counts in-window 200s "
            "only), plus one MMPP burst point at 1x for the aio "
            f"engine; coalescer on (window {window_ms} ms, max_rows "
            f"{max_rows}); aio runs its default admission control, "
            "thread is the admit-everything contrast; the "
            f"{probe_clients}-client closed-loop reference ties back "
            "to config 7"
        ),
        "byte_identity": identity,
        "engines": engines,
    }
    if aio_1x and aio_2x:
        retention = (
            aio_2x["goodput_in_window_rps"] / aio_1x["goodput_in_window_rps"]
            if aio_1x["goodput_in_window_rps"] else None
        )
        # `is not None`, not truthiness: a total 2x collapse is a REAL
        # 0.0, distinguishable from "no data"
        record["value"] = round(retention, 4) if retention is not None else None
        record["aio_2x_shed_fraction"] = aio_2x["shed_fraction"]
        record["aio_2x_p99_s"] = aio_2x["latency"]["p99_s"]
    return record


# -- config 11: compiled serving core ----------------------------------------

#: replica count for the fleet scale-out point: one worker per available
#: core up to 8 (each replica is a full serving process; oversubscribing
#: a small box measures the scheduler, not the fleet). The acceptance
#: target (>=10x the single-replica record) needs a correspondingly
#: multi-core box — the record carries cpu_count so a 1-2 core capture
#: reads as the protocol working, not the claim met.
COMPILED_REPLICA_WORKERS = max(2, min(8, os.cpu_count() or 2))
#: quantized dtypes config 11 sweeps against the f32 baseline — pinned
#: == serve.predictor.SERVE_DTYPES by tests/test_compiled.py
COMPILED_DTYPES = ("float32", "bfloat16", "int8")


def _device_dispatch_rate(predictor, n_features: int, bucket: int,
                          reps: int = 30) -> float:
    """Rows/s through one predictor's padded device call at ``bucket``
    (host->device + compute + device->host, HTTP-free) — the mechanism
    view of what quantization buys, uncontaminated by front-end cost."""
    import numpy as np

    X = np.zeros((bucket, n_features), dtype=np.float32)
    predictor.predict(X)  # ensure compiled + first-run costs paid
    t0 = time.perf_counter()
    for _ in range(reps):
        predictor.predict(X)
    return bucket * reps / (time.perf_counter() - t0)


def bench_compiled_serving(
    duration_s: float = 6.0,
    drive_rate_rps: float = 120.0,
    window_ms: float = 2.0,
    max_rows: int = 64,
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    isolate: bool = True,
    capacity_window_s: float = 3.0,
    dtypes: tuple = COMPILED_DTYPES,
    replica_point: bool = True,
    replica_workers: int | None = None,
    mlp_kwargs: dict | None = None,
) -> dict:
    """Config 11: the compiled serving core — AOT swap stalls, quantized
    capacity, and the N-replica fleet point.

    Three sub-records, one per tentpole axis:

    - **swap**: drive open-loop traffic against an in-process aio
      service while a hot swap to a same-architecture checkpoint lands
      mid-window (the real ``CheckpointWatcher.check_once`` path).
      With the process-wide executable cache the swap re-binds params
      to already-compiled executables: the record pins ZERO
      executable-cache misses across the whole drive (the request side
      never compiles) and reports p99 per answering checkpoint on both
      sides of the swap. The measured-stall BASELINE is captured
      directly: with the cache disabled (``BODYWORK_TPU_AOT_CACHE=0``)
      every bucket of the same architecture is re-lowered and re-timed —
      that compile wall time is exactly what the first post-swap request
      ate before AOT (and still eats with the cache off).
    - **dtypes**: per serving dtype (f32 baseline, bf16, int8 — each
      behind the shadow quality gate), the single-replica open-loop
      capacity (config 9's ramp protocol) AND the HTTP-free device
      dispatch rate at the largest sweep bucket. The device view is the
      mechanism (weight-byte reduction); the HTTP view is what a
      deployment actually gets, front-end costs included.
    - **replicas**: ``COMPILED_REPLICA_WORKERS`` SO_REUSEPORT aio
      replicas behind ONE shared admission budget
      (serve.multiproc/admission.SharedBudgetSlot) as a single
      benchmarkable unit: capacity ramp, a 2x-overload point (bounded
      p99 + sheds = the fleet degrades as one service), and the
      scale-out ratio vs the single-replica capacity measured through
      the SAME multiproc front (workers=1).

    CPU-safe: every mechanism here (compile elimination, weight-byte
    reduction, kernel-balanced replicas) exists on CPU; the record
    carries cpu_count and backend so small-box captures read correctly.
    """
    import numpy as np

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.models.checkpoint import load_model
    from bodywork_tpu.serve.predictor import (
        DEFAULT_BUCKETS,
        EXECUTABLE_CACHE,
        params_shape_digest,
    )
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.traffic import (
        TrafficConfig,
        generate_request_log,
        run_open_loop,
    )

    assert COMPILED_SWEEP_BUCKETS == tuple(DEFAULT_BUCKETS), (
        "bench sweep shapes drifted from serve.predictor.DEFAULT_BUCKETS"
    )
    buckets = COMPILED_SWEEP_BUCKETS
    mlp_kwargs = mlp_kwargs or {"hidden": [128, 128], "n_steps": 300}

    store_path = tempfile.mkdtemp(prefix="bench-compiled-")
    store = FilesystemStore(store_path)
    d1, d2 = date(2026, 1, 1), date(2026, 1, 2)
    X, y = generate_day(d1)
    persist_dataset(store, Dataset(X, y, d1))
    result_a = train_on_history(store, "mlp", model_kwargs=mlp_kwargs)
    key_a = result_a.model_artefact_key
    X2, y2 = generate_day(d2)
    persist_dataset(store, Dataset(X2, y2, d2))
    result_b = train_on_history(store, "mlp", model_kwargs=mlp_kwargs)
    key_b = result_b.model_artefact_key

    # -- measured stall baseline: what a cold (cache-off) swap compiles ------
    model_a, _ = load_model(store, key_a)
    prior_aot_env = os.environ.get("BODYWORK_TPU_AOT_CACHE")
    os.environ["BODYWORK_TPU_AOT_CACHE"] = "0"
    try:
        from bodywork_tpu.serve.predictor import PaddedPredictor

        cold = PaddedPredictor(model_a, buckets)
        n_features = model_a.n_features or 1
        stall = {}
        for b in buckets:
            t0 = time.perf_counter()
            cold._compiled_for(b, n_features)
            stall[str(b)] = round(time.perf_counter() - t0, 6)
    finally:
        # restore, don't delete: an operator-exported cache-off setting
        # must keep governing the rest of the run
        if prior_aot_env is None:
            os.environ.pop("BODYWORK_TPU_AOT_CACHE", None)
        else:
            os.environ["BODYWORK_TPU_AOT_CACHE"] = prior_aot_env
    baseline_stall_s = {
        "per_bucket_compile_s": stall,
        "total_compile_s": round(sum(stall.values()), 6),
        "note": (
            "wall time to lower+compile each serving bucket of this "
            "architecture with the executable cache disabled — the "
            "stall the first post-swap request pays when a swap lands "
            "uncompiled on the request path"
        ),
    }

    # -- swap drive: open-loop traffic across a live hot swap ----------------
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.serve.reload import CheckpointWatcher

    handle = serve_latest_model(
        store, host="127.0.0.1", port=0, block=False,
        buckets=buckets, batch_window_ms=window_ms,
        batch_max_rows=max_rows, server_engine="aio",
    )
    swap_result: dict = {}
    try:
        app = handle.app
        # boot serves key_b (newest); swap DOWN to key_a mid-drive via
        # the real watcher path so the drive crosses a genuine
        # load+warm+swap. Same architecture: the executable cache must
        # make it compile-free.
        assert app.model_key == key_b, app.model_key
        watcher = CheckpointWatcher(app, store, poll_interval_s=3600,
                                    served_key=key_b, buckets=buckets)
        digest_match = params_shape_digest(
            result_a.model.params
        ) == params_shape_digest(result_b.model.params)
        misses_before = EXECUTABLE_CACHE.stats()["misses"]

        def do_swap():
            time.sleep(duration_s / 2)
            model, model_date = load_model(store, key_a)
            predictor = watcher._build_swap_predictor(model)
            app.swap_model(model, model_date, predictor, model_key=key_a,
                           model_source="latest")

        import threading

        swapper = threading.Thread(target=do_swap)
        cfg = TrafficConfig(rate_rps=drive_rate_rps, duration_s=duration_s,
                            seed=111)
        url = handle.url
        swapper.start()
        report = run_open_loop(url, generate_request_log(cfg),
                               timeout_s=15.0, duration_s=duration_s)
        swapper.join()
        misses_during = EXECUTABLE_CACHE.stats()["misses"] - misses_before
        swap_result = {
            "same_architecture": digest_match,
            "executable_cache_misses_during_drive": misses_during,
            "request_side_compile_stalls": misses_during,  # 0 = claim holds
            "drive": report.to_dict(),
            "per_model_key": report.per_model_key,
            "baseline_stall": baseline_stall_s,
        }
        print(
            f"  swap: {misses_during} cache misses across the drive, "
            f"p99 {report.latency['p99_s']}s "
            f"(baseline stall {baseline_stall_s['total_compile_s']}s)",
            file=sys.stderr,
        )
    finally:
        handle.stop()

    # -- per-dtype single-replica capacity + device dispatch view ------------
    dtype_records: dict = {}
    for dtype in dtypes:
        from bodywork_tpu.serve.server import build_serving_predictor

        predictor, served_dtype = build_serving_predictor(
            store, result_b.model, None, "xla", buckets=buckets,
            dtype=dtype,
        )
        if predictor is None:
            from bodywork_tpu.serve.predictor import PaddedPredictor

            predictor = PaddedPredictor(result_b.model, buckets)
        predictor.warmup(sync=False)
        device_rate = _device_dispatch_rate(
            predictor, result_b.model.n_features or 1, buckets[-1]
        )
        target = _ServeTarget(store_path, "aio", window_ms, max_rows,
                              buckets, isolate, dtype=dtype)
        try:
            # confirm what actually serves (the gate may keep f32)
            import requests as rq

            health = rq.get(target.base_url + "/healthz", timeout=10).json()
            capacity, ramp = _open_loop_capacity(
                target.url, rate_cap_rps, window_s=capacity_window_s
            )
            over_cfg = TrafficConfig(
                rate_rps=min(2.0 * capacity, rate_cap_rps),
                duration_s=duration_s, seed=131,
            )
            overload = run_open_loop(
                target.url, generate_request_log(over_cfg),
                timeout_s=30.0, duration_s=duration_s,
            )
            dtype_records[dtype] = {
                "served_dtype": health.get("serving_dtype") or served_dtype,
                "device_dispatch_rows_per_s": round(device_rate, 1),
                "capacity_rps": capacity,
                "capacity_ramp": ramp,
                "overload_2x": overload.to_dict(),
            }
            print(
                f"  dtype {dtype}: serves {dtype_records[dtype]['served_dtype']}, "
                f"capacity {capacity:.0f} rps, device "
                f"{device_rate:,.0f} rows/s", file=sys.stderr,
            )
        finally:
            target.stop()

    f32_cap = dtype_records.get("float32", {}).get("capacity_rps")
    quant_caps = {
        dt: rec["capacity_rps"] for dt, rec in dtype_records.items()
        if dt != "float32" and rec.get("served_dtype") == dt
    }
    best_quant = max(quant_caps.values()) if quant_caps else None
    quant_ratio = (
        round(best_quant / f32_cap, 4) if best_quant and f32_cap else None
    )

    # -- fleet scale-out: N SO_REUSEPORT replicas, one admission budget ------
    replica_result: dict = {}
    if replica_point:
        from bodywork_tpu.serve import MultiProcessService

        workers = replica_workers or COMPILED_REPLICA_WORKERS

        def fleet_capacity(n: int) -> tuple[float, list, dict | None, object]:
            svc = MultiProcessService(
                store_path, workers=n, server_engine="aio",
                batch_window_ms=window_ms, batch_max_rows=max_rows,
                buckets=buckets, restart=True,
            ).start()
            try:
                warm_cfg = TrafficConfig(rate_rps=100.0, duration_s=1.0,
                                         seed=88)
                run_open_loop(svc.url, generate_request_log(warm_cfg),
                              timeout_s=15.0, duration_s=1.0)
                capacity, ramp = _open_loop_capacity(
                    svc.url, rate_cap_rps, window_s=capacity_window_s
                )
                over_cfg = TrafficConfig(
                    rate_rps=min(2.0 * capacity, rate_cap_rps),
                    duration_s=duration_s, seed=141,
                )
                overload = run_open_loop(
                    svc.url, generate_request_log(over_cfg),
                    timeout_s=30.0, duration_s=duration_s,
                ).to_dict()
                import requests as rq

                admission = rq.get(
                    svc.url.replace("/score/v1", "/healthz"), timeout=10
                ).json().get("admission")
            finally:
                svc.stop()
            return capacity, ramp, admission, overload

        cap_1, ramp_1, _adm1, over_1 = fleet_capacity(1)
        cap_n, ramp_n, adm_n, over_n = fleet_capacity(workers)
        replica_result = {
            "workers": workers,
            "cpu_count": os.cpu_count(),
            "single_replica_capacity_rps": cap_1,
            "fleet_capacity_rps": cap_n,
            "scaleout_ratio": round(cap_n / cap_1, 4) if cap_1 else None,
            "single_replica_ramp": ramp_1,
            "fleet_ramp": ramp_n,
            "fleet_overload_2x": over_n,
            "single_overload_2x": over_1,
            "fleet_admission": adm_n,
            "shared_admission_budget": True,
            "target_note": (
                ">=10x the single-replica record needs >=10 busy-capable "
                "cores; on a smaller box this point proves the protocol "
                "(shared budget, kernel-balanced listeners, bounded-p99 "
                "overload) and records the per-core scaling achieved"
            ),
        }
        print(
            f"  replicas: 1 -> {cap_1:.0f} rps, {workers} -> "
            f"{cap_n:.0f} rps (x{replica_result['scaleout_ratio']}, "
            f"{os.cpu_count()} cores)", file=sys.stderr,
        )

    return {
        "metric": "quantized_vs_f32_capacity",
        "unit": "capacity_ratio",
        "value": quant_ratio,
        "vs_baseline": None,
        "baseline_note": (
            "committed single-replica f32 record: 422 rps "
            "(BENCH_r06_config9.json, 2-core CPU box); this config's "
            "own f32 capacity on the current box is the in-record "
            "denominator — cross-box rps comparisons are not meaningful"
        ),
        "sweep_buckets": list(buckets),
        "swap": swap_result,
        "dtypes": dtype_records,
        "quantized_capacity_ratio": quant_ratio,
        "replicas": replica_result,
        "protocol": (
            "swap: open-loop drive (seed 111) across a live "
            "CheckpointWatcher swap to a same-architecture checkpoint; "
            "executable-cache miss delta over the whole drive must be 0 "
            "(request-side compiles eliminated); baseline stall = "
            "re-lowering every bucket with BODYWORK_TPU_AOT_CACHE=0. "
            "dtypes: per dtype (shadow-gated), config-9 ramp capacity + "
            "2x-overload point + HTTP-free device dispatch rows/s at "
            "the largest bucket. replicas: multiproc SO_REUSEPORT aio "
            "fleet behind ONE shared admission budget, workers=1 vs N, "
            "same ramp + 2x overload"
        ),
    }


#: the all-configs run list: every entry here must also carry a
# -- config 10: incremental training flatness --------------------------------

#: the committed-record protocol: >= 90 days (the SCALE_DEV horizon the
#: 1.21 full-refit baseline ratio was measured over), both model types.
#: 1440 rows/day is the reference generator's REAL day size
#: (DriftConfig.n_samples) — reference-parity workload, and large
#: enough that each day's CPU cost (~100 ms+) stands clear of the
#: kernel's 10 ms CPU-time accounting quantum and of the O(days)
#: listing/trainstate metadata (~1-2 ms at day 90)
INCREMENTAL_DAYS = 90
INCREMENTAL_ROWS_PER_DAY = 1440
#: measured full-refit MLP last-third/first-third ratio over 90 days
#: (SCALE_DEV_r05_cpu.json) — the baseline config 10 exists to beat
INCREMENTAL_BASELINE_RATIO = 1.21
#: MLP sized for a CPU-tractable 90-day x 2-mode sweep; the mechanism
#: under test is O(history)-vs-O(tail) row footprint, not model scale
INCREMENTAL_MLP_KWARGS = {"hidden": [32, 32], "n_steps": 400}


def _flatness(series: list, warmup_days: int = 1) -> dict:
    """Last-third/first-third mean ratio + horizon slope over the STEADY
    days. ``warmup_days`` are excluded from the front: day 1 pays the
    trainstate/donor bootstrap (config 2/3's ``_steady_days``
    convention), and config 10 additionally excludes the tail-window
    RAMP (days 2..TAIL_DAYS, whose replay/eval windows are still
    growing toward the tail width — genuinely cheaper days that would
    inflate the ratio of a series that is flat from the moment the
    window fills)."""
    steady = series[warmup_days:] if len(series) > warmup_days else list(series)
    n = len(steady)
    third = max(n // 3, 1)

    def trimmed_mean(xs):
        # 10% symmetric trim: an environment stall long enough to span
        # every min-of-N attempt of one day (two ~0.3 s disk stalls on
        # a ~0.05 s fit were measured doing exactly this) must not
        # decide a third's mean — the ratio compares typical days
        xs = sorted(xs)
        k = len(xs) // 10 if len(xs) >= 5 else 0
        return sum(xs[k:len(xs) - k] if k else xs) / (len(xs) - 2 * k)

    first = trimmed_mean(steady[:third])
    last = trimmed_mean(steady[-third:])
    mean_y = sum(steady) / n
    xs = range(n)
    mean_x = sum(xs) / n
    var_x = sum((x - mean_x) ** 2 for x in xs)
    slope = (
        sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, steady)) / var_x
        if var_x else 0.0
    )
    return {
        "last_third_over_first_third": round(last / first, 4) if first else None,
        "steady_mean_s": round(mean_y, 5),
        "slope_s_per_day": round(slope, 7),
    }


#: per-day repeat count for config 10: each day's train is measured
#: min-of-N (the standard noise-robust estimator) — single-shot series
#: on a shared box flipped their apparent slope sign between captures
INCREMENTAL_ATTEMPTS = 3


def _date_cutoff_store(root: str, day):
    """A COLD store handle whose date-keyed listings are truncated to
    reconstruct the store as day ``day``'s train saw it: ``datasets/``
    up to and including ``day`` (the rows that train consumes),
    everything else — checkpoints, metrics, registry records — up to
    the day BEFORE (the MLP warm start's donor must resolve to
    yesterday's checkpoint, exactly as it did live). Access by explicit
    key passes through; only listing-driven discovery is cut."""
    import datetime as _dt

    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.base import DelegatingStore
    from bodywork_tpu.store.schema import DATASETS_PREFIX
    from bodywork_tpu.utils.dates import date_from_key

    class _CutoffStore(DelegatingStore):
        def list_keys(self, prefix: str = "") -> list[str]:
            out = []
            for key in self.inner.list_keys(prefix):
                d = date_from_key(key)
                if d is None:
                    out.append(key)
                    continue
                limit = day if key.startswith(DATASETS_PREFIX) else (
                    day - _dt.timedelta(days=1)
                )
                if d <= limit:
                    out.append(key)
            return out

    return _CutoffStore(FilesystemStore(root))


def _measure_train_day(root: str, day, pre_trainstate, model_type: str,
                       mode: str, model_kwargs, rows_per_day: int,
                       attempts: int = INCREMENTAL_ATTEMPTS) -> tuple:
    """Measure ONE day's train cost against its reconstructed store
    state, robustly: ``attempts`` repeats, each from a COLD cutoff
    handle (fresh caches — the per-day-pod regime, config 8's
    convention), min over attempts on wall seconds. Repeats are made
    honest by restoring the PRE-day trainstate document
    (``pre_trainstate`` bytes, None = absent) before every attempt, so
    each attempt performs the same fold; the raw ``put_bytes`` reset is
    harness-level state surgery — product code only ever CAS-writes the
    key. All other writes re-put byte-identical artefacts (training is
    deterministic)."""
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.base import ArtefactNotFound
    from bodywork_tpu.store.schema import trainstate_key
    from bodywork_tpu.train import train_on_history

    ts_key = trainstate_key(model_type)
    admin = FilesystemStore(root)
    best = None
    result = None
    for _attempt in range(max(attempts, 1)):
        if pre_trainstate is None:
            try:
                admin.delete(ts_key)
            except ArtefactNotFound:
                pass
        else:
            admin.put_bytes(ts_key, pre_trainstate)
        view = _date_cutoff_store(root, day)
        t0 = time.perf_counter()
        c0 = time.process_time()
        result = train_on_history(
            view, model_type, model_kwargs=model_kwargs, mode=mode,
            rows_per_day=rows_per_day,
        )
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        if best is None or wall < best["s"]:
            best = {"s": round(wall, 5), "cpu_s": round(cpu, 5)}
    return best, result


def _incremental_day_series(model_type: str, mode: str, days: int,
                            rows_per_day: int, model_kwargs) -> tuple:
    """One (model, mode) run, in two passes. Returns
    ``(store_root, per_day list, final-day TrainResult)``.

    **Build pass (untimed):** generate every dataset, prewarm the FULL
    mode's bucket-crossing XLA compiles (the pipeline runner's
    ``_prewarm_horizon`` behaviour — the series must measure data-plane
    growth, not compile placement), run a (tail+1)-day scratch warmup
    (covers the incremental paths' window-ramp compiles and the fresh
    process' first-execution slowness, measured 0.12 s -> 0.02 s for
    the same compiled fit), then run the whole horizon sequentially,
    capturing each day's PRE-fold trainstate bytes.

    **Measurement pass:** re-measure every day's train against its
    reconstructed store state (date-cutoff view + trainstate restore —
    :func:`_measure_train_day`) in SEEDED-SHUFFLED day order. Shuffling
    is what makes the flatness ratio trustworthy on a shared box:
    machine-speed drift over the capture's minutes lands uniformly
    across history lengths instead of systematically inflating (or
    deflating) the last third — sequential single-shot captures
    measured ratios from 0.5 to 1.75 for the SAME workload."""
    import random as _random
    from datetime import timedelta

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.data.drift_config import DriftConfig
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.base import ArtefactNotFound
    from bodywork_tpu.store.schema import trainstate_key
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.train.incremental import TAIL_DAYS

    root = tempfile.mkdtemp(prefix=f"bench-inc-{model_type}-{mode}-")
    store = FilesystemStore(root)
    drift = DriftConfig(n_samples=rows_per_day)
    datasets = []
    for i in range(days):
        d = date(2026, 1, 1) + timedelta(days=i)
        X, y = generate_day(d, drift)
        datasets.append(Dataset(X, y, d))
    if mode == "full":
        from bodywork_tpu.train.prewarm import prewarm_async, wait_idle

        cum = 0
        for ds in datasets:
            cum += len(ds)
            prewarm_async(model_type, model_kwargs, cum,
                          n_features=ds.X.shape[1])
        wait_idle()
    scratch = FilesystemStore(tempfile.mkdtemp(prefix="bench-inc-warm-"))
    for ds in datasets[:TAIL_DAYS + 1]:
        persist_dataset(scratch, ds)
        train_on_history(scratch, model_type, model_kwargs=model_kwargs,
                         mode=mode, rows_per_day=rows_per_day)
    # build pass: sequential, untimed; capture pre-fold trainstate bytes
    ts_key = trainstate_key(model_type)
    pre_state: list = []
    for ds in datasets:
        try:
            pre_state.append(store.get_bytes(ts_key))
        except ArtefactNotFound:
            pre_state.append(None)
        persist_dataset(store, ds)
        train_on_history(store, model_type, model_kwargs=model_kwargs,
                         mode=mode, rows_per_day=rows_per_day)
    # measurement pass: shuffled day order
    order = list(range(days))
    _random.Random(93).shuffle(order)
    per_day: list = [None] * days
    final_result = None
    for i in order:
        measured, result = _measure_train_day(
            root, datasets[i].date, pre_state[i], model_type, mode,
            model_kwargs, rows_per_day,
        )
        per_day[i] = {
            **measured,
            "rows_touched": result.rows_touched,
            **({"fallback": result.fallback_reason}
               if result.fallback_reason else {}),
        }
        if i == days - 1:
            final_result = result
    print(
        f"  {model_type}/{mode}: {days}d, day1 {per_day[0]['s']:.3f}s -> "
        f"day{days} {per_day[-1]['s']:.3f}s, rows "
        f"{per_day[0]['rows_touched']} -> {per_day[-1]['rows_touched']}",
        file=sys.stderr,
    )
    return root, per_day, final_result


def _linear_coefficient_check(root: str, result, atol: float = 1e-4) -> dict:
    """The exactness proof: the incremental solution vs an INDEPENDENT
    float64 least-squares refit on the union of the same per-day train
    splits (the statistics' defining identity), and vs the float32
    device fit on those rows (the executable full refit)."""
    import numpy as np

    from bodywork_tpu.data.io import load_dataset
    from bodywork_tpu.models import LinearRegressor
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.schema import DATASETS_PREFIX
    from bodywork_tpu.train.incremental import day_split_indices

    store = FilesystemStore(root)
    Xs, ys = [], []
    for key, d in store.history(DATASETS_PREFIX):
        ds = load_dataset(store, key)
        train_idx, _ = day_split_indices(len(ds), d, 0.2, 42)
        Xs.append(ds.X[train_idx])
        ys.append(ds.y[train_idx])
    X = np.concatenate(Xs).astype(np.float64)
    y = np.concatenate(ys).astype(np.float64)
    A = np.concatenate([X, np.ones((len(y), 1))], axis=1)
    theta64, *_ = np.linalg.lstsq(A, y, rcond=None)
    inc = result.model.host_params()
    inc_theta = np.concatenate(
        [np.asarray(inc["w"]).ravel(), [float(inc["b"])]]
    )
    fit32 = LinearRegressor().fit(X.astype(np.float32), y.astype(np.float32))
    h32 = fit32.host_params()
    theta32 = np.concatenate(
        [np.asarray(h32["w"]).ravel(), [float(np.asarray(h32["b"]))]]
    )
    diff64 = float(np.max(np.abs(inc_theta - theta64)))
    diff32 = float(np.max(np.abs(inc_theta - theta32)))
    return {
        "coefficients": [round(float(v), 8) for v in inc_theta],
        "max_abs_diff_vs_float64_refit": diff64,
        "max_abs_diff_vs_float32_device_refit": diff32,
        "atol": atol,
        "within_atol": diff64 <= atol,
        "rows": int(len(y)),
    }


def _mlp_shadow_gate_check(root: str, result, model_kwargs,
                           rows_per_day: int) -> dict:
    """The quality proof: the final incremental candidate's shadow-window
    MAPE vs a same-store full refit's, against the gate's promotion
    ceiling (``GatePolicy.shadow_max_mape_ratio`` + slack) — the bound
    the runner's shadow-armed gate enforces every incremental day."""
    import numpy as np

    from bodywork_tpu.data.io import load_dataset
    from bodywork_tpu.registry.gates import GatePolicy
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.schema import DATASETS_PREFIX
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.train.incremental import INCREMENTAL_SHADOW_DAYS

    store = FilesystemStore(root)
    full = train_on_history(
        store, "mlp", model_kwargs=model_kwargs, mode="full", persist=False,
        rows_per_day=rows_per_day,
    )
    window = store.history(DATASETS_PREFIX)[-INCREMENTAL_SHADOW_DAYS:]
    eps = 2.220446049250313e-16
    mapes = {}
    for name, model in (("candidate", result.model), ("full_refit", full.model)):
        errs, denoms = [], []
        for key, _d in window:
            ds = load_dataset(store, key)
            pred = np.asarray(model.predict_padded(ds.X), dtype=np.float64)
            errs.append(np.abs(pred - ds.y))
            denoms.append(np.maximum(np.abs(ds.y), eps))
        mapes[name] = float(
            np.mean(np.concatenate(errs) / np.concatenate(denoms))
        )
    policy = GatePolicy()
    ceiling = mapes["full_refit"] * policy.shadow_max_mape_ratio + policy.mape_slack
    return {
        "shadow_days": INCREMENTAL_SHADOW_DAYS,
        "candidate_mape": round(mapes["candidate"], 6),
        "full_refit_mape": round(mapes["full_refit"], 6),
        "gate_ceiling": round(ceiling, 6),
        "within_gate": mapes["candidate"] <= ceiling,
    }


def bench_incremental_train(
    days: int = INCREMENTAL_DAYS,
    rows_per_day: int = INCREMENTAL_ROWS_PER_DAY,
    model_types=("linear", "mlp"),
) -> dict:
    """Config 10: per-day TRAIN cost vs history length, full refit vs
    incremental (docs/PERF.md has the full protocol).

    For each (model, mode): a fresh store runs ``days`` simulated days
    of generate-then-train with ONLY the train call timed — the exact
    compute config 10 exists to flatten (SCALE_DEV_r05_cpu.json showed
    the pipeline's residual growth is all here). The incremental runs
    also commit the two safety proofs: linear coefficients vs an
    independent float64 full refit on the same per-day splits (exactness)
    and the MLP candidate's shadow-window MAPE vs the gate's promotion
    ceiling (bounded approximation). CPU-safe end to end: the mechanism
    is rows-not-touched, not device speed."""
    models: dict = {}
    for model_type in model_types:
        kwargs = INCREMENTAL_MLP_KWARGS if model_type == "mlp" else None
        entry: dict = {}
        roots: dict = {}
        for mode in ("full", "incremental"):
            root, per_day, result = _incremental_day_series(
                model_type, mode, days, rows_per_day, kwargs
            )
            roots[mode] = (root, result)
            fallbacks: dict = {}
            for p in per_day:
                if "fallback" in p:
                    fallbacks[p["fallback"]] = fallbacks.get(p["fallback"], 0) + 1
            from bodywork_tpu.train.incremental import TAIL_DAYS

            # steady state starts once the tail window has filled: days
            # 1..TAIL_DAYS pay bootstrap + a growing window (see
            # _flatness); applied to BOTH modes for comparability
            warmup = min(TAIL_DAYS, max(len(per_day) - 3, 1))
            entry[mode] = {
                # flatness basis: min-of-N wall seconds measured in
                # SHUFFLED day order (drift-decorrelated — see
                # _incremental_day_series); per-day CPU seconds ride
                # alongside but the kernel accounts them in 10 ms
                # jiffies, too coarse to headline a ~50 ms fit
                "flatness": _flatness(
                    [p["s"] for p in per_day], warmup_days=warmup
                ),
                "cpu_flatness": _flatness(
                    [p["cpu_s"] for p in per_day], warmup_days=warmup
                ),
                "steady_from_day": warmup + 1,
                "rows_touched_final_day": per_day[-1]["rows_touched"],
                "fallbacks": fallbacks,
                "per_day": per_day,
            }
        root_inc, result_inc = roots["incremental"]
        if model_type == "linear":
            entry["coefficient_check"] = _linear_coefficient_check(
                root_inc, result_inc
            )
        else:
            entry["shadow_gate"] = _mlp_shadow_gate_check(
                root_inc, result_inc, kwargs, rows_per_day
            )
        models[model_type] = entry
    headline_model = "mlp" if "mlp" in models else next(iter(models))
    inc_flat = models[headline_model]["incremental"]["flatness"]
    return {
        "metric": "incremental_train_flatness",
        # headline: the incremental mode's last-third/first-third per-day
        # train-wall ratio at the largest model — 1.0 is perfectly flat,
        # the measured full-refit baseline is 1.21
        "value": inc_flat["last_third_over_first_third"],
        "unit": "last-third/first-third train wall ratio",
        "vs_baseline": INCREMENTAL_BASELINE_RATIO,
        "baseline_note": (
            "baseline is the measured full-refit MLP ratio over the same "
            "90-day horizon (SCALE_DEV_r05_cpu.json "
            "last_third_over_first_third=1.21, a WARM in-process loop); "
            "this record's own 'full' series re-measures the full refit "
            "under THIS protocol's cold-handle per-day-pod regime, where "
            "the O(history) reload makes the growth steeper — compare "
            "incremental against the in-record full series first"
        ),
        "days": days,
        "rows_per_day": rows_per_day,
        "headline_model": headline_model,
        "models": models,
        "protocol": (
            "fresh store per (model, mode); two passes: an UNTIMED "
            "sequential build (datasets pre-generated; full-mode "
            "bucket-crossing XLA compiles prewarmed; (tail+1)-day "
            "scratch warmup covers the incremental window-ramp compiles "
            "and fresh-process slowness; per-day pre-fold trainstate "
            "captured), then a measurement pass re-running every day's "
            "train against its reconstructed store state (date-cutoff "
            "listing view + trainstate restore) in SEEDED-SHUFFLED day "
            "order so machine-speed drift cannot masquerade as growth; "
            f"min-of-{INCREMENTAL_ATTEMPTS} wall seconds per day, each "
            "attempt from a COLD handle (per-day-pod regime); per-day "
            "CPU seconds recorded alongside (cpu_flatness; 10 ms kernel "
            "accounting quantum); steady days exclude day 1 (trainstate/"
            "donor bootstrap); incremental proofs: linear coefficients "
            "vs independent float64 lstsq on the union of per-day train "
            "splits, mlp shadow-window MAPE vs the gate ceiling "
            "(registry.gates.GatePolicy)"
        ),
    }


# -- config 12: sharded serving scaling --------------------------------------

#: the mesh sizes config 12 sweeps: single-device baseline, then the
#: data axis doubling up to a v5e-8's worth of devices. On CPU these are
#: VIRTUAL devices (xla_force_host_platform_device_count) sharing the
#: host's cores — see the in-record caveat.
SHARDED_MESH_SIZES = (1, 2, 4, 8)


def _sharded_backend_is_cpu() -> bool:
    """Whether the config-12 sweep runs on (virtual) CPU devices. On a
    real accelerator the sweep subprocesses must inherit the accelerator
    backend — forcing CPU there would silently benchmark virtual
    devices while the record claimed a hardware capture."""
    import jax

    return jax.devices()[0].platform == "cpu"


def _mesh_env(n_devices: int) -> dict:
    """Subprocess env for one sweep point. CPU backend: force exactly
    ``n_devices`` virtual devices (the standard JAX stand-in for an
    n-chip slice; tests/conftest.py uses the same flag — any inherited
    device-count flag is replaced, not doubled up). Real accelerator:
    inherit the environment untouched — the server's ``--mesh-data N``
    then takes the first N REAL devices, which is the capture the
    scaling-slope claim needs."""
    env = dict(os.environ)
    if not _sharded_backend_is_cpu():
        return env
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags).strip()
    return env


def _sharded_dispatch_probe(store_path: str, mesh_data: int,
                            bucket: int = 4096, reps: int = 20) -> dict:
    """Device-dispatch rows/s through the serving predictor at one mesh
    size (HTTP-free — the mechanism view of what the mesh buys, like
    config 11's per-dtype dispatch rate). Must run inside a process
    whose device count matches ``mesh_data`` — the sweep calls it in
    the per-mesh subprocess; the in-process smoke calls it directly on
    the test mesh."""
    import numpy as np

    from bodywork_tpu.models.checkpoint import load_model, resolve_serving_key
    from bodywork_tpu.serve.server import build_serving_predictor
    from bodywork_tpu.store import FilesystemStore

    store = FilesystemStore(store_path)
    key, _source = resolve_serving_key(store)
    model, _d = load_model(store, key)
    predictor, _dtype = build_serving_predictor(
        store, model, mesh_data if mesh_data > 1 else None, "xla",
        buckets=(bucket,), dtype="float32",
    )
    if predictor is None:
        from bodywork_tpu.serve.predictor import PaddedPredictor

        predictor = PaddedPredictor(model, (bucket,))
    predictor.warmup(sync=False)
    X = np.zeros((bucket, model.n_features or 1), dtype=np.float32)
    predictor.predict(X)  # compiled + first-run costs paid
    t0 = time.perf_counter()
    for _ in range(reps):
        predictor.predict(X)
    rate = bucket * reps / (time.perf_counter() - t0)
    return {
        "bucket": bucket,
        "device_dispatch_rows_per_s": round(rate, 1),
    }


def _dispatch_probe_isolated(store_path: str, mesh_data: int,
                             bucket: int, reps: int) -> dict:
    """Run :func:`_sharded_dispatch_probe` in a subprocess with exactly
    ``mesh_data`` virtual devices (the driver's own device count is
    fixed at import; the probe's mesh must match the sweep point's)."""
    code = (
        "import json, sys; from bench import _sharded_dispatch_probe; "
        f"print(json.dumps(_sharded_dispatch_probe({store_path!r}, "
        f"{mesh_data}, {bucket}, {reps})))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=_mesh_env(mesh_data), capture_output=True, timeout=300,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"dispatch probe (mesh {mesh_data}) failed: "
            f"{proc.stderr.decode(errors='replace')[-800:]}"
        )
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


def bench_sharded_scaling(
    mesh_sizes: tuple = SHARDED_MESH_SIZES,
    window_ms: float = 2.0,
    max_rows: int = 64,
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    isolate: bool = True,
    capacity_window_s: float = 3.0,
    dispatch_bucket: int = 4096,
    dispatch_reps: int = 20,
    mlp_kwargs: dict | None = None,
) -> dict:
    """Config 12: sharded serving scaling efficiency vs device count.

    The first capacity record whose serving hot path dispatches through
    a device mesh (every earlier config is single-device): per mesh
    size in ``mesh_sizes``, a subprocess-isolated server with exactly
    that many (virtual) devices serves ``--mesh-data N`` through the
    AOT-cached :class:`~bodywork_tpu.parallel.ShardedMLPPredictor`, and
    the record reports

    - **device_dispatch_rows_per_s**: the padded device call at
      ``dispatch_bucket`` rows, HTTP-free (the mechanism view — rows
      split over the ``data`` axis, params resident per device);
    - **capacity_rps**: config 9's open-loop ramp against the live
      server (the deployment view, front-end costs included);
    - **scaling efficiency** per mesh size, computed in-record:
      ``rate(N) / (N * rate(1))`` for both views — the number a TPU
      capture of this config turns into the scale-out claim.

    The /healthz ``mesh`` block of every sweep point is captured in the
    record: each point PROVES it really served sharded (or really
    single-device, for the baseline) rather than silently falling back.

    CPU CAVEAT (in-record): virtual devices multiplex the same host
    cores, so CPU efficiency NEVER approaches 1 and may fall below
    1/N — the sweep on CPU proves the end-to-end sharded-dispatch
    protocol (mesh placement, per-mesh executables, byte-identical
    responses, capacity harness against a sharded replica); the
    efficiency-vs-device-count slope is a TPU claim.
    """
    import numpy as np  # noqa: F401  (parity with sibling benches)

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    mlp_kwargs = mlp_kwargs or {"hidden": [64, 64], "n_steps": 300}
    store_path = tempfile.mkdtemp(prefix="bench-sharded-")
    store = FilesystemStore(store_path)
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "mlp", model_kwargs=mlp_kwargs)
    buckets = tuple(sorted({1, 16, max_rows}))

    import requests as rq

    points: dict = {}
    for n in mesh_sizes:
        mesh_data = n if n > 1 else None
        target = _ServeTarget(
            store_path, "aio", window_ms, max_rows, buckets, isolate,
            mesh_data=mesh_data, env=_mesh_env(n) if isolate else None,
        )
        try:
            health = rq.get(target.base_url + "/healthz", timeout=10).json()
            capacity, ramp = _open_loop_capacity(
                target.url, rate_cap_rps, window_s=capacity_window_s
            )
        finally:
            target.stop()
        if isolate:
            probe = _dispatch_probe_isolated(
                store_path, n, dispatch_bucket, dispatch_reps
            )
        else:
            probe = _sharded_dispatch_probe(
                store_path, n, dispatch_bucket, dispatch_reps
            )
        # did the ramp actually find a peak, or run out of offered rate?
        # (a fast front end can outrun the driver's rate cap — capacity
        # is then a LOWER BOUND, which the efficiency math must not
        # silently treat as the peak)
        last = ramp[-1] if ramp else None
        truncated = bool(
            last
            and last["goodput_in_window_rps"] >= 0.9 * last["offered_rps"]
            and last["shed_fraction"] == 0.0
            and 2.0 * last["offered_rps"] > rate_cap_rps
        )
        points[str(n)] = {
            "mesh_data": n,
            # the server's own testimony that this point served sharded
            # (None = single-device baseline)
            "healthz_mesh": health.get("mesh"),
            "capacity_rps": capacity,
            "capacity_is_lower_bound": truncated,
            "capacity_ramp": ramp,
            **probe,
        }
        print(
            f"  mesh {n}: healthz mesh={health.get('mesh')}, capacity "
            f"{capacity:.0f} rps, device "
            f"{probe['device_dispatch_rows_per_s']:,.0f} rows/s",
            file=sys.stderr,
        )

    base_n = mesh_sizes[0]
    base = points.get(str(base_n), {})
    base_cap = base.get("capacity_rps") or None
    base_disp = base.get("device_dispatch_rows_per_s") or None
    for n in mesh_sizes:
        p = points[str(n)]
        # normalised per DEVICE relative to the sweep's own baseline
        # point (rate(N) / ((N/base_n) * rate(base_n))): the baseline
        # reads exactly 1.0 even when a sweep starts above mesh size 1
        p["capacity_scaling_efficiency"] = (
            round(p["capacity_rps"] / ((n / base_n) * base_cap), 4)
            if base_cap else None
        )
        p["dispatch_scaling_efficiency"] = (
            round(
                p["device_dispatch_rows_per_s"] / ((n / base_n) * base_disp),
                4,
            )
            if base_disp else None
        )

    top = points[str(mesh_sizes[-1])]
    capacity_note = None
    if all(p["capacity_is_lower_bound"] for p in points.values()):
        capacity_note = (
            "every mesh size's ramp ran out of offered rate before "
            "saturating (zero sheds at the harness rate cap): the aio "
            "front end, not the device plane, is the bottleneck on this "
            "box (config 11 measured the same per-replica ceiling), so "
            "capacity_rps is a LOWER BOUND at every size and the "
            "capacity-view efficiency degenerates to 1/N — the "
            "device-dispatch view is the discriminating signal here"
        )
    return {
        "metric": "sharded_scaling_efficiency",
        "cpu_count": os.cpu_count(),
        # True = xla_force_host_platform_device_count stand-ins (the
        # cpu_caveat applies); False = the sweep ran on real accelerator
        # devices and the efficiency slope is a hardware claim
        "virtual_devices": _sharded_backend_is_cpu(),
        "capacity_note": capacity_note,
        "unit": f"capacity_N/(N*capacity_1) at N={mesh_sizes[-1]}",
        "value": top["capacity_scaling_efficiency"],
        "vs_baseline": None,
        "baseline_note": (
            "config 9/11 capacity records are single-device serving; "
            "the per-mesh baseline here is this run's own mesh-1 point "
            "(same box, same harness) — cross-box rps comparisons are "
            "not meaningful"
        ),
        "mesh_sizes": list(mesh_sizes),
        "points": points,
        "cpu_caveat": (
            "virtual CPU devices (xla_force_host_platform_device_count) "
            "share the host's physical cores: an N-device mesh adds "
            "sharding overhead without adding compute, so CPU "
            "efficiency is expected well below 1 and can fall below "
            "1/N. This record proves the sharded serving protocol end "
            "to end (mesh placement, per-mesh AOT executables, config-9 "
            "capacity harness against a sharded replica); the "
            "efficiency slope itself is a TPU claim"
        ),
        "protocol": (
            "one day's dataset, one MLP checkpoint "
            f"({mlp_kwargs}); per mesh size N in {list(mesh_sizes)}: a "
            "subprocess-isolated aio server with exactly N virtual "
            "devices serving --mesh-data N (ShardedMLPPredictor: params "
            "NamedSharding-placed, rows split on the data axis, "
            "programs AOT-cached per mesh), /healthz mesh block "
            "captured as proof of sharded dispatch; config-9 open-loop "
            "ramp capacity + HTTP-free device dispatch rows/s at "
            f"bucket {dispatch_bucket} (subprocess with matching device "
            "count); scaling efficiency rate(N)/(N*rate(1)) computed "
            "in-record for both views"
        ),
    }


# -- config 13: self-tuning runtime ------------------------------------------

#: profile -> the knob whose mechanism it exercises (the knob the
#: profile's win is CREDITED to; acceptance needs >=2 distinct knobs
#: beating their hand-set defaults across >=2 profiles).
#: `max_pending` keeps its Little's-law model + decision trace but is
#: deliberately NOT a credited knob here: on this box's aio engine the
#: overload tail is dominated by pre-admission event-loop/accept
#: backlog the budget cannot see (the config-9 front-end ceiling), so
#: budget changes move the goodput/shed balance, not p99 — measured
#: (budgets 512/150/64 at 2000 rps: p99 0.76/1.29/0.30 s,
#: non-monotonic = stall-noise-bound) and documented in-record. The
#: bursty profile's `batch_max_rows` credit is the same box-limited
#: story: a squall's backlog drains at the FRONT END's per-request
#: rate, so flush-size gains are masked here — the committed capture
#: shows the profile beating defaults on p50/p99 via its OTHER fitted
#: knobs while the knee honestly matched the default flush size
#: (uncredited); the mechanism's regime is a dispatch-bound box
#: (TPU/multi-core), where the credited knob earns its place.
SELF_TUNING_PROFILES = {
    "uniform_row": "batch_window_ms",
    "heavy_tail_row": "buckets",
    "bursty_mmpp": "batch_max_rows",
}


def _merge_request_logs(*logs):
    """Deterministically interleave request logs by scheduled arrival
    (stable sort: composition of seeded logs stays seeded)."""
    merged = [r for log in logs for r in log]
    merged.sort(key=lambda r: r.t_s)
    return merged


def _profile_request_log(profile: str, rate_rps: float, duration_s: float,
                         heavy_batch_rows: int = 700):
    """The seeded request log for one config-13 traffic profile.

    - ``uniform_row``: Poisson single-row arrivals — the regime where
      the default 2 ms coalescer window is pure latency tax.
    - ``heavy_tail_row``: 75% single-row + 25% ``heavy_batch_rows``-row
      batch requests (two seeded logs merged by arrival time) — a
      row-shape distribution whose tail the default bucket ladder pads
      to 4096.
    - ``bursty_mmpp``: MMPP squalls at the same mean rate — the
      admission-budget regime (drive it above capacity).
    """
    from bodywork_tpu.traffic import TrafficConfig, generate_request_log

    if profile == "uniform_row":
        return generate_request_log(TrafficConfig(
            rate_rps=rate_rps, duration_s=duration_s, seed=131,
        ))
    if profile == "heavy_tail_row":
        singles = generate_request_log(TrafficConfig(
            rate_rps=rate_rps * 0.75, duration_s=duration_s, seed=132,
        ))
        batches = generate_request_log(TrafficConfig(
            rate_rps=rate_rps * 0.25, duration_s=duration_s, seed=133,
            batch_fraction=1.0, batch_rows=heavy_batch_rows,
        ))
        return _merge_request_logs(singles, batches)
    if profile == "bursty_mmpp":
        return generate_request_log(TrafficConfig(
            rate_rps=rate_rps, duration_s=duration_s, arrival="mmpp",
            seed=134,
        ))
    raise ValueError(f"unknown profile {profile!r}")


def _tuned_beats_default(default_rep: dict, tuned_rep: dict) -> tuple[bool, dict]:
    """Did the tuned config beat the hand-set defaults on in-window
    goodput OR p99 (without materially regressing the other)?"""
    d_p99 = (default_rep.get("latency") or {}).get("p99_s")
    t_p99 = (tuned_rep.get("latency") or {}).get("p99_s")
    d_good = default_rep.get("goodput_in_window_rps") or 0.0
    t_good = tuned_rep.get("goodput_in_window_rps") or 0.0
    p99_improved = (
        d_p99 is not None and t_p99 is not None
        and t_p99 <= 0.95 * d_p99
        and t_good >= 0.95 * d_good
    )
    goodput_improved = (
        t_good >= 1.05 * d_good
        and (d_p99 is None or t_p99 is None or t_p99 <= 1.2 * d_p99)
    )
    return p99_improved or goodput_improved, {
        "default_p99_s": d_p99, "tuned_p99_s": t_p99,
        "default_goodput_in_window_rps": d_good,
        "tuned_goodput_in_window_rps": t_good,
        "p99_improved": p99_improved,
        "goodput_improved": goodput_improved,
    }


def bench_self_tuning(
    drive_s: float = 8.0,
    uniform_rate_rps: float = 150.0,
    heavy_rate_rps: float = 40.0,
    heavy_batch_rows: int = 700,
    burst_load_factor: float = 0.9,
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    capacity_window_s: float = 3.0,
    isolate: bool = True,
    probe_reps: int = 5,
    mlp_kwargs: dict | None = None,
    profiles_run: tuple = tuple(SELF_TUNING_PROFILES),
    probe_buckets: tuple = (1, 8, 64, 256, 512, 1024, 4096),
) -> dict:
    """Config 13: the self-tuning runtime (``bodywork_tpu/tune``,
    ROADMAP item 5) — ``cli tune`` on a profile's own traces must beat
    the hand-set serving defaults when the SAME seeded traffic is
    re-driven under the tuned config.

    Per seeded profile (uniform-row / heavy-tail-row / bursty MMPP):

    1. drive the profile's request log against a DEFAULT-knob aio
       server (window 2 ms, max_rows 64, the 5-rung default bucket
       ladder, admission 512), request + results logs written;
    2. tune exactly as ``cli tune`` would: ingest both logs, probe the
       serving checkpoint's per-bucket dispatch-cost curve, fit the
       cost model, persist the tuned document under ``tuning/``;
    3. re-drive the IDENTICAL log against a server booted with ONLY
       ``--tuned-config <key>`` (no explicit knob flags — the document
       supplies every value; /healthz ``effective_config.tuned_config``
       is captured as proof of consumption);
    4. compare in-window goodput and p99. A profile's win is credited
       to the knob whose mechanism it exercises
       (:data:`SELF_TUNING_PROFILES`); acceptance = >=2 distinct knobs
       beating their defaults across >=2 profiles, decision traces
       in-record.

    A sabotage block additionally boots a server against a garbage
    tuned document and records that it serves with the built-in
    defaults (effective_config.tuned_config null) — the
    malformed-degrades contract, measured not assumed.

    CPU-safe: every mechanism (window latency tax, padding waste,
    burst-backlog drain) exists wherever the dispatch cost is nonzero;
    the record carries cpu_count and backend.
    """
    from datetime import timedelta

    import requests as rq

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve.admission import DEFAULT_MAX_PENDING
    from bodywork_tpu.serve.batcher import DEFAULT_MAX_ROWS, DEFAULT_WINDOW_MS
    from bodywork_tpu.serve.predictor import DEFAULT_BUCKETS
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.traffic import run_open_loop, write_request_log
    from bodywork_tpu.traffic.generator import TrafficConfig
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.tune.collect import (
        ObservationTable,
        ingest_request_log,
        ingest_results_log,
        probe_dispatch_costs,
    )
    from bodywork_tpu.tune.config import write_tuned_config
    from bodywork_tpu.tune.model import fit_tuned_config

    store_path = tempfile.mkdtemp(prefix="bench-selftune-")
    store = FilesystemStore(store_path)
    d0 = date(2026, 1, 1)
    X, y = generate_day(d0)
    persist_dataset(store, Dataset(X, y, d0))
    # an MLP checkpoint: enough per-row compute that padding a 700-row
    # request to 4096 instead of its 1024 cover is a dispatch-cost
    # delta (tens of ms) far above the box's ~10 ms scheduling-noise
    # tail — the heavy-tail profile's p99 must measure the LADDER, not
    # the noise floor (single-row dispatch stays trivial, so the other
    # profiles' dynamics are unchanged)
    train_on_history(
        store, "mlp",
        model_kwargs=mlp_kwargs or {"hidden": [256, 256], "n_steps": 60},
    )
    defaults = {
        "window_ms": DEFAULT_WINDOW_MS, "max_rows": DEFAULT_MAX_ROWS,
        "buckets": tuple(DEFAULT_BUCKETS),
        "max_pending": DEFAULT_MAX_PENDING,
    }

    def start_default():
        return _ServeTarget(
            store_path, "aio", defaults["window_ms"], defaults["max_rows"],
            defaults["buckets"], isolate,
            max_pending=defaults["max_pending"],
        )

    def healthz(target):
        return rq.get(target.base_url + "/healthz", timeout=10).json()

    profiles: dict = {}
    scratch = tempfile.mkdtemp(prefix="bench-selftune-logs-")
    for i, profile in enumerate(profiles_run):
        primary_knob = SELF_TUNING_PROFILES[profile]
        # -- offered traffic (capacity-relative for the overload profile)
        target = start_default()
        try:
            if profile == "bursty_mmpp":
                # 0.9x the default server's measured capacity: the MEAN
                # rate fits, but the MMPP burst state (4x the calm
                # rate) transiently exceeds it — the burst-absorption
                # regime where flush size decides how fast a squall's
                # backlog drains (overload p99 on this box is
                # front-end-backlog-bound and admission-insensitive —
                # see SELF_TUNING_PROFILES)
                capacity, _ramp = _open_loop_capacity(
                    target.url, rate_cap_rps, window_s=capacity_window_s
                )
                rate = min(burst_load_factor * capacity, rate_cap_rps)
            else:
                capacity = None
                rate = (
                    uniform_rate_rps if profile == "uniform_row"
                    else heavy_rate_rps
                )
            request_log = _profile_request_log(
                profile, rate, drive_s, heavy_batch_rows
            )
            log_path = os.path.join(scratch, f"{profile}.requests.jsonl")
            results_path = os.path.join(scratch, f"{profile}.results.jsonl")
            # the header's config is nominal (the heavy-tail profile is
            # a merged composition) — the tuner reads only the entries
            write_request_log(
                log_path,
                TrafficConfig(rate_rps=rate, duration_s=drive_s, seed=131),
                request_log,
            )
            default_report = run_open_loop(
                target.url, request_log, timeout_s=30.0,
                duration_s=drive_s, results_log=results_path,
            ).to_dict()
        finally:
            target.stop()

        # -- tune on this profile's traces (the cli tune flow)
        table = ObservationTable()
        ingest_request_log(table, log_path)
        ingest_results_log(table, results_path)
        table.dispatch_cost_s = probe_dispatch_costs(
            store, probe_buckets, reps=probe_reps
        )
        table.sources.append("dispatch_probe")
        doc = fit_tuned_config(table)
        tuned_key, tuned_digest = write_tuned_config(
            store, doc, day=d0 + timedelta(days=i + 1)
        )

        # -- re-drive the identical log under the tuned config
        tuned_target = _ServeTarget(
            store_path, "aio", None, None, None, isolate,
            tuned_config=tuned_key,
        )
        try:
            applied = healthz(tuned_target).get("effective_config")
            tuned_report = run_open_loop(
                tuned_target.url, request_log, timeout_s=30.0,
                duration_s=drive_s,
            ).to_dict()
        finally:
            tuned_target.stop()
        beats, comparison = _tuned_beats_default(default_report, tuned_report)
        changed = {
            dec["knob"] for dec in doc["decisions"]
            if dec["source"] == "fitted" and dec["chosen"] != dec["default"]
        }
        print(
            f"  {profile}: default p99 {comparison['default_p99_s']}s / "
            f"{comparison['default_goodput_in_window_rps']:.0f} rps -> "
            f"tuned p99 {comparison['tuned_p99_s']}s / "
            f"{comparison['tuned_goodput_in_window_rps']:.0f} rps "
            f"({'BEATS' if beats else 'no win'}; primary={primary_knob})",
            file=sys.stderr,
        )
        profiles[profile] = {
            "offered_rate_rps": rate,
            "measured_capacity_rps": capacity,
            "primary_knob": primary_knob,
            "tuned_config_key": tuned_key,
            "tuned_config_digest": tuned_digest,
            "effective_config_applied": applied,
            "knobs": doc["knobs"],
            "decisions": doc["decisions"],
            "changed_knobs": sorted(changed),
            "default": default_report,
            "tuned": tuned_report,
            "comparison": comparison,
            "tuned_beats_default": beats,
            "primary_knob_credited": beats and primary_knob in changed,
        }

    # -- sabotage: a garbage tuned document must degrade, not crash ---------
    sabotage_key = "tuning/tuned-config-2026-09-01.json"
    store.put_bytes(sabotage_key, b'{"schema": "nope", "knobs": 17')
    from bodywork_tpu.serve import serve_latest_model

    handle = serve_latest_model(
        store, host="127.0.0.1", port=0, block=False,
        server_engine="aio", tuned_config=sabotage_key,
    )
    try:
        sab = rq.get(
            handle.url.replace("/score/v1", "") + "/healthz", timeout=10
        ).json()
        score = rq.post(
            handle.url, json={"X": [50.0]}, timeout=10
        )
        sabotage = {
            "healthz_status": sab.get("status"),
            "effective_config": sab.get("effective_config"),
            "score_status": score.status_code,
            "degraded_to_defaults": (
                (sab.get("effective_config") or {}).get("tuned_config")
                is None
                and score.status_code == 200
            ),
        }
    finally:
        handle.stop()

    credited = sorted({
        p["primary_knob"] for p in profiles.values()
        if p["primary_knob_credited"]
    })
    beating_profiles = [
        name for name, p in profiles.items() if p["tuned_beats_default"]
    ]
    return {
        "metric": "self_tuning_knobs_beating_defaults",
        "unit": "distinct knobs credited with a tuned win",
        "value": len(credited),
        "vs_baseline": None,
        "baseline_note": (
            "the baseline IS the hand-set defaults (window "
            f"{defaults['window_ms']} ms, max_rows "
            f"{defaults['max_rows']}, buckets {list(defaults['buckets'])}, "
            f"max_pending {defaults['max_pending']}) driven on the same "
            "seeded logs in the same run — no external number applies"
        ),
        "cpu_count": os.cpu_count(),
        "knobs_beating_defaults": credited,
        "profiles_beating": beating_profiles,
        "acceptance": {
            "required": ">=2 distinct knobs beating their hand-set "
                        "defaults on in-window goodput or p99, across "
                        ">=2 seeded profiles, and the sabotaged config "
                        "degrading to defaults",
            "passed": (
                len(credited) >= 2
                and len(beating_profiles) >= 2
                and sabotage["degraded_to_defaults"]
            ),
        },
        "sabotage": sabotage,
        "profiles": profiles,
        "protocol": (
            "one MLP checkpoint; per seeded profile (uniform-row "
            f"Poisson @{uniform_rate_rps} rps, heavy-tail-row 75/25 "
            f"single/{heavy_batch_rows}-row mix @{heavy_rate_rps} rps, "
            f"bursty MMPP @{burst_load_factor}x measured capacity, "
            "4x burst multiplier): drive "
            "the log against default knobs (request+results logs "
            "written), tune from those traces + the dispatch-cost "
            "probe (the cli tune flow), persist under tuning/, "
            "re-drive the IDENTICAL log against a server booted with "
            "only --tuned-config, compare in-window goodput/p99; wins "
            "credited to each profile's primary knob; plus the "
            "garbage-document degrade check"
        ),
    }


def _scrape_families(base_url: str, prefixes: tuple) -> dict:
    """Sum every exposition line under each metric-name prefix from the
    service's aggregated /metrics (labels and exposition suffixes
    collapse into the family totals the occupancy math needs)."""
    import requests as rq

    totals = {p: 0.0 for p in prefixes}
    text = rq.get(base_url + "/metrics", timeout=10).text
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        for p in prefixes:
            if name.startswith(p):
                try:
                    totals[p] += float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
    return totals


def bench_disaggregated_serving(
    frontend_counts: tuple = (1, 4),
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    capacity_window_s: float = 3.0,
    occupancy_rate_rps: float = 250.0,
    occupancy_window_s: float = 3.0,
    template_reps: int = 50000,
) -> dict:
    """Config 14: disaggregated serving — N parse/admission front-ends
    feeding ONE device-owning dispatcher over the shared-memory
    row-queue (``serve --frontends N``).

    The question this record answers: config 9/11 pinned serving
    capacity to the Python HTTP front-end (~1.6k rps on the round-8 box)
    while config 8's device dispatch sustains ~2M rows/s — and
    ``--workers N`` scale-out FRAGMENTS batches (each SO_REUSEPORT
    replica coalesces only its own connection share). Per N in
    ``frontend_counts``, a subprocess fleet (CLI path: ``serve
    --frontends N``, aio front-ends) is measured for

    - **capacity_rps**: config 9's open-loop ramp (goodput peak);
    - **flush occupancy under the SAME offered load**: a fixed-rate
      window (``occupancy_rate_rps``) at every N, mean rows/max_rows
      per coalesced flush read from the dispatcher's
      ``bodywork_tpu_serve_batch_occupancy_ratio`` histogram through
      the aggregated /metrics — the anti-fragmentation regression
      (occupancy at N=4 must not fall below N=1, where ``--workers``
      would divide it);
    - **cross-front-end merging**: the multisource-flush counter over
      the same window (only flushes mixing rows from DIFFERENT
      front-ends move it);
    - **json-vs-binary transport**: the same fixed-rate log driven once
      per framing against the top fleet (satellite: the binary row
      framing strips request-side JSON cost from the same contract).

    Byte-identity is pinned over real HTTP: in-process server vs the
    disaggregated fleet (single/batch/malformed), and JSON vs binary
    framing on the fleet. The single-row response template (the
    front-end's pre-serialized hot path) is micro-benchmarked against
    the full ``json.dumps`` build it is byte-pinned to.

    CPU CAVEAT (in-record): front-ends, the dispatcher, and the
    open-loop driver multiplex the same host cores — on a small box the
    goodput-vs-N slope is core-limited (N=4 can read BELOW N=1) and the
    ≥1.5x scale-out claim is a many-core/TPU-host capture; the
    occupancy/merging regression and the byte contract are
    box-independent and are the binding assertions here.
    """
    import numpy as np

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve.wire import (
        BatchResponseTemplate,
        SingleResponseTemplate,
        batch_score_payload,
        encode_binary_rows,
        single_score_payload,
    )
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.traffic import (
        TrafficConfig,
        generate_request_log,
        run_open_loop,
    )
    from bodywork_tpu.train import train_on_history

    store_path = tempfile.mkdtemp(prefix="bench-disagg-")
    store = FilesystemStore(store_path)
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")

    import requests as rq

    occ_cfg = TrafficConfig(
        rate_rps=occupancy_rate_rps, duration_s=occupancy_window_s, seed=23
    )
    occ_log = generate_request_log(occ_cfg)
    families = (
        "bodywork_tpu_serve_batch_occupancy_ratio_sum",
        "bodywork_tpu_serve_batch_occupancy_ratio_count",
        "bodywork_tpu_coalesced_multisource_flush_total",
        "bodywork_tpu_rowqueue_rows_total",
        "bodywork_tpu_rowqueue_handoff_seconds_count",
    )

    points: dict = {}
    fleet_bodies: dict = {}
    transport_drive: dict = {}
    for n in frontend_counts:
        target = _ServeTarget(
            store_path, "aio", None, None, None, True, frontends=n,
        )
        try:
            health = rq.get(target.base_url + "/healthz", timeout=10).json()
            capacity, ramp = _open_loop_capacity(
                target.url, rate_cap_rps, window_s=capacity_window_s
            )
            # fixed-rate occupancy window: SAME offered load at every N,
            # so the flush-occupancy comparison isolates topology from
            # load. Flusher interval is 0.25 s; the settle sleeps let the
            # dispatcher's snapshot land before each scrape.
            time.sleep(0.6)
            s0 = _scrape_families(target.base_url, families)
            occ_report = run_open_loop(
                target.url, occ_log, timeout_s=15.0,
                duration_s=occupancy_window_s,
            )
            time.sleep(0.6)
            s1 = _scrape_families(target.base_url, families)
            flushes = (
                s1["bodywork_tpu_serve_batch_occupancy_ratio_count"]
                - s0["bodywork_tpu_serve_batch_occupancy_ratio_count"]
            )
            occ_sum = (
                s1["bodywork_tpu_serve_batch_occupancy_ratio_sum"]
                - s0["bodywork_tpu_serve_batch_occupancy_ratio_sum"]
            )
            multisource = (
                s1["bodywork_tpu_coalesced_multisource_flush_total"]
                - s0["bodywork_tpu_coalesced_multisource_flush_total"]
            )
            # byte-identity bodies from this fleet (compared across
            # topologies and framings after the sweep)
            single = rq.post(target.url, json={"X": [50.0]}, timeout=30)
            binary = rq.post(
                target.url, data=encode_binary_rows(np.asarray([50.0])),
                headers={"Content-Type": "application/x-bodywork-rows"},
                timeout=30,
            )
            fleet_bodies[n] = {
                "single": (single.status_code, single.content),
                "binary": (binary.status_code, binary.content),
            }
            if n == max(frontend_counts):
                # transport comparison on the biggest fleet: identical
                # request log, json vs binary framing
                for kind in ("json", "binary"):
                    rep = run_open_loop(
                        target.url, occ_log, timeout_s=15.0,
                        duration_s=occupancy_window_s, transport_kind=kind,
                    )
                    transport_drive[kind] = {
                        "offered_rps": rep.offered_rps,
                        "goodput_in_window_rps": rep.goodput_in_window_rps,
                        "p99_latency_s": rep.latency.get("p99_s"),
                    }
        finally:
            target.stop()
        last = ramp[-1] if ramp else None
        truncated = bool(
            last
            and last["goodput_in_window_rps"] >= 0.9 * last["offered_rps"]
            and last["shed_fraction"] == 0.0
            and 2.0 * last["offered_rps"] > rate_cap_rps
        )
        points[str(n)] = {
            "frontends": n,
            "healthz_role": health.get("role"),
            "healthz_dispatcher_up": health.get("dispatcher_up"),
            "capacity_rps": capacity,
            "capacity_is_lower_bound": truncated,
            "capacity_ramp": ramp,
            "occupancy_window": {
                "offered_rps": occ_report.offered_rps,
                "goodput_in_window_rps": occ_report.goodput_in_window_rps,
                "flushes": flushes,
                "mean_flush_occupancy": (
                    round(occ_sum / flushes, 4) if flushes else None
                ),
                "multisource_flushes": multisource,
                "rowqueue_rows": (
                    s1["bodywork_tpu_rowqueue_rows_total"]
                    - s0["bodywork_tpu_rowqueue_rows_total"]
                ),
            },
        }
        print(
            f"  frontends {n}: capacity {capacity:.0f} rps, mean flush "
            f"occupancy {points[str(n)]['occupancy_window']['mean_flush_occupancy']}"
            f", multisource flushes {multisource:.0f}",
            file=sys.stderr,
        )

    # cross-topology byte identity over real HTTP: one plain in-process
    # server vs the disaggregated fleet (plus malformed-400 parity)
    base_target = _ServeTarget(store_path, "aio", None, None, None, True)
    fleet_target = _ServeTarget(
        store_path, "aio", None, None, None, True,
        frontends=max(frontend_counts),
    )
    try:
        byte_identity = _byte_identity_check({
            "in_process": base_target.base_url,
            "disaggregated": fleet_target.base_url,
        })
    finally:
        base_target.stop()
        fleet_target.stop()
    framing_identical = all(
        bodies["single"] == bodies["binary"] for bodies in fleet_bodies.values()
    )

    # the pre-serialized template vs the full dict-build + dumps it is
    # byte-pinned to (the single-row serialize hot path)
    class _Served:
        model_info = "LinearRegressor(closed_form_ols)"
        model_date = "2026-07-01"

    template = SingleResponseTemplate(
        _Served.model_info, _Served.model_date
    )
    p0 = 25.999998092651367
    assert template.render(p0) == json.dumps(
        single_score_payload(_Served, p0)
    ).encode()
    t0 = time.perf_counter()
    for _ in range(template_reps):
        template.render(p0)
    t_template = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(template_reps):
        json.dumps(single_score_payload(_Served, p0)).encode()
    t_dumps = time.perf_counter() - t0

    # same micro-bench for the batch path (/score/v1/batch): the batch
    # template splices one dumps of the float list between cached
    # invariant bytes instead of rebuilding + re-serializing the whole
    # dict (model_info dominates the body at small batch sizes)
    batch_template = BatchResponseTemplate(
        _Served.model_info, _Served.model_date
    )
    batch_preds = [p0 + i * 0.125 for i in range(64)]
    assert batch_template.render(batch_preds) == json.dumps(
        batch_score_payload(_Served, batch_preds)
    ).encode()
    batch_reps = max(1, template_reps // 10)
    t0 = time.perf_counter()
    for _ in range(batch_reps):
        batch_template.render(batch_preds)
    t_batch_template = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(batch_reps):
        json.dumps(batch_score_payload(_Served, batch_preds)).encode()
    t_batch_dumps = time.perf_counter() - t0

    counts = [str(n) for n in frontend_counts]
    base_cap = points[counts[0]]["capacity_rps"] or None
    top_cap = points[counts[-1]]["capacity_rps"]
    occ = {
        c: points[c]["occupancy_window"]["mean_flush_occupancy"]
        for c in counts
    }
    occupancy_regression_holds = (
        occ[counts[0]] is not None
        and occ[counts[-1]] is not None
        and occ[counts[-1]] >= 0.95 * occ[counts[0]]  # noise floor, not a dip
    )
    core_limited = (os.cpu_count() or 1) < (max(frontend_counts) + 2)
    return {
        "metric": "disaggregated_frontend_scaling",
        "cpu_count": os.cpu_count(),
        "unit": (
            f"goodput_N{counts[-1]}/goodput_N{counts[0]} (open-loop "
            "capacity)"
        ),
        "value": (
            round(top_cap / base_cap, 4) if base_cap else None
        ),
        "vs_baseline": None,
        "baseline_note": (
            "the per-topology baseline is this run's own "
            f"--frontends {counts[0]} point (same box, same harness); "
            "config 9/11 single-process capacity records are the "
            "motivating numbers, not comparable across boxes"
        ),
        "core_limited": core_limited,
        "frontend_counts": list(frontend_counts),
        "points": points,
        "occupancy_regression": {
            "mean_flush_occupancy_by_n": occ,
            "holds": occupancy_regression_holds,
            "note": (
                "same offered load at every N; --workers N would "
                "DIVIDE occupancy by N (each replica coalesces only "
                "its own connection share) — the dispatcher-side "
                "coalescer must keep it flat-or-better as front-ends "
                "scale"
            ),
        },
        "byte_identity": byte_identity,
        "binary_framing_identical": framing_identical,
        "transport_drive": transport_drive,
        "template_bench": {
            "reps": template_reps,
            "template_ns_per_render": round(t_template / template_reps * 1e9),
            "dumps_ns_per_build": round(t_dumps / template_reps * 1e9),
            "speedup": round(t_dumps / t_template, 2) if t_template else None,
        },
        "batch_template_bench": {
            "reps": batch_reps,
            "batch_rows": len(batch_preds),
            "template_ns_per_render": round(
                t_batch_template / batch_reps * 1e9
            ),
            "dumps_ns_per_build": round(t_batch_dumps / batch_reps * 1e9),
            "speedup": (
                round(t_batch_dumps / t_batch_template, 2)
                if t_batch_template else None
            ),
        },
        "cpu_caveat": (
            "front-ends + dispatcher + the open-loop driver multiplex "
            f"{os.cpu_count()} host core(s): the goodput-vs-N slope is "
            "core-limited here and the >=1.5x scale-out claim needs a "
            "many-core/TPU host; occupancy/merging regression and byte "
            "identity are box-independent"
            if core_limited else
            "virtual-device-free host-side measurement; the goodput "
            "slope still reflects this box's core count, not TPU "
            "front-end economics"
        ),
        "protocol": (
            "one linear checkpoint; per N in frontend_counts a "
            "subprocess fleet (cli serve --frontends N, aio "
            "front-ends, dispatcher-side coalescing at defaults): "
            "config-9 capacity ramp + a fixed-rate "
            f"{occupancy_rate_rps:.0f} rps occupancy window with "
            "before/after /metrics scrapes (flush occupancy, "
            "multisource flushes); then in-process vs fleet "
            "byte-identity, json-vs-binary framing identity + drive, "
            "and the single-row template micro-bench"
        ),
    }


def bench_multitenant_stacked(
    fleet_sizes: tuple = (2, 4, 8),
    rows_per_tenant: int = 8,
    bucket: int = 8,
    hidden: tuple = (32, 32),
    train_steps: int = 60,
    windows: int = 7,
    reps_per_window: int = 100,
) -> dict:
    """Config 15: stacked multi-tenant dispatch — N same-architecture
    tenants' MLPs scored in ONE device call (``tenancy.stacked``).

    The question this record answers: the device dispatch sustains ~2M
    rows/s against a ~1.5k rps ingress (config 8 vs 9) — >99% idle
    headroom that a fleet of small per-tenant models can share, IF
    serving N tenants does not cost N dispatches. Per N in
    ``fleet_sizes``, the SAME per-tenant row batches are scored two
    ways — N sequential solo ``PaddedPredictor`` dispatches (the
    one-service-per-tenant deployment) vs one ``StackedMLPPredictor``
    scan dispatch — and the record keeps both paths' min-of-windows
    latency, throughput, and the speedup. The flagship claim
    (``value``): at the largest N, the stacked dispatch is >=3x the
    sequential-solo throughput on identical rows.

    What makes the comparison honest:

    - **byte_identity**: every tenant's stacked (scan-mode) predictions
      are compared byte-for-byte against its own solo predictor —
      stacking must change the economics, never the answers. (vmap mode
      is the batched-GEMM form: measured as its own point with its
      numeric deviation, the quantized-engine treatment.)
    - **residency churn never compiles**: executables are lowered at
      the FIXED ``[capacity, bucket, features]`` stack shape, so the
      record evicts a tenant, admits a NEVER-SEEN one, re-dispatches —
      and pins ``EXECUTABLE_CACHE`` miss count unchanged
      (``readmission_compiles: 0``). Admission cost is data movement,
      not compilation: the multi-tenant analogue of config 11's
      swap-without-recompile.
    - each tenant's training data comes from its scenario-zoo spec
      (``tenancy.scenarios.zoo``) — distinct seeded distributions, so
      the N params trees are genuinely different models, not copies.

    CPU CAVEAT (in-record): on CPU the scan executes slots serially, so
    the speedup here is pure dispatch/padding-overhead amortisation — a
    floor. On a real MXU the batched form (vmap mode) additionally
    converts N small GEMMs into one wide one; the CPU capture cannot
    see that term.
    """
    import numpy as np

    from bodywork_tpu.data import generate_day
    from bodywork_tpu.models.mlp import MLPConfig, MLPRegressor
    from bodywork_tpu.serve.predictor import EXECUTABLE_CACHE, PaddedPredictor
    from bodywork_tpu.tenancy.scenarios import zoo
    from bodywork_tpu.tenancy.stacked import StackedMLPPredictor

    flagship = max(fleet_sizes)
    d = date(2026, 1, 1)
    # one spare spec beyond the flagship: the never-seen tenant the
    # re-admission proof admits into a warmed stack
    specs = zoo(flagship + 1, base_seed=42, n_samples=256)
    models = []
    for spec in specs:
        X, y = generate_day(d, spec.drift_config())
        models.append(
            MLPRegressor(
                MLPConfig(
                    hidden=tuple(hidden), n_steps=train_steps,
                    seed=spec.seed % 10_000,
                )
            ).fit(X.reshape(-1, 1).astype(np.float32), y.astype(np.float32))
        )

    rng = np.random.default_rng(7)
    batches_all = {
        spec.tenant_id: rng.uniform(0.0, 100.0, size=(rows_per_tenant, 1))
        .astype(np.float32)
        for spec in specs
    }

    def min_window(fn) -> float:
        best = float("inf")
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(reps_per_window):
                fn()
            best = min(best, (time.perf_counter() - t0) / reps_per_window)
        return best

    points: dict = {}
    byte_identity = True
    flagship_stack = None
    for n in fleet_sizes:
        stack = StackedMLPPredictor(capacity=n, buckets=(bucket,))
        solos = {}
        for spec, model in zip(specs[:n], models[:n]):
            stack.admit(spec.tenant_id, model)
            solos[spec.tenant_id] = PaddedPredictor(model, buckets=(bucket,))
        stack.warmup()
        batches = {t: batches_all[t] for t in solos}
        for t, solo in solos.items():
            solo.predict(batches[t])  # warm the solo path too
        # the answers must agree BYTE-for-byte before the timing means
        # anything (scan mode = the solo scalar program per slot)
        out = stack.predict_multi(batches)
        for t, solo in solos.items():
            if not np.array_equal(
                np.asarray(out[t]).ravel(),
                np.asarray(solo.predict(batches[t])).ravel(),
            ):
                byte_identity = False
        stacked_s = min_window(lambda: stack.predict_multi(batches))
        solo_s = min_window(
            lambda: [s.predict(batches[t]) for t, s in solos.items()]
        )
        total_rows = n * rows_per_tenant
        points[str(n)] = {
            "tenants": n,
            "stacked_us_per_dispatch": round(stacked_s * 1e6, 1),
            "sequential_solo_us": round(solo_s * 1e6, 1),
            "stacked_rows_per_s": round(total_rows / stacked_s),
            "sequential_rows_per_s": round(total_rows / solo_s),
            "speedup": round(solo_s / stacked_s, 3),
        }
        if n == flagship:
            flagship_stack = stack
    speedup_at_flagship = points[str(flagship)]["speedup"]

    # -- residency churn: evict + admit a never-seen tenant, zero compiles
    misses_before = EXECUTABLE_CACHE.misses
    victim = specs[0].tenant_id
    newcomer = specs[flagship]
    flagship_stack.evict(victim)
    flagship_stack.admit(newcomer.tenant_id, models[flagship])
    churn_batches = {
        t: batches_all[t]
        for t in flagship_stack.resident()
    }
    flagship_stack.predict_multi(churn_batches)
    readmission_compiles = EXECUTABLE_CACHE.misses - misses_before

    # -- vmap point: the batched-GEMM form, with its numeric deviation
    vstack = StackedMLPPredictor(
        capacity=flagship, buckets=(bucket,), stack_mode="vmap"
    )
    for spec, model in zip(specs[:flagship], models[:flagship]):
        vstack.admit(spec.tenant_id, model)
    vstack.warmup()
    vbatches = {s.tenant_id: batches_all[s.tenant_id] for s in specs[:flagship]}
    vout = vstack.predict_multi(vbatches)
    sout = {
        t: np.asarray(
            PaddedPredictor(m, buckets=(bucket,)).predict(vbatches[t])
        ).ravel()
        for t, m in zip(vbatches, models[:flagship])
    }
    vmap_rel_dev = max(
        float(
            np.max(
                np.abs(np.asarray(vout[t]).ravel() - sout[t])
                / np.maximum(np.abs(sout[t]), 1e-9)
            )
        )
        for t in vbatches
    )
    vmap_s = min_window(lambda: vstack.predict_multi(vbatches))

    return {
        "metric": "multitenant_stacked_dispatch",
        "cpu_count": os.cpu_count(),
        "unit": f"sequential_solo_time / stacked_time at N={flagship} "
                "(same rows, scan mode)",
        "value": speedup_at_flagship,
        "vs_baseline": None,
        "baseline_note": (
            "the baseline is this run's own N sequential solo "
            "PaddedPredictor dispatches over identical rows — the "
            "one-service-per-tenant deployment the stack replaces"
        ),
        "fleet_sizes": list(fleet_sizes),
        "rows_per_tenant": rows_per_tenant,
        "bucket": bucket,
        "hidden": list(hidden),
        "points": points,
        "byte_identity": byte_identity,
        "readmission": {
            "evicted": victim,
            "admitted": newcomer.tenant_id,
            "compiles": readmission_compiles,
            "note": (
                "executables are lowered at the fixed "
                f"[{flagship}, {bucket}, 1] stack shape: eviction and "
                "re-admission are data movement, never compilation"
            ),
        },
        "vmap_point": {
            "stacked_us_per_dispatch": round(vmap_s * 1e6, 1),
            "speedup_vs_sequential": round(
                points[str(flagship)]["sequential_solo_us"] / (vmap_s * 1e6), 3
            ),
            "max_rel_deviation_vs_solo": vmap_rel_dev,
            "note": (
                "batched-GEMM form: opt-in because dot_general may "
                "reduce in a different order than the solo program — "
                "close, not byte-identical (the quantized-engine "
                "treatment)"
            ),
        },
        "cpu_caveat": (
            "CPU scan executes slots serially, so this speedup is pure "
            "dispatch/padding-overhead amortisation — a floor; an MXU "
            "additionally fuses N small GEMMs into one wide one (the "
            "vmap point), which this box cannot see"
        ),
        "protocol": (
            f"{flagship + 1} scenario-zoo tenants trained on their own "
            f"seeded distributions (hidden={list(hidden)}); per N in "
            f"{list(fleet_sizes)}: warmed stacked-scan dispatch vs N "
            "warmed sequential solo dispatches over identical "
            f"{rows_per_tenant}-row batches, min over {windows} windows "
            f"x {reps_per_window} reps; per-tenant byte-identity check, "
            "evict/admit zero-compile proof, vmap comparison point"
        ),
    }


def bench_cross_host_transports(
    frontend_counts: tuple = (1, 2, 4),
    transports: tuple = ("shm", "unix", "tcp"),
    rate_cap_rps: float = OPEN_LOOP_RATE_CAP_RPS,
    capacity_window_s: float = 3.0,
    handoff_rate_rps: float = 250.0,
    handoff_window_s: float = 3.0,
    driver_shards: int = 4,
    compare_frontends: int = 2,
    kill_rate_rps: float = 150.0,
    kill_window_s: float = 3.0,
    kill_drill: bool = True,
) -> dict:
    """Config 16: the cross-host socket transport for the row queue
    (``serve --transport {shm,tcp,unix}``) — PR 18's capture.

    Three questions, all on loopback (one box stands in for the
    cross-host pair; the wire cost is real, the network distance is
    not):

    - **transport equivalence**: at N=``compare_frontends`` front-ends,
      every transport (and a plain single-process server) answers
      byte-identical responses over real HTTP — single, batch,
      malformed-400, and the binary row framing. The socket path speaks
      the SAME ``application/x-bodywork-rows`` frames as the HTTP body
      (serve/wire.py), so equivalence is by construction; this measures
      it.
    - **per-row handoff overhead**: a fixed-rate window per transport;
      the dispatcher-side ``rowqueue_handoff_seconds`` histogram delta
      gives the queue hop (shm: cross-process enqueue->dequeue on the
      shared clock; sockets: server receive->dispatch poll), and the
      client p50/p99 under identical load carries the full end-to-end
      difference — the number a platform pays for crossing a host
      boundary.
    - **goodput-vs-N slope over tcp**, captured with the SHARDED
      open-loop driver (``run_open_loop(shards=N)``): the single-process
      driver ceilinged near ~1.6k rps on this harness (docs/PERF.md
      §config 14's N=4 point was generator-truncated); sharding the
      generator across worker processes lifts the ceiling so the slope
      is the SERVICE's, with any remaining ``rate_cap_rps`` truncation
      flagged per point.

    Plus the failure drill the k8s split relies on: SIGKILL the
    dispatcher under the tcp transport mid-load — every in-outage
    response is a 503 with Retry-After (zero hung requests, zero other
    errors), and post-respawn goodput recovers to within 10% of the
    pre-kill window.
    """
    import numpy as np
    import requests as rq

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve.wire import encode_binary_rows
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.traffic import (
        TrafficConfig,
        generate_request_log,
        run_open_loop,
    )
    from bodywork_tpu.train import train_on_history

    store_path = tempfile.mkdtemp(prefix="bench-netq-")
    store = FilesystemStore(store_path)
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")

    handoff_cfg = TrafficConfig(
        rate_rps=handoff_rate_rps, duration_s=handoff_window_s, seed=31
    )
    handoff_log = generate_request_log(handoff_cfg)
    families = (
        "bodywork_tpu_rowqueue_handoff_seconds_sum",
        "bodywork_tpu_rowqueue_handoff_seconds_count",
        "bodywork_tpu_netqueue_rtt_seconds_sum",
        "bodywork_tpu_netqueue_rtt_seconds_count",
        "bodywork_tpu_netqueue_reconnects_total",
        "bodywork_tpu_rowqueue_rows_total",
    )
    identity_cases = {
        "single": ("/score/v1", {"X": [50.0]}),
        "batch": ("/score/v1/batch", {"X": [1.0, 2.0, 3.0]}),
        "malformed": ("/score/v1", {"nope": 1}),
    }

    def collect_bodies(target) -> dict:
        bodies = {}
        for name, (route, body) in identity_cases.items():
            resp = rq.post(target.base_url + route, json=body, timeout=30)
            bodies[name] = (resp.status_code, resp.content)
        binary = rq.post(
            target.url, data=encode_binary_rows(np.asarray([50.0])),
            headers={"Content-Type": "application/x-bodywork-rows"},
            timeout=30,
        )
        bodies["binary_single"] = (binary.status_code, binary.content)
        return bodies

    # -- per-transport comparison at a fixed fleet size ----------------------
    bodies_by_topology: dict = {}
    transport_points: dict = {}
    base_target = _ServeTarget(store_path, "aio", None, None, None, True)
    try:
        bodies_by_topology["single_process"] = collect_bodies(base_target)
    finally:
        base_target.stop()
    for transport in transports:
        target = _ServeTarget(
            store_path, "aio", None, None, None, True,
            frontends=compare_frontends, transport=transport,
        )
        try:
            health = rq.get(target.base_url + "/healthz", timeout=10).json()
            bodies_by_topology[transport] = collect_bodies(target)
            time.sleep(0.6)  # let the 0.25 s metrics flusher settle
            s0 = _scrape_families(target.base_url, families)
            report = run_open_loop(
                target.url, handoff_log, timeout_s=15.0,
                duration_s=handoff_window_s,
            )
            time.sleep(0.6)
            s1 = _scrape_families(target.base_url, families)
            hops = (
                s1["bodywork_tpu_rowqueue_handoff_seconds_count"]
                - s0["bodywork_tpu_rowqueue_handoff_seconds_count"]
            )
            hop_sum = (
                s1["bodywork_tpu_rowqueue_handoff_seconds_sum"]
                - s0["bodywork_tpu_rowqueue_handoff_seconds_sum"]
            )
            rtts = (
                s1["bodywork_tpu_netqueue_rtt_seconds_count"]
                - s0["bodywork_tpu_netqueue_rtt_seconds_count"]
            )
            rtt_sum = (
                s1["bodywork_tpu_netqueue_rtt_seconds_sum"]
                - s0["bodywork_tpu_netqueue_rtt_seconds_sum"]
            )
            transport_points[transport] = {
                "healthz_transport": health.get("transport"),
                "goodput_in_window_rps": report.goodput_in_window_rps,
                "p50_latency_s": report.latency.get("p50_s"),
                "p99_latency_s": report.latency.get("p99_s"),
                "mean_handoff_s": (
                    round(hop_sum / hops, 7) if hops else None
                ),
                "mean_rtt_s": (
                    round(rtt_sum / rtts, 7) if rtts else None
                ),
            }
        finally:
            target.stop()
        print(
            f"  transport {transport}: mean handoff "
            f"{transport_points[transport]['mean_handoff_s']}s, p50 "
            f"{transport_points[transport]['p50_latency_s']}s",
            file=sys.stderr,
        )

    topologies = list(bodies_by_topology)
    byte_identity = {"identical": True, "cases": {}}
    for name in (*identity_cases, "binary_single"):
        unique = {bodies_by_topology[t][name] for t in topologies}
        byte_identity["cases"][name] = {
            "statuses": {
                t: bodies_by_topology[t][name][0] for t in topologies
            },
            "identical": len(unique) == 1,
        }
        if len(unique) != 1:
            byte_identity["identical"] = False

    def _mean(transport, key):
        point = transport_points.get(transport)
        return point and point[key]

    shm_hop = _mean("shm", "mean_handoff_s")
    handoff_overhead = {
        "mean_handoff_s_by_transport": {
            t: _mean(t, "mean_handoff_s") for t in transports
        },
        "mean_rtt_s_by_socket_transport": {
            t: _mean(t, "mean_rtt_s")
            for t in transports if t != "shm"
        },
        "p50_delta_vs_shm_s": {
            t: (
                round(
                    _mean(t, "p50_latency_s") - _mean("shm", "p50_latency_s"),
                    7,
                )
                if _mean(t, "p50_latency_s") is not None
                and _mean("shm", "p50_latency_s") is not None else None
            )
            for t in transports if t != "shm"
        },
        "note": (
            "mean_handoff_s is the dispatcher-side queue hop "
            "(shm: cross-process enqueue->dequeue on the shared clock; "
            "sockets: local receive->dispatch poll — two hosts share no "
            "monotonic clock, so the cross-host number is the client's "
            "netqueue_rtt_seconds minus service time); p50_delta under "
            "identical load is the end-to-end per-row cost of leaving "
            "shared memory"
        ),
    }

    # -- goodput-vs-N over tcp, sharded driver -------------------------------
    scaling_points: dict = {}
    for n in frontend_counts:
        target = _ServeTarget(
            store_path, "aio", None, None, None, True,
            frontends=n, transport="tcp",
        )
        try:
            capacity, ramp = _open_loop_capacity(
                target.url, rate_cap_rps, window_s=capacity_window_s,
                shards=driver_shards,
            )
        finally:
            target.stop()
        last = ramp[-1] if ramp else None
        truncated = bool(
            last
            and last["goodput_in_window_rps"] >= 0.9 * last["offered_rps"]
            and last["shed_fraction"] == 0.0
            and 2.0 * last["offered_rps"] > rate_cap_rps
        )
        scaling_points[str(n)] = {
            "frontends": n,
            "capacity_rps": capacity,
            "capacity_is_lower_bound": truncated,
            "capacity_ramp": ramp,
        }
        print(
            f"  tcp frontends {n}: capacity {capacity:.0f} rps "
            f"(driver shards {driver_shards}"
            f"{', rate-cap truncated' if truncated else ''})",
            file=sys.stderr,
        )

    # -- dispatcher kill under the socket transport --------------------------
    drill: dict = {"ran": False}
    if kill_drill:
        from bodywork_tpu.serve import MultiProcessService

        kill_cfg = TrafficConfig(
            rate_rps=kill_rate_rps, duration_s=kill_window_s, seed=47
        )
        kill_log = generate_request_log(kill_cfg)
        svc = MultiProcessService(
            store_path, frontends=compare_frontends, engine="xla",
            server_engine="aio", transport="tcp",
        ).start()
        try:
            baseline = rq.post(svc.url, json={"X": [50.0]}, timeout=30)
            pre = run_open_loop(
                svc.url.replace("/score/v1", ""), kill_log, timeout_s=15.0,
                duration_s=kill_window_s,
            )
            old_pid = svc.dispatcher_pid
            svc.kill_dispatcher()
            outage = {"requests": 0, "ok": 0, "unavailable": 0,
                      "other": 0, "timeouts": 0,
                      "missing_retry_after": 0}
            deadline = time.monotonic() + 60.0
            healed = False
            while time.monotonic() < deadline:
                outage["requests"] += 1
                try:
                    r = rq.post(svc.url, json={"X": [50.0]}, timeout=10)
                except rq.Timeout:
                    outage["timeouts"] += 1
                    continue
                except rq.RequestException:
                    outage["other"] += 1
                    continue
                if r.status_code == 503:
                    outage["unavailable"] += 1
                    if not r.headers.get("Retry-After"):
                        outage["missing_retry_after"] += 1
                elif r.status_code == 200:
                    outage["ok"] += 1
                    # a 200 after SIGKILL means service is back: either
                    # the probe's in-flight row was held and replayed
                    # over the re-established connection (a late 200,
                    # the post-PR-19 best case — no 503 ever surfaces
                    # to a sequential prober) or the shed window closed.
                    # That the outage was real is proved below by the
                    # front-end's reconnect counter, not by demanding a
                    # 503 first.
                    healed = True
                    break
                else:
                    outage["other"] += 1
                time.sleep(0.05)
            try:
                h = rq.get(
                    svc.url.replace("/score/v1", "") + "/healthz",
                    timeout=10,
                ).json()
                reconnects = int(
                    (h.get("transport") or {}).get("reconnects") or 0
                )
            except (rq.RequestException, ValueError):
                reconnects = -1
            post = run_open_loop(
                svc.url.replace("/score/v1", ""), kill_log, timeout_s=15.0,
                duration_s=kill_window_s,
            )
            after = rq.post(svc.url, json={"X": [50.0]}, timeout=30)
            recovery = (
                post.goodput_in_window_rps / pre.goodput_in_window_rps
                if pre.goodput_in_window_rps else None
            )
            drill = {
                "ran": True,
                "healed": healed,
                "dispatcher_respawned": (
                    svc.dispatcher_pid is not None
                    and svc.dispatcher_pid != old_pid
                ),
                "frontend_reconnects": reconnects,
                "outage": outage,
                "outage_clean": (
                    outage["timeouts"] == 0
                    and outage["other"] == 0
                    and outage["missing_retry_after"] == 0
                ),
                "pre_kill_goodput_rps": pre.goodput_in_window_rps,
                "post_heal_goodput_rps": post.goodput_in_window_rps,
                "recovery_ratio": (
                    round(recovery, 4) if recovery is not None else None
                ),
                "recovered_within_10pct": (
                    recovery is not None and recovery >= 0.9
                ),
                "byte_identical_after_heal": (
                    after.status_code == baseline.status_code == 200
                    and after.content == baseline.content
                ),
            }
            print(
                f"  kill drill: {outage['unavailable']} x 503 + "
                f"{outage['ok']} x 200 (held rows replay as late 200s) / "
                f"{outage['timeouts']} hung, {reconnects} reconnect(s), "
                f"recovery {drill['recovery_ratio']}",
                file=sys.stderr,
            )
        finally:
            svc.stop()

    counts = [str(n) for n in frontend_counts]
    base_cap = scaling_points[counts[0]]["capacity_rps"] or None
    top_cap = scaling_points[counts[-1]]["capacity_rps"]
    core_limited = (
        (os.cpu_count() or 1)
        < (max(frontend_counts) + 2 + driver_shards)
    )
    return {
        "metric": "cross_host_transport_scaling",
        "cpu_count": os.cpu_count(),
        "unit": (
            f"goodput_N{counts[-1]}/goodput_N{counts[0]} over tcp "
            "(sharded open-loop capacity)"
        ),
        "value": round(top_cap / base_cap, 4) if base_cap else None,
        "vs_baseline": None,
        "baseline_note": (
            "the per-topology baseline is this run's own tcp N="
            f"{counts[0]} point; config 14's shm points were captured "
            "with the single-process driver and are not slope-comparable"
        ),
        "core_limited": core_limited,
        "transports": transport_points,
        "byte_identity": byte_identity,
        "handoff_overhead": handoff_overhead,
        "scaling": {
            "transport": "tcp",
            "frontend_counts": list(frontend_counts),
            "driver_shards": driver_shards,
            "points": scaling_points,
        },
        "kill_drill": drill,
        "driver": {
            "shards": driver_shards,
            "superseded_ceiling_note": (
                "the single-process open-loop driver saturated near "
                "~1.6k rps on the round-11 box (docs/PERF.md §config 14 "
                "annotates the truncated N=4 point); this capture's "
                f"driver fans the request log across {driver_shards} "
                "worker processes and merges per-shard reports, so any "
                "remaining truncation is the rate_cap_rps guard, "
                "flagged per point as capacity_is_lower_bound"
            ),
        },
        "cpu_caveat": (
            "front-ends, the dispatcher, and the sharded driver "
            f"multiplex {os.cpu_count()} host core(s): the goodput "
            "slope is core-limited here and loopback sockets understate "
            "real network distance; byte identity, the shed/heal "
            "contract, and the handoff-overhead ordering are "
            "box-independent"
            if core_limited else
            "loopback sockets stand in for the cross-host pair: the "
            "wire cost is real, the network distance is not"
        ),
        "protocol": (
            "one linear checkpoint; per transport in "
            f"{list(transports)} a subprocess fleet (cli serve "
            f"--frontends {compare_frontends} --transport T, aio "
            "front-ends) answers the byte-identity cases and a "
            f"fixed-rate {handoff_rate_rps:.0f} rps window with "
            "before/after /metrics scrapes (handoff + rtt histogram "
            "deltas); then per N in "
            f"{list(frontend_counts)} a tcp fleet under the config-9 "
            f"capacity ramp driven by {driver_shards} generator "
            "shards; then the in-process tcp fleet kill drill "
            "(SIGKILL dispatcher mid-load, classify every in-outage "
            "response, compare pre/post fixed-rate goodput)"
        ),
    }


def bench_dispatcher_failover(
    frontends: int = 2,
    leader_ttl_s: float = 1.0,
    drive_rate_rps: float = 120.0,
    drive_window_s: float = 10.0,
    kill_after_s: float = 3.0,
    fixed_rate_rps: float = 150.0,
    fixed_window_s: float = 3.0,
) -> dict:
    """Config 17: warm-standby dispatcher failover (PR 19's capture).

    One fleet, one fault, one number: a tcp fleet runs an active/standby
    dispatcher pair under lease-fenced leadership
    (``MultiProcessService(standby=True)``); a seeded open-loop drive is
    in flight when the ACTIVE dispatcher takes SIGKILL. The front-ends
    hold the in-flight rows, reconnect to the standby (which bumped the
    lease fence and bound the listener), resubmit, and every held
    request completes — scoring is pure, so duplicate dispatch is safe
    and the answers are byte-identical.

    Asserted bounds, each a line in docs/RESILIENCE.md's runbook:

    - ``max_blackout_s`` (longest span of consecutive scheduled
      arrivals with zero 200s) stays under ``leader_ttl_s`` plus ONE
      reconnect backoff (``RECONNECT_MAX_S``) — the TTL-sizing formula.
    - zero hung requests, zero non-503 errors mid-outage.
    - post-failover fixed-rate goodput recovers to >= 0.98 of the
      pre-kill window — vs 0.9182 for the respawn-only drill in
      BENCH_r13_config16.json, where the replacement dispatcher pays a
      cold JAX init + compile inside the outage.
    - the lease fence observed by the front-ends strictly increases
      across the kill (zombie ex-leaders are refused at HELLO).
    """
    import threading

    import requests as rq

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve import MultiProcessService
    from bodywork_tpu.serve.netqueue import RECONNECT_MAX_S
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.traffic import (
        TrafficConfig,
        generate_request_log,
        run_open_loop,
    )
    from bodywork_tpu.train import train_on_history

    store_path = tempfile.mkdtemp(prefix="bench-failover-")
    store = FilesystemStore(store_path)
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    train_on_history(store, "linear")

    fixed_log = generate_request_log(TrafficConfig(
        rate_rps=fixed_rate_rps, duration_s=fixed_window_s, seed=53
    ))
    drive_log = generate_request_log(TrafficConfig(
        rate_rps=drive_rate_rps, duration_s=drive_window_s, seed=61
    ))

    def leadership_snapshot() -> dict:
        """Worst-case-informed view across front-ends: SO_REUSEPORT
        round-robins /healthz, so sample several times and keep the
        max fence / max takeovers seen."""
        snap = {"fence": 0, "takeovers_observed": 0, "role": None}
        for _ in range(max(4, 2 * frontends)):
            try:
                h = rq.get(base_url + "/healthz", timeout=10).json()
            except rq.RequestException:
                continue
            lead = (h.get("transport") or {}).get("leadership") or {}
            snap["fence"] = max(snap["fence"], int(lead.get("fence") or 0))
            snap["takeovers_observed"] = max(
                snap["takeovers_observed"],
                int(lead.get("takeovers_observed") or 0),
            )
            snap["role"] = lead.get("role") or snap["role"]
        return snap

    svc = MultiProcessService(
        store_path, frontends=frontends, engine="xla",
        server_engine="aio", transport="tcp",
        standby=True, leader_ttl_s=leader_ttl_s,
    ).start()
    base_url = svc.url.replace("/score/v1", "")
    try:
        baseline = rq.post(svc.url, json={"X": [50.0]}, timeout=30)
        before = leadership_snapshot()
        pre = run_open_loop(
            base_url, fixed_log, timeout_s=15.0, duration_s=fixed_window_s
        )

        # -- the drill: SIGKILL the ACTIVE dispatcher mid-drive ----------
        old_pid = svc.dispatcher_pid
        box: dict = {}

        def _drive():
            box["report"] = run_open_loop(
                base_url, drive_log, timeout_s=15.0,
                duration_s=drive_window_s,
            )

        driver = threading.Thread(target=_drive, daemon=True)
        driver.start()
        time.sleep(kill_after_s)
        svc.kill_dispatcher()
        killed_at = time.monotonic()
        driver.join(timeout=drive_window_s + 60.0)
        drill = box.get("report")
        if drill is None:
            raise RuntimeError("failover drive never returned")
        new_pid = svc.dispatcher_pid
        takeover_observed_after_s = round(time.monotonic() - killed_at, 3)

        after_snap = leadership_snapshot()
        post = run_open_loop(
            base_url, fixed_log, timeout_s=15.0, duration_s=fixed_window_s
        )
        after = rq.post(svc.url, json={"X": [50.0]}, timeout=30)
    finally:
        svc.stop()

    blackout_bound_s = leader_ttl_s + RECONNECT_MAX_S
    recovery = (
        post.goodput_in_window_rps / pre.goodput_in_window_rps
        if pre.goodput_in_window_rps else None
    )
    drill_clean = (
        drill.timeouts == 0
        and drill.transport_errors == 0
        and drill.server_error == 0
        and drill.client_error == 0
    )
    print(
        f"  failover drill: blackout {drill.max_blackout_s}s "
        f"(bound {blackout_bound_s}s), {drill.ok}/{drill.requests} ok, "
        f"fence {before['fence']} -> {after_snap['fence']}, "
        f"recovery {round(recovery, 4) if recovery else None}",
        file=sys.stderr,
    )
    return {
        "metric": "dispatcher_failover_blackout",
        "cpu_count": os.cpu_count(),
        "unit": "max_blackout_s under SIGKILL of the active dispatcher",
        "value": drill.max_blackout_s,
        "vs_baseline": 0.9182,
        "baseline_note": (
            "vs_baseline is the RECOVERY ratio of the respawn-only kill "
            "drill in BENCH_r13_config16.json (no standby: the "
            "replacement dispatcher pays cold JAX init + compile inside "
            "the outage and every in-outage request is shed 503); this "
            "config's recovery_ratio must beat it and its blackout must "
            "stay under leader_ttl_s + one reconnect backoff"
        ),
        "leader_ttl_s": leader_ttl_s,
        "blackout_bound_s": blackout_bound_s,
        "blackout_within_bound": drill.max_blackout_s <= blackout_bound_s,
        "drill": {
            "requests": drill.requests,
            "ok": drill.ok,
            "unavailable": drill.unavailable,
            "shed": drill.shed,
            "timeouts": drill.timeouts,
            "transport_errors": drill.transport_errors,
            "server_error": drill.server_error,
            "client_error": drill.client_error,
            "max_blackout_s": drill.max_blackout_s,
            "p99_latency_s": drill.latency.get("p99_s"),
            "zero_hung_zero_errors": drill_clean,
        },
        "leadership": {
            "before": before,
            "after": after_snap,
            "fence_monotonic": after_snap["fence"] > before["fence"],
            "takeover_observed": after_snap["takeovers_observed"] >= 1,
            "active_pid_changed": (
                new_pid is not None and new_pid != old_pid
            ),
            "takeover_observed_after_s": takeover_observed_after_s,
        },
        "pre_kill_goodput_rps": pre.goodput_in_window_rps,
        "post_failover_goodput_rps": post.goodput_in_window_rps,
        "recovery_ratio": round(recovery, 4) if recovery is not None else None,
        "recovered_98pct": recovery is not None and recovery >= 0.98,
        "byte_identical_after_failover": (
            after.status_code == baseline.status_code == 200
            and after.content == baseline.content
        ),
        "protocol": (
            "one linear checkpoint; an in-process tcp fleet "
            f"(MultiProcessService frontends={frontends}, standby=True, "
            f"leader_ttl_s={leader_ttl_s}) answers a fixed-rate "
            f"{fixed_rate_rps:.0f} rps pre-kill window, then a seeded "
            f"{drive_rate_rps:.0f} rps x {drive_window_s:.0f}s open-loop "
            f"drive takes SIGKILL of the ACTIVE dispatcher at "
            f"t={kill_after_s:.0f}s (front-ends hold + resubmit "
            "in-flight rows to the fenced standby), then the same "
            "fixed-rate window replays post-failover and the baseline "
            "request is repeated for byte identity"
        ),
    }


# -- config 18: the online tuning control plane ------------------------------


def bench_online_tuning(
    phase_a_s: float = 5.0,
    phase_b_s: float = 5.0,
    phase_a_rate_rps: float = 60.0,
    # 3x phase A — far past the 0.5 drift threshold, but comfortably
    # under this box's CPU service rate: the refit's OWN guard compares
    # post-apply p99 against the pre-apply window, so a phase-B rate
    # that saturates the box reverts the (correct) refit for latency
    # the traffic caused, not the knobs
    phase_b_rate_rps: float = 180.0,
    poll_interval_s: float = 0.25,
    min_window_requests: int = 60,
    min_verdict_requests: int = 15,
    verdict_polls: int = 40,
    cooldown_polls: int = 2,
    revert_p99_ratio: float = 12.0,
    sabotage_window_ms: float = 900.0,
    calibration_s: float = 2.5,
    calibration_rate_rps: float = 40.0,
    sabotage_drive_s: float = 4.0,
    sabotage_rate_rps: float = 40.0,
    cost_holdout_bound: float = 0.5,
    probe_reps: int = 3,
    cost_budget_s: float = 4.0,
    mlp_kwargs: dict | None = None,
    wait_slack_s: float = 20.0,
) -> dict:
    """Config 18: the online tuning control plane (``tune/online.py``,
    ``tune/costmodel.py``, ``registry/configlog.py`` — ROADMAP 5b/5d).
    One seeded in-process serving run proves the three tentpole claims
    end to end:

    1. **Learned cost model**: the dispatch-cost probe's curve trains
       the ridge regressor; its held-out relative error is recorded
       and must sit inside the stated bound — the evidence behind
       pricing unprobed ladder rungs during the online refit (and the
       admission layer's cost-priced shed, armed here with a generous
       budget so the pricing path runs without shedding healthy load).
    2. **Mid-flight refit, zero compiles, zero dropped requests**: a
       live drive shifts traffic shape mid-flight (uniform trickle ->
       ~4x arrival rate, appended to the controller's watch log while
       requests are in flight); the controller detects the drift,
       refits against the cost-model-priced window, and applies the
       knobs to the LIVE service. Every possible fitted ladder rung is
       a power of two <= 512, and serving boots with exactly that
       ladder AOT-warmed — so the executable-cache miss counter must
       not move after boot (the zero-compile claim, measured), every
       request across both phases must answer 200, and a fixed probe
       request must return byte-identical bodies before and after the
       refit.
    3. **Config-as-canary auto-revert**: a deliberately sabotaged
       config (an absurd-but-valid coalescer window — the valid-but-
       terrible case knob validation cannot catch) is injected through
       ``apply_tuned`` — the SAME machinery the refit uses. The guard
       window catches the p99 regression within its poll budget and
       auto-reverts in exactly ONE config-log CAS (counted at the
       store boundary), restoring the graduated config's knobs, with
       the flight-recorder dump key carried in the revert event.

    CPU-safe: every mechanism (drift arithmetic, AOT cache, CAS
    discipline, guard verdicts) is host-side or cached-executable
    work; the record carries cpu_count and backend."""
    import threading

    import requests as rq

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.obs.tracing import configured_tracing
    from bodywork_tpu.ops.slo import _sum_counter
    from bodywork_tpu.registry.configlog import read_config_log
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.store.base import DelegatingStore
    from bodywork_tpu.store.schema import CONFIG_LOG_KEY
    from bodywork_tpu.traffic import run_open_loop, write_request_log
    from bodywork_tpu.traffic.generator import (
        TrafficConfig,
        generate_request_log,
    )
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.tune.collect import probe_dispatch_costs
    from bodywork_tpu.tune.config import write_tuned_config
    from bodywork_tpu.tune.costmodel import (
        fit_cost_model,
        samples_from_probe,
        write_cost_model,
    )

    class _CasCountingStore(DelegatingStore):
        """Counts ``put_bytes_if_match`` calls per key at the store
        boundary — the exactly-one-CAS budget is asserted on what hit
        the backend, not on what the ledger code intended."""

        def __init__(self, inner):
            super().__init__(inner)
            self.cas_calls: dict = {}

        def put_bytes_if_match(self, key, data, expected_token=None):
            self.cas_calls[key] = self.cas_calls.get(key, 0) + 1
            return self._inner.put_bytes_if_match(key, data, expected_token)

    def _wait_for(predicate, timeout_s: float, tick_s: float = 0.02):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            value = predicate()
            if value:
                return value
            time.sleep(tick_s)
        return predicate()

    def _drive_summary(report: dict) -> dict:
        return {
            "requests": report["requests"],
            "ok": report["ok"],
            "shed": report["shed"],
            "unavailable": report["unavailable"],
            "client_error": report["client_error"],
            "server_error": report["server_error"],
            "transport_errors": report["transport_errors"],
            "timeouts": report["timeouts"],
            "p99_s": (report.get("latency") or {}).get("p99_s"),
        }

    def _zero_errors(report: dict) -> bool:
        return report["ok"] == report["requests"]

    # every ladder rung the fitter can choose is a power of two <= 512
    # (row-quantile covers <= the 512-clamped max_rows flush cover) —
    # booting with ALL of them warmed is what makes the zero-compile
    # assertion global instead of "trust me, it was the watcher thread"
    serve_buckets = tuple(2 ** i for i in range(10))  # 1 .. 512

    store_path = tempfile.mkdtemp(prefix="bench-onlinetune-")
    store = _CasCountingStore(FilesystemStore(store_path))
    d0 = date(2026, 1, 1)
    X, y = generate_day(d0)
    persist_dataset(store, Dataset(X, y, d0))
    train_on_history(
        store, "mlp",
        model_kwargs=mlp_kwargs or {"hidden": [32, 32], "n_steps": 40},
    )

    # -- 1. the learned cost model, from the dispatch probe ------------------
    curve = probe_dispatch_costs(store, serve_buckets, reps=probe_reps)
    from bodywork_tpu.models.checkpoint import load_model, resolve_serving_key

    serving_key, _src = resolve_serving_key(store)
    model, _day = load_model(store, serving_key)
    samples = samples_from_probe(curve, n_features=model.n_features or 1)
    cm_doc = fit_cost_model(samples, seed=18)
    cm_key, cm_digest = write_cost_model(store, cm_doc, d0)
    holdout = cm_doc["holdout"]
    cost_model = {
        "key": cm_key,
        "digest": cm_digest,
        "n_samples": len(samples),
        "holdout": holdout,
        "rel_err_bound": cost_holdout_bound,
        "within_bound": (
            holdout["mean_rel_err"] is not None
            and holdout["mean_rel_err"] <= cost_holdout_bound
        ),
    }

    # -- controller policy, through the deployed env channel -----------------
    tune_env = {
        "BODYWORK_TPU_TUNE_MIN_WINDOW_REQUESTS": str(min_window_requests),
        "BODYWORK_TPU_TUNE_DRIFT_THRESHOLD": "0.5",
        "BODYWORK_TPU_TUNE_COOLDOWN_POLLS": str(cooldown_polls),
        "BODYWORK_TPU_TUNE_VERDICT_POLLS": str(verdict_polls),
        "BODYWORK_TPU_TUNE_MIN_VERDICT_REQUESTS": str(min_verdict_requests),
        "BODYWORK_TPU_TUNE_REVERT_P99_RATIO": str(revert_p99_ratio),
    }
    saved_env = {k: os.environ.get(k) for k in tune_env}
    os.environ.update(tune_env)

    scratch = tempfile.mkdtemp(prefix="bench-onlinetune-logs-")
    watch_log = os.path.join(scratch, "live.requests.jsonl")
    counters = {
        "refits": lambda: _sum_counter(
            "bodywork_tpu_tune_online_refits_total", outcome="applied"
        ),
        "reverts": lambda: _sum_counter(
            "bodywork_tpu_tune_online_reverts_total"
        ),
        "cache_misses": lambda: _sum_counter(
            "bodywork_tpu_serve_executable_cache_misses_total"
        ),
        "cache_hits": lambda: _sum_counter(
            "bodywork_tpu_serve_executable_cache_hits_total"
        ),
        "ingest_bytes": lambda: _sum_counter(
            "bodywork_tpu_tune_ingest_bytes_total", kind="request_log"
        ),
    }
    base = {name: fn() for name, fn in counters.items()}

    handle = None
    try:
        with configured_tracing(1.0, seed=18):
            handle = serve_latest_model(
                store, host="127.0.0.1", port=0, block=False,
                server_engine="aio", watch_interval_s=poll_interval_s,
                buckets=serve_buckets, max_pending=512,
                batch_window_ms=2.0, batch_max_rows=64,
                online_tune=True, tune_request_logs=(watch_log,),
                cost_budget_s=cost_budget_s,
            )
            app = handle.app
            controller = app.tune_controller
            base_url = handle.url.replace("/score/v1", "")
            misses_at_boot = counters["cache_misses"]()
            cost_shed_armed = (app.admission.state() or {}).get("cost_shed")
            probe_payload = {"X": [50.0]}
            body_boot = rq.post(
                handle.url, json=probe_payload, timeout=10
            ).content

            # -- 2a. phase A: the shape the reference pins to ----------------
            cfg_a = TrafficConfig(
                rate_rps=phase_a_rate_rps, duration_s=phase_a_s, seed=181,
            )
            requests_a = generate_request_log(cfg_a)
            write_request_log(watch_log, cfg_a, requests_a)
            report_a = run_open_loop(
                handle.url, requests_a, timeout_s=15.0,
                duration_s=phase_a_s,
            ).to_dict()
            reference = _wait_for(
                lambda: controller._reference, wait_slack_s
            )

            # -- 2b. phase B: shape shift appended MID-DRIVE -----------------
            requests_b = generate_request_log(TrafficConfig(
                rate_rps=phase_b_rate_rps, duration_s=phase_b_s, seed=182,
            ))

            def _append_phase_b():
                offset = phase_a_s + 0.2 * phase_b_s
                with open(watch_log, "a") as f:
                    for r in requests_b:
                        f.write(json.dumps({
                            "t_s": round(r.t_s + offset, 9),
                            "route": r.route, "rows": r.rows,
                            "x": list(r.x),
                        }) + "\n")

            cas_before_refit = store.cas_calls.get(CONFIG_LOG_KEY, 0)
            appender = threading.Timer(0.2 * phase_b_s, _append_phase_b)
            appender.start()
            try:
                report_b = run_open_loop(
                    handle.url, requests_b, timeout_s=15.0,
                    duration_s=phase_b_s,
                ).to_dict()
            finally:
                appender.join()
            refit_applied = _wait_for(
                lambda: counters["refits"]() - base["refits"] >= 1,
                wait_slack_s,
            )
            # the guard window closes by graduating (healthy) — a revert
            # here means the refit regressed its own service
            _wait_for(
                lambda: controller._guard is None,
                verdict_polls * poll_interval_s + wait_slack_s,
            )
            graduated = (
                controller._guard is None
                and counters["reverts"]() - base["reverts"] == 0
            )
            cas_refit = (
                store.cas_calls.get(CONFIG_LOG_KEY, 0) - cas_before_refit
            )
            refit_log = read_config_log(store)
            refit_entry = (refit_log or {}).get("active") or {}
            body_after_refit = rq.post(
                handle.url, json=probe_payload, timeout=10
            ).content
            healthz_after_refit = rq.get(
                base_url + "/healthz", timeout=10
            ).json()

            # -- 3. sabotage: absurd-but-valid window, same machinery --------
            calibration = generate_request_log(TrafficConfig(
                rate_rps=calibration_rate_rps, duration_s=calibration_s,
                seed=183,
            ))
            report_cal = run_open_loop(
                handle.url, calibration, timeout_s=15.0,
                duration_s=calibration_s,
            ).to_dict()
            sab_knobs = {"batch_window_ms": float(sabotage_window_ms)}
            sab_key, sab_digest = write_tuned_config(
                store,
                {"knobs": sab_knobs, "decisions": [], "note": (
                    "bench-18 sabotage: validly-shaped config with an "
                    "absurd coalescer window — the guard, not the "
                    "validator, must catch it"
                )},
                day=date(2026, 9, 18),
            )
            cas_before_sab = store.cas_calls.get(CONFIG_LOG_KEY, 0)
            sab_applied = controller.apply_tuned(
                sab_knobs, sab_key, sab_digest,
                reason=f"bench_sabotage(window_ms={sabotage_window_ms})",
            )
            cas_sab_apply = (
                store.cas_calls.get(CONFIG_LOG_KEY, 0) - cas_before_sab
            )
            sabotage_drive = generate_request_log(TrafficConfig(
                rate_rps=sabotage_rate_rps, duration_s=sabotage_drive_s,
                seed=184,
            ))
            report_sab = run_open_loop(
                handle.url, sabotage_drive,
                timeout_s=max(15.0, 6.0 * sabotage_window_ms / 1e3),
                duration_s=sabotage_drive_s,
            ).to_dict()
            reverted = _wait_for(
                lambda: counters["reverts"]() - base["reverts"] >= 1,
                verdict_polls * poll_interval_s + wait_slack_s,
            )
            cas_revert = (
                store.cas_calls.get(CONFIG_LOG_KEY, 0)
                - cas_before_sab - cas_sab_apply
            )
            final_log = read_config_log(store)
            revert_events = [
                e for e in (final_log or {}).get("history", [])
                if e["event"] == "reverted"
            ]
            revert_event = revert_events[-1] if revert_events else {}
            flight_record_key = revert_event.get("flight_record")
            body_after_revert = rq.post(
                handle.url, json=probe_payload, timeout=10
            ).content
            effective_after_revert = app.effective_config()
            misses_final = counters["cache_misses"]()
    finally:
        if handle is not None:
            handle.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    miss_delta = misses_final - misses_at_boot
    restored_digest = ((final_log or {}).get("active") or {}).get("digest")
    refit = {
        "applied": bool(refit_applied),
        "graduated": graduated,
        "config_log_cas_writes": cas_refit,
        "tuned_config_key": refit_entry.get("key"),
        "tuned_config_digest": refit_entry.get("digest"),
        "knobs": refit_entry.get("knobs"),
        "reference_shape": reference,
        "healthz_tuning": healthz_after_refit.get("tuning"),
        "executable_cache_miss_delta_after_boot": miss_delta,
        "executable_cache_hits_delta": (
            counters["cache_hits"]() - base["cache_hits"]
        ),
        "byte_identical_across_refit": body_after_refit == body_boot,
        "phase_a": _drive_summary(report_a),
        "phase_b": _drive_summary(report_b),
    }
    sabotage = {
        "key": sab_key,
        "digest": sab_digest,
        "knobs": sab_knobs,
        "apply_outcome": sab_applied,
        "config_log_cas_writes_apply": cas_sab_apply,
        "config_log_cas_writes_revert": cas_revert,
        "reverted": bool(reverted),
        "revert_event": revert_event,
        "flight_record_key": flight_record_key,
        "flight_record_exists": bool(
            flight_record_key and store.exists(flight_record_key)
        ),
        "restored_digest": restored_digest,
        "restored_is_graduated_config": (
            restored_digest is not None
            and restored_digest == refit_entry.get("digest")
        ),
        "effective_window_after_revert": (
            effective_after_revert.get("batch_window_ms")
        ),
        "byte_identical_after_revert": body_after_revert == body_boot,
        "calibration": _drive_summary(report_cal),
        "drive": _drive_summary(report_sab),
    }
    zero_errors = all(
        _zero_errors(r) for r in (report_a, report_b, report_cal, report_sab)
    )
    passed = (
        cost_model["within_bound"]
        and refit["applied"] and refit["graduated"]
        and cas_refit == 1
        and miss_delta == 0
        and refit["byte_identical_across_refit"]
        and zero_errors
        and sabotage["reverted"]
        and cas_sab_apply == 1 and cas_revert == 1
        and sabotage["flight_record_exists"]
        and sabotage["restored_is_graduated_config"]
    )
    return {
        "metric": "online_tuning_zero_compile_refit",
        "unit": "executable-cache misses after boot warmup",
        "value": miss_delta,
        "vs_baseline": None,
        "baseline_note": (
            "no external baseline applies: the claims are invariants "
            "(zero misses, zero dropped requests, one CAS per "
            "lifecycle transition) measured against this run's own "
            "counters and store"
        ),
        "cpu_count": os.cpu_count(),
        "cost_model": cost_model,
        "refit": refit,
        "sabotage": sabotage,
        "zero_request_errors": zero_errors,
        "ingest_bytes": counters["ingest_bytes"]() - base["ingest_bytes"],
        "acceptance": {
            "required": (
                "cost-model held-out mean relative error within "
                f"{cost_holdout_bound}; a mid-drive traffic-shape shift "
                "triggers an online refit applied live with zero "
                "executable-cache misses after boot, zero non-200 "
                "responses, and byte-identical probe bodies; the "
                "sabotaged config auto-reverts within "
                f"{verdict_polls} polls in exactly one config-log CAS "
                "with the flight-record key in the revert event"
            ),
            "passed": passed,
        },
        "protocol": (
            "one MLP checkpoint; dispatch probe over the full pow2 "
            "ladder trains the ridge cost model (held-out error "
            "in-record); an aio server boots with every pow2 rung <= "
            "512 AOT-warmed, the online controller watching a live "
            f"request log at {poll_interval_s}s polls; phase A "
            f"({phase_a_rate_rps:.0f} rps x {phase_a_s:.0f}s) pins the "
            f"reference shape, phase B ({phase_b_rate_rps:.0f} rps) is "
            "appended to the watch log MID-DRIVE, the drift refit "
            "applies live under guard and graduates; then an absurd "
            f"{sabotage_window_ms:.0f} ms coalescer window is injected "
            "through apply_tuned and the guard auto-reverts it on the "
            "p99 verdict, flight record dumped, one CAS per transition "
            "counted at the store boundary"
        ),
    }


#: CONFIG_TIMEOUT_S budget and appear in ALL_CONFIGS — pinned by
#: tests/test_bench.py::test_config_registry_sync so a new config can
#: never silently miss one of the three tables (config 7 was once wired
#: by hand; config 8 must not repeat that)
CONFIG_BENCHES = {
    1: lambda: bench_single_day(),
    2: lambda: bench_day_loop("linear", days=7),
    3: lambda: bench_day_loop(
        "mlp", days=30, model_kwargs={"hidden": [64, 64, 64]}
    ),
    4: lambda: bench_batched_scoring(),
    5: lambda: bench_ab(),
    6: lambda: bench_wide(),
    7: lambda: bench_single_row_scoring(),
    8: lambda: bench_history_cold_start(),
    9: lambda: bench_open_loop_serving(),
    10: lambda: bench_incremental_train(),
    11: lambda: bench_compiled_serving(),
    12: lambda: bench_sharded_scaling(),
    13: lambda: bench_self_tuning(),
    14: lambda: bench_disaggregated_serving(),
    15: lambda: bench_multitenant_stacked(),
    16: lambda: bench_cross_host_transports(),
    17: lambda: bench_dispatcher_failover(),
    18: lambda: bench_online_tuning(),
}


def run_config(n: int) -> dict:
    return CONFIG_BENCHES[n]()


def probe_backend(timeout_s: float) -> bool:
    """Check the configured device backend comes up, in a throwaway
    subprocess so a wedged relay cannot hang *this* process. Returns True
    when ``jax.devices()`` answers within the timeout."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            print(
                f"bench: backend probe failed (rc={proc.returncode}): "
                f"{proc.stderr.decode(errors='replace').strip()[-500:]}",
                file=sys.stderr,
            )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        print(
            f"bench: backend probe timed out after {timeout_s}s "
            "(TPU relay wedged?)",
            file=sys.stderr,
        )
        return False


# ---------------------------------------------------------------------------
# Driver-robustness layer (VERDICT r3 item 1): parent/child orchestration,
# bounded re-probe, per-config resume, compact stdout.
# ---------------------------------------------------------------------------

#: bump when record shapes change — stale .bench_state entries never match
#: (v5: fence-based sync; v4 records timed block_until_ready, which does
#: not block over the relay and produced impossible numbers)
SCHEMA_VERSION = 5
#: reuse window for staged records; beyond this a capture is re-measured
RESUME_MAX_AGE_S = 6 * 3600
#: per-config child timeouts, sized at ~4x the round-3 TPU capture plus
#: fresh-process JAX init + compiles (each child is a cold process);
#: config 6 carries the MXU sweep — 4 extra scan compiles at new static
#: shapes, two of them ~4x the flagship FLOPs — on top of the budget the
#: 600 s figure was sized for (the .bench_state compile cache amortises
#: the compiles on any retry)
#: config 7 is host-side HTTP plumbing around tiny device calls — the
#: budget covers JAX init + bucket warmup + ~1.7k requests twice
#: config 8 is host-side store I/O + four small linear fits — the budget
#: covers JAX init plus the per-horizon compiles
#: config 9 is host-side open-loop HTTP around tiny device calls — the
#: budget covers JAX init + two engines x (capacity probe + 3 timed
#: sweep points + the aio MMPP point) at ~4 s per point
#: config 10 is 2 models x 2 modes x a 90-day train loop of small fits
#: (the full-mode MLP series dominates at ~1-2 s/day on CPU) plus the
#: exactness/shadow proof refits — host-compute-bound, generously sized
#: config 11 is host-side HTTP + subprocess serving around small device
#: calls: 2 in-process trains, the swap drive, 3 per-dtype subprocess
#: servers (each a cold JAX init), and two multiproc fleet points
#: (another cold init per worker) — generously sized for a loaded box
#: config 12 is four subprocess-isolated servers (a cold JAX init each)
#: plus four dispatch-probe subprocesses (another cold init each) around
#: capacity ramps of a few seconds per window — generously sized for a
#: loaded box
#: config 13 is host-side HTTP + subprocess serving around small device
#: calls: 3 profiles x 2 subprocess servers (a cold JAX init each) +
#: one capacity ramp + ~12 s of timed drives per profile + the
#: in-process dispatch probe and sabotage boot — generously sized
#: config 14 is four subprocess fleets (front-ends are JAX-free and
#: cheap, but each fleet's dispatcher is a cold JAX init) around two
#: capacity ramps, three fixed-rate occupancy/transport windows, and
#: host-only micro-benches — generously sized for a loaded box
#: config 15 is in-process: 9 small MLP fits, one scan compile per
#: fleet size plus solo/vmap compiles, then microsecond-scale timed
#: windows — the budget is almost entirely JAX init + compiles
#: config 16 is seven subprocess fleets (one cold JAX dispatcher
#: init each: 3 transports + 3 tcp fleet sizes + the single-process
#: baseline) plus the in-process kill-drill fleet, around sharded
#: capacity ramps and fixed-rate handoff windows — generously sized
#: config 18 is one in-process aio server (JAX init + ~10 small AOT
#: compiles from the probe, reused by boot warmup) around ~17 s of
#: timed drives plus the guard windows' poll budgets (~10 s each for
#: graduation and the sabotage verdict) — generously sized
CONFIG_TIMEOUT_S = {
    1: 300, 2: 300, 3: 600, 4: 600, 5: 450, 6: 1200, 7: 600, 8: 300,
    9: 600, 10: 1800, 11: 1200, 12: 1200, 13: 900, 14: 900, 15: 600,
    16: 1200, 17: 900, 18: 900,
}


def tree_fingerprint(root: str | None = None) -> str:
    """Content hash of bench.py + the package source — the resume key.
    Deliberately git-independent: the driver may run with a dirty tree."""
    import hashlib
    from pathlib import Path

    root_p = Path(root or os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    files = sorted((root_p / "bodywork_tpu").rglob("*.py"))
    files.append(root_p / "bench.py")
    for p in files:
        h.update(str(p.relative_to(root_p)).encode())
        h.update(p.read_bytes())
    return h.hexdigest()[:16]


def load_staged_record(state_dir, n: int, fingerprint: str):
    """A previously captured config record, if it is reusable: same schema
    and source fingerprint, fresh enough, error-free, and TPU-backed (CPU
    records are cheap to re-measure; TPU ones are the precious captures a
    mid-run wedge must not discard)."""
    from pathlib import Path

    path = Path(state_dir) / f"config_{n}.json"
    if not path.exists():
        return None
    try:
        staged = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    record = staged.get("record") or {}
    if (
        staged.get("schema") == SCHEMA_VERSION
        and staged.get("fingerprint") == fingerprint
        and time.time() - staged.get("created_unix", 0) < RESUME_MAX_AGE_S
        and "error" not in record
        # an anomalous capture (impossible timing) must re-measure, not
        # pin an invalid record for the whole resume window
        and "timing_anomaly" not in record
        and record.get("backend") == "tpu"
    ):
        return record
    return None


def _stream_tail(data, limit: int) -> str:
    """Bounded text tail of a captured byte stream (None-safe)."""
    if not data:
        return ""
    return data.decode(errors="replace")[-limit:]


def load_timeout_diagnostics(state_dir, n: int) -> dict | None:
    """The stdout/stderr tails persisted by ``run_config_child`` when
    config ``n``'s child hit the timeout — attached to the final failure
    record so a hang is diagnosable from the capture alone."""
    from pathlib import Path

    path = Path(state_dir) / f"config_{n}.timeout.json"
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def save_staged_record(state_dir, n: int, fingerprint: str, record: dict):
    from pathlib import Path

    state_dir = Path(state_dir)
    state_dir.mkdir(parents=True, exist_ok=True)
    tmp = state_dir / f"config_{n}.json.tmp"
    tmp.write_text(json.dumps({
        "schema": SCHEMA_VERSION,
        "fingerprint": fingerprint,
        "created_unix": time.time(),
        "record": record,
    }))
    tmp.replace(state_dir / f"config_{n}.json")


class RelayGate:
    """Bounded re-probe with backoff for a flaky TPU relay.

    The first refusal walks the full backoff schedule; after a full cycle
    has failed, later configs get single cheap probes (so a relay that
    recovers mid-run is still picked up without re-paying the backoff).
    All probe + sleep time draws from one budget, bounding the whole
    bench's probe spend.
    """

    def __init__(self, probe_timeout_s: float = 60.0,
                 budget_s: float = 480.0,
                 backoff_s: tuple = (15.0, 45.0, 90.0)):
        self.probe_timeout_s = probe_timeout_s
        self.budget_s = budget_s
        self.backoff_s = backoff_s
        self.spent_s = 0.0
        self.full_cycle_failed = False

    def _probe_once(self) -> bool:
        t0 = time.perf_counter()
        ok = probe_backend(self.probe_timeout_s)
        self.spent_s += time.perf_counter() - t0
        return ok

    def acquire(self, allow_backoff: bool = True) -> bool:
        """True when the device backend is reachable right now."""
        if self.spent_s + self.probe_timeout_s > self.budget_s:
            print("bench: probe budget exhausted; staying on CPU",
                  file=sys.stderr)
            return False
        if self._probe_once():
            self.full_cycle_failed = False
            return True
        if not allow_backoff or self.full_cycle_failed:
            return False
        for delay in self.backoff_s:
            if self.spent_s + delay + self.probe_timeout_s > self.budget_s:
                break
            print(f"bench: relay down; retrying probe in {delay:.0f}s",
                  file=sys.stderr)
            time.sleep(delay)
            self.spent_s += delay
            if self._probe_once():
                self.full_cycle_failed = False
                return True
        self.full_cycle_failed = True
        return False


def run_config_child(
    n: int,
    use_tpu: bool,
    state_dir,
    cache_dir=None,
    timeout_s: float | None = None,
    backend_timeout_s: float = 120.0,
    skip_probe: bool = False,
) -> dict | None:
    """One config in a fresh child process.

    Returns the record the child wrote — including an ``error`` record for
    a deterministic config failure (those are terminal: retrying a
    reproducible exception on another backend would burn the timeout
    budget and lose the message). ``None`` means the child produced no
    record at all (timeout/crash — the mid-config-wedge signature), and
    the caller decides on retry/fallback.

    A fresh process per config means a mid-config relay wedge cannot take
    already-captured configs with it, at the cost of each child re-paying
    JAX init; ``cache_dir`` (persistent XLA compilation cache) claws the
    compile share of that back. ``skip_probe`` skips the child's own
    backend probe (the parent's gate just ran one) while keeping its
    bring-up watchdog armed.

    A timed-out child is not silent: its captured stdout/stderr tails are
    persisted to ``config_<n>.timeout.json`` (picked up by
    :func:`load_timeout_diagnostics` into the staged failure record), and
    the child arms ``faulthandler.dump_traceback_later`` shortly before
    the parent's deadline, so the tail carries every thread's stack at
    the moment of the hang — round 5's config-5 timeout left an empty
    record with no way to tell WHERE the child wedged.
    """
    from pathlib import Path

    out_file = Path(state_dir) / f"config_{n}.child.json"
    out_file.unlink(missing_ok=True)
    # a stale tail from an earlier run must never label THIS attempt
    (Path(state_dir) / f"config_{n}.timeout.json").unlink(missing_ok=True)
    timeout_s = timeout_s or CONFIG_TIMEOUT_S.get(n, 600)
    # dump all thread stacks ~10 s before the parent kills us (never
    # below half the budget, so a tiny test timeout still dumps first)
    faulthandler_after_s = max(timeout_s - 10.0, timeout_s * 0.5)
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--config", str(n),
        "--json-out", str(out_file),
        "--backend-timeout", str(backend_timeout_s if use_tpu else 0),
        "--faulthandler-after", str(faulthandler_after_s),
    ]
    if skip_probe and use_tpu:
        cmd.append("--skip-probe")
    env = os.environ.copy()
    if not use_tpu:
        # bypass the relay entirely: the axon plugin probes its pool at
        # backend init even under JAX_PLATFORMS=cpu
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        # a virtual 8-device mesh (the test env) so the sharded/mesh
        # sub-benches still execute structurally in a CPU fallback record
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if cache_dir is not None:
        env["JAX_COMPILATION_CACHE_DIR"] = str(cache_dir)
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    try:
        proc = subprocess.run(
            cmd, timeout=timeout_s, capture_output=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
    except subprocess.TimeoutExpired as exc:
        # VERDICT r5 weak §2: the child's captured output up to the kill
        # — including the faulthandler all-thread stack dump armed above
        # — is the only evidence of WHERE it wedged. Persist it for the
        # staged failure record instead of dropping it on the floor.
        diag = {
            "timeout_s": timeout_s,
            "stdout_tail": _stream_tail(exc.stdout, 2000),
            "stderr_tail": _stream_tail(exc.stderr, 6000),
        }
        diag_file = Path(state_dir) / f"config_{n}.timeout.json"
        diag_file.write_text(json.dumps(diag, indent=1))
        print(f"bench: config {n} child timed out after {timeout_s}s; "
              f"captured tails -> {diag_file}", file=sys.stderr)
        if diag["stderr_tail"]:
            print(diag["stderr_tail"][-2000:], file=sys.stderr)
        return None
    # the child's stdout/stderr are progress, never the parent's one line
    for stream in (proc.stdout, proc.stderr):
        text = stream.decode(errors="replace").strip()
        if text:
            print(text[-4000:], file=sys.stderr)
    # a written record wins even on rc != 0: the child captured a
    # deterministic config failure, which is a result, not a wedge
    if out_file.exists():
        try:
            return json.loads(out_file.read_text())
        except ValueError as exc:
            print(f"bench: config {n} child record unparseable: {exc}",
                  file=sys.stderr)
            return None
    print(f"bench: config {n} child died without a record "
          f"(rc={proc.returncode})", file=sys.stderr)
    return None


def summarize_backends(records: list[dict]) -> str:
    def label(r: dict) -> str:
        b = r.get("backend", "unknown")
        if b == "cpu":
            return "cpu fallback"
        if b == "tpu":
            return "tpu"
        return "failed (no measurement)"

    backends = {r.get("backend", "unknown") for r in records}
    if backends == {"tpu"}:
        return "tpu"
    if backends == {"cpu"}:
        return "cpu (fallback: tpu relay unreachable for every config)"
    exceptions = "; ".join(
        f"config {r.get('config')}: {label(r)}"
        for r in records if r.get("backend") != "tpu"
    )
    if "tpu" in backends:
        return f"mixed (tpu, except {exceptions})"
    return f"cpu/failed ({exceptions})"


def compact_output(records: list[dict], backend: str,
                   full_record_path: str) -> dict:
    """The ONE stdout line: headline + per-config one-liners. The driver
    archives only a bounded tail of stdout and parses its last line —
    round 3's full record outgrew that tail and parsed as null — so this
    line stays small and the detail goes to ``full_record_path``."""
    ok = [r for r in records if "error" not in r]
    headline = next(
        (r for r in ok if r.get("config") == HEADLINE_CONFIG),
        ok[0] if ok else None,
    )
    out: dict = {}
    if headline is None:
        out["error"] = "all configs failed"
    else:
        for k in ("metric", "value", "unit", "vs_baseline"):
            out[k] = headline.get(k)
        if headline.get("config") != HEADLINE_CONFIG:
            out["headline_fallback"] = (
                f"config {HEADLINE_CONFIG} failed; headline is "
                f"config {headline['config']}"
            )
    def _sig(v):
        # 5 significant digits is plenty for a one-liner (the full
        # record keeps full precision) and buys line budget at 10 configs
        return float(f"{v:.5g}") if isinstance(v, float) else v

    out["backend"] = backend
    out["schema"] = SCHEMA_VERSION
    out["configs"] = [
        {
            # error messages are truncated: a multi-KB JAX traceback in
            # one config would push this line past the driver's tail and
            # recreate the parsed-as-null failure (full text is in the
            # full record). 80 chars each (plus the float rounding) keeps
            # the worst case — a failed config AND flagged configs — under
            # the 2000-char tail now that the run list holds 13 configs;
            # per-config `unit` (at 10 configs), `vs_baseline` (at 11),
            # `resumed` (at 13), and `metric` (at 14) are dropped from
            # the one-liners for the same budget (the headline keeps
            # metric/unit/vs_baseline, the full record has them all —
            # config numbers alone key the per-config lines)
            k: (r[k][:80] if k in ("error", "cpu_scaled_protocol",
                                   "timing_anomaly") else _sig(r[k]))
            for k in ("config", "value",
                      "backend", "elapsed_s", "error",
                      "cpu_scaled_protocol", "timing_anomaly")
            if k in r
        }
        for r in records
    ]
    out["full_record"] = full_record_path
    return out


def diff_captures(path_a: str, path_b: str) -> list[str]:
    """Per-config headline comparison of two capture files (the full
    record written by the orchestrator, or an interim/dev capture with a
    ``configs`` list). Prints one line per config present in either:
    value A -> value B, the ratio, and backend changes — the tool for
    truthing up README claims against a fresh capture."""

    def load(path):
        with open(path) as f:
            data = json.loads(f.read())
        # entries without a config number can't be paired — report, don't
        # crash on a hand-written/truncated capture
        return {
            c["config"]: c
            for c in data.get("configs", [])
            if c.get("config") is not None
        }

    a, b = load(path_a), load(path_b)
    lines = [f"capture diff: A={path_a}  B={path_b}"]
    for n in sorted(set(a) | set(b), key=str):
        ra, rb = a.get(n), b.get(n)
        if ra is None or rb is None:
            lines.append(f"  config {n}: only in {'B' if ra is None else 'A'}")
            continue
        va, vb = ra.get("value"), rb.get("value")
        ua, ub = ra.get("unit"), rb.get("unit")
        backends = f"{ra.get('backend')}->{rb.get('backend')}"
        if ua != ub:
            # a ratio of incommensurable values would be a wildly wrong
            # verdict in exactly the README-truthing workflow this is for
            lines.append(
                f"  config {n}: {va} {ua} -> {vb} {ub} ({backends}; "
                f"units differ — not comparable)"
            )
        elif isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and va and vb:
            # every headline is time-per-X: lower is better
            speedup = va / vb
            verdict = (f"B {speedup:.2f}x faster" if speedup >= 1
                       else f"B {1 / speedup:.2f}x slower")
            lines.append(
                f"  config {n}: {va} -> {vb} {ub} ({verdict}, {backends})"
            )
        else:
            # distinguish a crashed config from an anomaly-nulled one —
            # the operator shouldn't have to open the raw captures
            notes = [
                f"{side} {field}: {str(r[field])[:80]}"
                for side, r in (("A", ra), ("B", rb))
                for field in ("error", "timing_anomaly")
                if r.get(field)
            ]
            detail = "; ".join(notes) if notes else \
                "non-numeric value on one side"
            lines.append(f"  config {n}: {va} -> {vb} ({backends}; {detail})")
    return lines


def _child_main(args) -> int:
    """Single-config mode: run one config in THIS process and write the
    record to ``--json-out`` (parent mode) and stdout (human use)."""
    from bodywork_tpu.utils.logging import configure_logger
    from bodywork_tpu.utils.watchdog import (
        abort_if_backend_hangs,
        force_cpu_platform,
    )

    if args.faulthandler_after > 0:
        # if this child wedges, dump EVERY thread's stack to stderr just
        # before the parent's kill — the parent persists the captured
        # tail, so the hang site survives into the failure record
        import faulthandler

        faulthandler.dump_traceback_later(
            args.faulthandler_after, exit=False, file=sys.stderr
        )
    hang_s = float(os.environ.get("BENCH_TEST_HANG_S", "0") or 0)
    if hang_s > 0:  # test hook: simulate a wedged child (tests/test_bench.py)
        print(f"bench: test-hang hook armed ({hang_s}s)", file=sys.stderr)
        sys.stderr.flush()
        time.sleep(hang_s)

    fallback = False
    if (
        args.backend_timeout > 0
        and not args.skip_probe
        and not probe_backend(args.backend_timeout)
    ):
        force_cpu_platform()
        fallback = True
        print("bench: falling back to the CPU platform", file=sys.stderr)

    configure_logger(stream=sys.stderr)
    with abort_if_backend_hangs(
        args.backend_timeout if args.backend_timeout > 0 else 0.0,
        what="bench: device backend",
    ):
        import jax

        devices = jax.devices()
    print(f"bench devices: {devices}", file=sys.stderr)

    t0 = time.perf_counter()
    try:
        record = run_config(args.config)
    except Exception as exc:
        record = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench: config {args.config} FAILED: {record['error']}",
              file=sys.stderr)
    record["config"] = args.config
    record["elapsed_s"] = round(time.perf_counter() - t0, 2)
    record["backend"] = devices[0].platform
    if fallback:
        record["backend_note"] = "cpu fallback: tpu relay unreachable"
    if args.faulthandler_after > 0:
        import faulthandler

        faulthandler.cancel_dump_traceback_later()
    line = json.dumps(record)
    if args.json_out:
        from pathlib import Path

        Path(args.json_out).write_text(line)
    print(line)
    return 0 if "error" not in record else 1


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--config", type=int, default=None, choices=ALL_CONFIGS,
        help="run a single config IN-PROCESS: 1-5 = BASELINE.json, 6 = the "
             "beyond-reference wide workload, 7 = single-row serving "
             "latency/concurrency with the request coalescer off vs on, "
             "8 = cold-path history load with the snapshot off vs on "
             "(default: orchestrate all in per-config child processes)",
    )
    parser.add_argument(
        "--json-out", default=None,
        help="(single-config mode) also write the record JSON to this file",
    )
    parser.add_argument(
        "--backend-timeout", type=float, default=120.0,
        help="seconds to wait for the device backend before falling back "
             "to CPU (a wedged TPU relay otherwise hangs jax.devices() "
             "forever); <= 0 skips every probe and trusts the backend",
    )
    parser.add_argument(
        "--skip-probe", action="store_true",
        help="(single-config mode) skip the child's own backend probe — "
             "the parent's gate just ran one — but keep the bring-up "
             "watchdog armed",
    )
    parser.add_argument(
        "--faulthandler-after", type=float, default=0.0, metavar="S",
        help="(single-config mode) dump all thread stacks to stderr "
             "after S seconds if the config is still running — armed by "
             "the parent just under its kill timeout so a wedged child's "
             "hang site lands in the persisted timeout diagnostics "
             "(<= 0 disables)",
    )
    parser.add_argument(
        "--state-dir", default=None,
        help="staging dir for per-config records + the XLA compile cache "
             "(default: .bench_state next to bench.py)",
    )
    parser.add_argument(
        "--full-out", default=None,
        help="where the full record is written "
             "(default: bench_full.json next to bench.py)",
    )
    parser.add_argument(
        "--no-resume", action="store_true",
        help="ignore staged records from a previous (wedged) run",
    )
    parser.add_argument(
        "--probe-budget", type=float, default=480.0,
        help="total seconds the parent may spend probing/backing off on a "
             "flaky relay across the whole run",
    )
    parser.add_argument(
        "--scale-proof", type=int, default=None, metavar="DAYS",
        help="run the day-loop flatness proof at this horizon (e.g. 90) "
             "instead of the 6-config capture; writes to --json-out",
    )
    parser.add_argument(
        "--diff", nargs=2, metavar=("A.json", "B.json"), default=None,
        help="compare two capture files per-config (no benching): "
             "value A -> B, speedup, backend changes",
    )
    args = parser.parse_args()

    if args.diff:
        for line in diff_captures(*args.diff):
            print(line)
        return 0

    if args.scale_proof:
        # the 90-day flatness proof (VERDICT r4 item 10) — separate from
        # the 6-config capture so it never eats the config budget. Probe
        # the relay first: a wedge must degrade to a CPU-structural
        # record, not a hang (env must change BEFORE jax imports — this
        # process has not imported jax yet).
        if args.backend_timeout > 0 and not probe_backend(args.backend_timeout):
            print("bench: relay down; scale proof on CPU (structural)",
                  file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["PALLAS_AXON_POOL_IPS"] = ""
        record = bench_scale_proof(args.scale_proof)
        import jax

        record["backend"] = jax.devices()[0].platform
        out_line = json.dumps(record)
        if args.json_out:
            from pathlib import Path

            Path(args.json_out).write_text(json.dumps(record, indent=1))
        print(out_line)
        return 0

    if args.config is not None:
        return _child_main(args)

    from pathlib import Path

    here = Path(os.path.dirname(os.path.abspath(__file__)))
    state_dir = Path(args.state_dir) if args.state_dir else here / ".bench_state"
    state_dir.mkdir(parents=True, exist_ok=True)
    full_out = Path(args.full_out) if args.full_out else here / "bench_full.json"
    cache_dir = state_dir / "xla_cache"
    fingerprint = tree_fingerprint()
    # <= 0 trusts the backend: no parent gate, no child probes (children
    # still run without watchdogs only in this trust mode)
    trust_backend = args.backend_timeout <= 0
    gate = None if trust_backend else RelayGate(
        probe_timeout_s=max(min(args.backend_timeout, 90.0), 10.0),
        budget_s=args.probe_budget,
    )
    child_timeout = 0.0 if trust_backend else args.backend_timeout

    def _child(n, use_tpu):
        return run_config_child(
            n, use_tpu, state_dir, cache_dir,
            backend_timeout_s=child_timeout,
            # the gate's probe (moments ago) stands in for the child's
            skip_probe=True,
        )

    records: list[dict] = []
    for n in ALL_CONFIGS:
        if not args.no_resume:
            staged = load_staged_record(state_dir, n, fingerprint)
            if staged is not None:
                print(f"bench: config {n} resumed from staged TPU record",
                      file=sys.stderr)
                staged["resumed"] = True
                records.append(staged)
                continue

        use_tpu = True if trust_backend else gate.acquire()
        print(f"bench: running config {n} "
              f"({'tpu' if use_tpu else 'cpu fallback'}) ...", file=sys.stderr)
        if n == 1:
            # config 1 measures a cold process INCLUDING first-compile: run
            # it against a fresh compile cache, then again warm — the pair
            # is the persistent-cache before/after evidence
            import shutil

            shutil.rmtree(cache_dir, ignore_errors=True)
        record = _child(n, use_tpu)
        if record is None and use_tpu and not trust_backend:
            # the relay may have wedged mid-config; one full backoff cycle,
            # then one retry on whatever backend that leaves us
            retry_tpu = gate.acquire(allow_backoff=True)
            print(f"bench: retrying config {n} "
                  f"({'tpu' if retry_tpu else 'cpu fallback'}) ...",
                  file=sys.stderr)
            record = _child(n, retry_tpu)
        if record is None and use_tpu:
            record = _child(n, False)
        if record is None:
            record = {
                "config": n, "backend": "none",
                "error": "child process died without a record on every "
                         "backend (timeout/crash)",
            }
            # a timed-out attempt left its captured output tails (with
            # the faulthandler stack dump) — stage them with the failure
            # so the hang is diagnosable from the record alone
            diag = load_timeout_diagnostics(state_dir, n)
            if diag is not None:
                record["timeout_diagnostics"] = diag
        if n == 1 and "error" not in record:
            warm = _child(n, record.get("backend") == "tpu")
            if warm is not None and "error" not in warm:
                record["warm_cache_rerun"] = {
                    "value": warm["value"],
                    "unit": warm.get("unit"),
                    "elapsed_s": warm.get("elapsed_s"),
                    "note": "same config, fresh process, persistent XLA "
                            "compile cache warm (pipeline/k8s daily-pod "
                            "regime)",
                }
        save_staged_record(state_dir, n, fingerprint, record)
        records.append(record)

    backend = summarize_backends(records)
    full = {"backend": backend, "schema": SCHEMA_VERSION, "configs": records}
    full_out.write_text(json.dumps(full, indent=1))
    print(f"bench: full record -> {full_out}", file=sys.stderr)
    out = compact_output(records, backend, full_out.name)
    print(json.dumps(out))
    return 0 if any("error" not in r for r in records) else 1


if __name__ == "__main__":
    sys.exit(main())
