"""Benchmark: end-to-end pipeline wall-clock per simulated day.

North-star metric from BASELINE.json: the daily train -> serve -> generate ->
test loop, run in-process on the TPU. The reference publishes no end-to-end
number; the only defensible baseline quantity is its recorded live-scoring
cost — 8.22 ms/request x 1317 rows = 10.83 s for the stage-4 loop alone
(``notebooks/4-test-model-scoring-service.ipynb`` cell-10; BASELINE.md) —
which *understates* the reference's full day (it excludes train/generate/
deploy overhead), so ``vs_baseline`` = baseline_s / ours_s is conservative.

With no arguments, runs the five BASELINE.json configs plus the wide
config and prints ONE JSON line whose top-level metric is the north-star
config-2 record, with every per-config record under ``"configs"``.
``--config N`` runs a single config:

1. single simulated day, in-process train+serve (includes first-compile)
2. jitted linear regressor, 7-day drift loop with daily retrain
3. 3-layer MLP, 30-day drift loop with daily retrain + test
4. batched scoring: 1k-row requests through the data-parallel service
   (plus, on a real TPU, the fused Pallas-kernel engine as a sub-record,
   each with a device-side HTTP-free latency view)
5. two concurrent A/B pipelines (linear vs MLP) sharing the pool
6. the WIDE workload (beyond-reference): (1024,1024,1024) MLP, 32
   features, batch 8192 — single-device XLA train with an MFU estimate,
   dp x tp sharded train when the pool allows, device-side serving
   through both engines

Protocol (configs 2/3/5): bootstrap a fresh store, run the multi-day
simulation, report the mean wall-clock of the steady-state days (day 1
pays one-time XLA compiles and is excluded). Config 4 reports mean seconds
per 1k-row scoring request; config 1 reports the single day.

Backend bring-up is self-defending: the device backend is probed in a
subprocess with a timeout, and if it is unreachable (wedged TPU relay —
the round-1 failure mode) the whole bench falls back to the CPU platform
and says so in the emitted record, so a driver capture always yields
numbers instead of a watchdog abort.

Prints ONE JSON line to stdout; progress goes to stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from datetime import date

BASELINE_DAY_S = 1317 * 0.00822  # reference stage-4 scoring loop, see above
BASELINE_REQUEST_S = 0.00822  # reference per-request scoring latency

ALL_CONFIGS = (1, 2, 3, 4, 5, 6)
HEADLINE_CONFIG = 2  # the north-star day loop

# -- config 6: the "wide" workload (no reference analogue) -------------------
# The BASELINE.json configs are all KB-scale (d=2 OLS, 64-wide MLP) — every
# matmul is sub-MXU-tile, so they measure round-trips, not the TPU-first
# design. Config 6 is the first workload where the MXU, the Pallas kernel's
# VMEM residency, and the dp x tp shardings can win or lose: a
# (1024, 1024, 1024) MLP over 32 features, batch 8192.
WIDE_HIDDEN = (1024, 1024, 1024)
WIDE_FEATURES = 32
WIDE_BATCH = 8192
WIDE_STEPS = 50
#: bf16 MXU peak of one v5e chip (~197 TFLOP/s). MFU here is an *estimate*:
#: the train step runs float32 arrays through XLA's default matmul
#: precision, which on TPU executes bf16 MXU passes.
PEAK_FLOPS_V5E = 197e12


def _steady_days(results) -> list:
    """THE steady-state day slice, defined once for every config: day 1
    (XLA compiles) excluded whenever more than one day exists."""
    return list(results[1:]) or [results[0]]


def _steady_mean(results) -> float:
    steady = [r.wall_clock_s for r in _steady_days(results)]
    return sum(steady) / len(steady)


def _run_sim(model_type: str, days: int, model_kwargs=None):
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline
    from bodywork_tpu.store import FilesystemStore

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-store-"))
    spec = default_pipeline(
        model_type=model_type, scoring_mode="batch", overlap_generate=True
    )
    if model_kwargs:
        spec.stages["stage-1-train-model"].args.update(model_kwargs)
    if model_type == "mlp":
        # the reference's 30 s batch budget (bodywork.yaml:20) is sized for
        # its sklearn OLS; the beyond-reference MLP's first-day XLA compile
        # on a cold process needs more headroom
        spec.stages["stage-1-train-model"].max_completion_time_s = 180.0
    runner = LocalRunner(spec, store)
    results = runner.run_simulation(date(2026, 1, 1), days)
    for r in results:
        print(f"  day {r.day}: {r.wall_clock_s:.3f}s", file=sys.stderr)
    return results


def bench_day_loop(model_type: str, days: int, model_kwargs=None) -> dict:
    value = _steady_mean(_run_sim(model_type, days, model_kwargs))
    return {
        "metric": "e2e_day_wallclock",
        "value": round(value, 4),
        "unit": "s/day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def bench_single_day() -> dict:
    results = _run_sim("linear", 1)
    value = results[0].wall_clock_s
    return {
        "metric": "e2e_single_day_wallclock",
        "value": round(value, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
    }


def _time_requests(url: str, payload: dict, rows: int, requests: int) -> float:
    import requests as rq

    rq.post(url, json=payload, timeout=60)  # warm
    t0 = time.perf_counter()
    for _ in range(requests):
        resp = rq.post(url, json=payload, timeout=60)
        assert resp.ok and len(resp.json()["predictions"]) == rows
    return (time.perf_counter() - t0) / requests


def time_device_batch(dispatch, X, iters: int = 30, repeats: int = 3) -> dict:
    """Device-side (HTTP-free) latency of one batch through ``dispatch``.

    The input is ``device_put`` once so no per-call host->device transfer is
    timed. Two numbers, because on a tunnel-attached TPU they differ by the
    tunnel round-trip:

    - ``pipelined_s`` — N dispatches then ONE block, divided by N: the
      round-trip amortises away, leaving per-batch device execution +
      dispatch cost. This is the number that isolates the serving engine
      (XLA vs Pallas) from the transport.
    - ``sync_s`` — mean of per-dispatch ``block_until_ready``: what one
      isolated request would wait for the device, including one full
      host<->device round-trip per call (RTT-floor-bound over a tunnel).

    Protocol: the pipelined measurement is the MIN over ``repeats``
    passes (each: N dispatches, one block), run BEFORE the sync pass.
    Repeated passes through the tunnel are visibly bimodal — the same
    Pallas executable measured 4.0 ms on one pass and 1.9 ms on a later
    pass in the same process while XLA sat at ~3.5 ms throughout — so a
    single pass can report transport contamination as engine time; the
    min is the standard robust floor estimator for latency and every
    pass is recorded for transparency.
    """
    import jax

    Xd = jax.device_put(jnp_float32(X))
    jax.block_until_ready(dispatch(Xd))  # compile + warm
    passes = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = dispatch(Xd)
        jax.block_until_ready(out)
        passes.append((time.perf_counter() - t0) / iters)
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(dispatch(Xd))
    sync_s = (time.perf_counter() - t0) / iters
    return {
        "device_sync_s": round(sync_s, 6),
        "device_pipelined_s": round(min(passes), 6),
        "device_pipelined_passes": [round(p, 6) for p in passes],
        "iters": iters,
    }


def jnp_float32(X):
    import jax.numpy as jnp
    import numpy as np

    X = np.asarray(X, dtype=np.float32)
    if X.ndim == 1:
        X = X[:, None]
    return jnp.asarray(X)


def bench_batched_scoring(rows: int = 1000, requests: int = 20) -> dict:
    """Config 4: 1k-row predict requests through the (data-parallel when
    the pool allows) scoring service; on a real TPU also through the fused
    Pallas MLP kernel (``engine='pallas'``) for an engine-vs-engine record.

    Each engine sub-record additionally carries a device-side measurement
    (:func:`time_device_batch`) so the record separates what the tunnel
    costs (end-to-end HTTP value minus ``device_sync_s``) from what the
    engine costs (``device_pipelined_s``).
    """
    import jax
    import numpy as np

    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.serve import serve_latest_model
    from bodywork_tpu.store import FilesystemStore
    from bodywork_tpu.train import train_on_history

    from functools import partial

    store = FilesystemStore(tempfile.mkdtemp(prefix="bench-score-"))
    d = date(2026, 1, 1)
    X, y = generate_day(d)
    persist_dataset(store, Dataset(X, y, d))
    linear_result = train_on_history(store, "linear")
    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    request_rows = rng.uniform(0, 100, rows)
    payload = {"X": [float(v) for v in request_rows]}

    handle = serve_latest_model(
        store,
        host="127.0.0.1",
        port=0,
        block=False,
        mesh_data=n_dev if n_dev > 1 else None,
    )
    try:
        value = _time_requests(handle.url + "/batch", payload, rows, requests)
    finally:
        handle.stop()
    record = {
        "metric": "batched_1k_request_latency",
        "value": round(value, 5),
        "unit": "s/request",
        # reference scores serially at 8.22 ms/row => 1k rows = 8.22 s
        "vs_baseline": round(rows * BASELINE_REQUEST_S / value, 2),
    }
    # device-side view of the same batch, no HTTP: end-to-end minus
    # device_sync is what the transport (tunnel) costs
    linear_model = linear_result.model
    linear_apply = jax.jit(type(linear_model).apply)
    record["device_batch_linear"] = time_device_batch(
        partial(linear_apply, linear_model.params), request_rows
    )

    # Engine-vs-engine sub-records: the SAME MLP checkpoint timed through
    # the XLA apply and through the fused Pallas kernel, so the pair
    # isolates the serving engine (the main record above is the linear
    # model and is not comparable). Pallas is only meaningful on a real
    # TPU — elsewhere it runs in the interpreter, which benchmarks the
    # interpreter, not the kernel.
    if jax.devices()[0].platform == "tpu":
        # a sub-bench failure (e.g. the first real-TPU Mosaic compile)
        # must not discard the already-measured records above
        try:
            from bodywork_tpu.ops import make_pallas_mlp_apply

            mlp_result = train_on_history(
                store, "mlp", model_kwargs={"hidden": [64, 64, 64]}
            )
            mlp_model = mlp_result.model
            xla_apply = jax.jit(type(mlp_model).apply)
            device_views = {
                "xla": time_device_batch(
                    partial(xla_apply, mlp_model.params), request_rows
                ),
                "pallas": time_device_batch(
                    make_pallas_mlp_apply(mlp_model.params), request_rows
                ),
            }
            engine_values = {}
            for engine in ("xla", "pallas"):
                handle = serve_latest_model(
                    store, host="127.0.0.1", port=0, block=False, engine=engine
                )
                try:
                    engine_values[engine] = _time_requests(
                        handle.url + "/batch", payload, rows, requests
                    )
                finally:
                    handle.stop()
            for engine, value in engine_values.items():
                record[f"{engine}_engine_mlp"] = {
                    "metric": f"batched_1k_request_latency_mlp_{engine}",
                    "value": round(value, 5),
                    "unit": "s/request",
                    "vs_baseline": round(rows * BASELINE_REQUEST_S / value, 2),
                    # the engine-isolating number: device_pipelined_s is
                    # per-batch execution with the tunnel RTT amortised out
                    **device_views[engine],
                }
        except Exception as exc:
            record["pallas_engine"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
            print(f"bench: engine sub-bench FAILED: {exc!r}", file=sys.stderr)
    else:
        record["pallas_engine"] = {
            "skipped": f"non-tpu backend ({jax.devices()[0].platform}); "
            "the kernel would run in the interpreter"
        }
    return record


def wide_train_flops_per_step(
    batch: int = WIDE_BATCH,
    d_in: int = WIDE_FEATURES,
    hidden: tuple = WIDE_HIDDEN,
) -> float:
    """Matmul FLOPs of one optimisation step of the wide MLP: forward
    2*b*sum(in_i*out_i) over the dense stack, backward ~2x forward (dL/dW
    and dL/dx matmuls), so ~3x forward per step. Elementwise/optimizer
    FLOPs are noise next to the matmuls and are ignored."""
    widths = (d_in, *hidden, 1)
    fwd = sum(2.0 * batch * a * b for a, b in zip(widths[:-1], widths[1:]))
    return 3.0 * fwd


def _wide_data(n_rows: int = 2 * WIDE_BATCH):
    """Synthetic 32-feature regression data (the drift generator is the
    1-feature parity workload; the wide config is beyond-reference)."""
    import numpy as np

    rng = np.random.default_rng(7)
    X = rng.uniform(-1.0, 1.0, (n_rows, WIDE_FEATURES)).astype(np.float32)
    w = rng.normal(size=WIDE_FEATURES).astype(np.float32)
    y = X @ w + 0.1 * rng.normal(size=n_rows).astype(np.float32)
    return X, y


def bench_wide(
    steps: int = WIDE_STEPS, serve_iters: int = 20, serve_repeats: int = 3
) -> dict:
    """Config 6: the wide MLP through (a) single-device XLA training with an
    MFU estimate, (b) dp x tp sharded training when the pool has >1 device,
    and (c) batched serving device-side through both engines.

    Training records time a *second* fit (the first pays the XLA compile)
    and report seconds/step, model FLOP/s, and estimated MFU against the
    v5e bf16 peak. Serving records use the device-side pipelined timing
    (:func:`time_device_batch`) on one 8192-row batch.
    """
    import jax
    import numpy as np

    from bodywork_tpu.models.mlp import MLPConfig, MLPRegressor
    from bodywork_tpu.ops import make_pallas_mlp_apply

    on_tpu = jax.devices()[0].platform == "tpu"
    peak = PEAK_FLOPS_V5E if on_tpu else None
    X, y = _wide_data()
    cfg = MLPConfig(
        hidden=WIDE_HIDDEN, batch_size=WIDE_BATCH, n_steps=steps,
        learning_rate=1e-3,
    )
    flops_per_step = wide_train_flops_per_step()

    def _throughput_record(elapsed_s: float, n_chips: int) -> dict:
        """seconds/step + model FLOP/s + MFU estimate — ONE definition for
        the single-device and sharded records so they can't diverge."""
        flops_s = steps * flops_per_step / elapsed_s
        rec = {
            "seconds_per_step": round(elapsed_s / steps, 6),
            "model_tflops_s": round(flops_s / 1e12, 2),
            "steps": steps,
            "batch": WIDE_BATCH,
        }
        if peak:
            rec["mfu_pct_est"] = round(100.0 * flops_s / (peak * n_chips), 2)
        return rec

    def _train_record(fit, n_chips: int):
        fit()  # compile
        t0 = time.perf_counter()
        model = fit()
        jax.block_until_ready(model.params)
        return _throughput_record(time.perf_counter() - t0, n_chips), model

    record: dict = {
        "metric": "wide_mlp_1024x3",
        "hidden": list(WIDE_HIDDEN),
        "features": WIDE_FEATURES,
        "flops_per_step": flops_per_step,
    }

    xla_rec, model = _train_record(lambda: MLPRegressor(cfg).fit(X, y), 1)
    record["train_xla_single"] = xla_rec

    n_dev = len(jax.devices())
    if n_dev >= 2:
        # a sub-bench failure must not discard the already-measured
        # single-device record above (same guard as config 4's engines)
        try:
            from bodywork_tpu.parallel import make_mesh, train_mlp_sharded

            dp = n_dev // 2  # odd pools: use the largest even subset
            devices = jax.devices()[: dp * 2]
            mesh = make_mesh(data=dp, model=2, devices=devices)

            train_mlp_sharded(X, y, cfg, mesh)  # compile
            # time via the path's own staging/scan split: billing the
            # host-side batch-schedule staging (which the single-device
            # program performs on-device) to MFU would let untimed-vs-
            # timed host work invert the dp x tp conclusion
            timings: dict = {}
            train_mlp_sharded(X, y, cfg, mesh, timings=timings)
            sharded_rec = _throughput_record(timings["scan_s"], len(devices))
            sharded_rec["host_staging_s"] = round(timings["staging_s"], 4)
            sharded_rec["mesh"] = f"{dp}x2"
            record["train_sharded_dp_tp"] = sharded_rec
        except Exception as exc:
            record["train_sharded_dp_tp"] = {
                "error": f"{type(exc).__name__}: {exc}"
            }
            print(f"bench: wide sharded sub-bench FAILED: {exc!r}",
                  file=sys.stderr)
    else:
        record["train_sharded_dp_tp"] = {
            "skipped": f"{n_dev} device(s); dp x tp needs >= 2"
        }

    # serving: one 8192x32 batch, device-side, engine vs engine
    Xb = X[:WIDE_BATCH]
    from functools import partial

    xla_apply = jax.jit(type(model).apply)
    record["serve_xla"] = time_device_batch(
        partial(xla_apply, model.params), Xb,
        iters=serve_iters, repeats=serve_repeats,
    )
    if on_tpu:
        record["serve_pallas"] = time_device_batch(
            make_pallas_mlp_apply(model.params), Xb,
            iters=serve_iters, repeats=serve_repeats,
        )
    else:
        record["serve_pallas"] = {
            "skipped": "non-tpu backend; the kernel would run in the "
            "interpreter"
        }
    # rows/s through the faster engine's pipelined path, for scale feel
    best = min(
        v["device_pipelined_s"]
        for v in (record["serve_xla"], record.get("serve_pallas", {}))
        if "device_pipelined_s" in v
    )
    record["serve_rows_per_s"] = round(WIDE_BATCH / best, 1)
    record["value"] = record["train_xla_single"]["seconds_per_step"]
    record["unit"] = "s/step"
    record["vs_baseline"] = None
    record["baseline_note"] = (
        "no reference analogue — beyond-reference workload; the reference's "
        "only model is d=2 OLS (SURVEY.md §2)"
    )
    return record


def bench_ab(days: int = 5, model_types=("linear", "mlp")) -> dict:
    """Config 5: N concurrent A/B pipelines sharing the pool.

    Protocol now matches configs 2/3 (steady-state mean, day 1 excluded):
    the round-2 capture divided TOTAL wall-clock — including each
    variant's day-1 XLA compiles and store bootstrap — by pipeline-days,
    which is what produced the unexplained '7.4x config 2' number
    (VERDICT r2 item 3); the per-variant steady means (0.10-0.13 s/day on
    the same capture) only went to stderr. Here the headline is the mean
    of per-variant steady-state s/day, and the JSON carries the full
    attribution: per-variant steady mean, first-day cost, per-stage steady
    seconds, the untimed bootstrap overhead, and the total wall-clock the
    old protocol measured.

    Attribution note: ``run_simulation`` pays store bootstrap and the
    horizon's train-bucket compiles BEFORE its timed day loop, so
    ``day1_s`` is the first *timed* day (it still pays the serve-path
    compiles); the pre-loop cost appears as ``untimed_bootstrap_s``
    (total wall-clock minus the slowest variant's summed day times).
    """
    from bodywork_tpu.pipeline import run_ab_simulation, variants_from_model_types

    root = tempfile.mkdtemp(prefix="bench-ab-")
    variants = variants_from_model_types(list(model_types))
    t0 = time.perf_counter()
    results = run_ab_simulation(variants, root, date(2026, 1, 1), days)
    total = time.perf_counter() - t0

    variant_records = {}
    steady_means = []
    slowest_day_sum = 0.0
    for name, vr in results.items():
        if vr.error is not None:
            raise RuntimeError(f"variant {name} failed: {vr.error!r}")
        # ONE steady-day slice (shared with configs 2/3 via _steady_days)
        # for both the mean and the stage attribution, so the protocols
        # can never silently diverge again
        steady_days = _steady_days(vr.results)
        steady = sum(r.wall_clock_s for r in steady_days) / len(steady_days)
        steady_means.append(steady)
        slowest_day_sum = max(
            slowest_day_sum, sum(r.wall_clock_s for r in vr.results)
        )
        stage_means = {}
        for r in steady_days:
            for stage, secs in r.stage_seconds.items():
                stage_means.setdefault(stage, []).append(secs)
        variant_records[name] = {
            "steady_s_per_day": round(steady, 4),
            "day1_s": round(vr.results[0].wall_clock_s, 4),
            "stage_seconds_steady": {
                stage: round(sum(v) / len(v), 4)
                for stage, v in sorted(stage_means.items())
            },
        }
        print(f"  {name}: {steady:.3f}s/day steady", file=sys.stderr)

    value = sum(steady_means) / len(steady_means)
    return {
        "metric": "ab_day_wallclock_per_pipeline_day",
        "value": round(value, 4),
        "unit": "s/pipeline-day",
        "vs_baseline": round(BASELINE_DAY_S / value, 2),
        "protocol": (
            "steady-state mean over variants, day 1 excluded "
            if days > 1
            else "SINGLE-day run: day 1 (serve-path compiles) IS the mean "
        )
        + "(same _steady_days slice as configs 2/3); day1_s is the first "
          "TIMED day — store bootstrap and horizon train-compile prewarm "
          "run before the timer and are untimed_bootstrap_s",
        "variants": variant_records,
        "total_wallclock_s": round(total, 2),
        "untimed_bootstrap_s": round(max(total - slowest_day_sum, 0.0), 2),
        "days": days,
    }


def run_config(n: int) -> dict:
    if n == 1:
        return bench_single_day()
    if n == 2:
        return bench_day_loop("linear", days=7)
    if n == 3:
        return bench_day_loop("mlp", days=30, model_kwargs={"hidden": [64, 64, 64]})
    if n == 4:
        return bench_batched_scoring()
    if n == 6:
        return bench_wide()
    return bench_ab()


def probe_backend(timeout_s: float) -> bool:
    """Check the configured device backend comes up, in a throwaway
    subprocess so a wedged relay cannot hang *this* process. Returns True
    when ``jax.devices()`` answers within the timeout."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            print(
                f"bench: backend probe failed (rc={proc.returncode}): "
                f"{proc.stderr.decode(errors='replace').strip()[-500:]}",
                file=sys.stderr,
            )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        print(
            f"bench: backend probe timed out after {timeout_s}s "
            "(TPU relay wedged?)",
            file=sys.stderr,
        )
        return False


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--config", type=int, default=None, choices=ALL_CONFIGS,
        help="run a single config: 1-5 = BASELINE.json, 6 = the "
             "beyond-reference wide workload (default: all six)",
    )
    parser.add_argument(
        "--backend-timeout", type=float, default=180.0,
        help="seconds to wait for the device backend before falling back "
             "to CPU (a wedged TPU relay otherwise hangs jax.devices() "
             "forever); <= 0 skips the probe and trusts the backend",
    )
    args = parser.parse_args()

    from bodywork_tpu.utils.logging import configure_logger
    from bodywork_tpu.utils.watchdog import (
        abort_if_backend_hangs,
        force_cpu_platform,
    )

    fallback = False
    if args.backend_timeout > 0 and not probe_backend(args.backend_timeout):
        # The relay is down: record CPU numbers with a caveat rather than
        # aborting with nothing (round-1 outcome: parsed=null).
        force_cpu_platform()
        fallback = True
        print("bench: falling back to the CPU platform", file=sys.stderr)

    configure_logger(stream=sys.stderr)  # keep stdout = the one JSON line

    # Belt and braces: the probe said the backend is fine (or was skipped),
    # but bring-up in *this* process still gets a watchdog.
    with abort_if_backend_hangs(
        args.backend_timeout if args.backend_timeout > 0 else 0.0,
        what="bench: device backend",
    ):
        import jax

        devices = jax.devices()
    print(f"bench devices: {devices}", file=sys.stderr)
    platform = devices[0].platform

    configs = [args.config] if args.config else list(ALL_CONFIGS)
    records = []
    for n in configs:
        print(f"bench: running config {n} ...", file=sys.stderr)
        t0 = time.perf_counter()
        try:
            record = run_config(n)
        except Exception as exc:  # record the failure, keep benching
            record = {"error": f"{type(exc).__name__}: {exc}"}
            print(f"bench: config {n} FAILED: {record['error']}", file=sys.stderr)
        record["config"] = n
        record["elapsed_s"] = round(time.perf_counter() - t0, 2)
        records.append(record)

    backend_note = (
        f"{platform} (fallback: tpu relay unreachable; TPU-backed capture "
        "of the same configs: BENCH_DEV_r03.json)"
        if fallback
        else platform
    )
    ok = [r for r in records if "error" not in r]
    if not ok:
        print(json.dumps({"error": "all configs failed", "backend": backend_note,
                          "configs": records}))
        return 1
    headline = next(
        (r for r in ok if r["config"] == HEADLINE_CONFIG), ok[0]
    )
    out = dict(headline)
    if len(configs) > 1:
        out["configs"] = records
        if headline["config"] != HEADLINE_CONFIG:
            out["headline_fallback"] = (
                f"config {HEADLINE_CONFIG} failed; headline is "
                f"config {headline['config']}"
            )
    out["backend"] = backend_note
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
