"""bodywork_tpu — a TPU-native ML pipeline lifecycle framework.

A brand-new JAX/XLA-first framework with the capabilities of the Bodywork
MLOps demo (reference: AlexIoannides/bodywork-mlops-demo): a daily
train -> serve -> generate-drift-data -> test-the-live-service loop for a
regression model under concept drift.

Subpackages
-----------
- ``store``    — date-versioned artefact store (filesystem / GCS-ready),
                 replacing the reference's S3 data plane (C7 in SURVEY.md).
- ``data``     — drift-data generator on ``jax.random`` (reference C4,
                 ``stage_3_synthetic_data_generation.py``).
- ``models``   — jitted regressors (closed-form OLS, 3-layer MLP), metrics,
                 pytree checkpointing (reference C2/C6).
- ``train``    — training orchestration over the artefact store
                 (reference ``stage_1_train_model.py``).
- ``serve``    — HTTP ``/score/v1`` scoring service with params resident in
                 TPU HBM, shape-bucketed batch scoring
                 (reference ``stage_2_serve_model.py``).
- ``monitor``  — live-service tester + drift metrics + longitudinal
                 analytics (reference ``stage_4`` + analytics notebook).
- ``pipeline`` — declarative pipeline spec, local day-loop runner, GKE TPU
                 manifest generation (reference ``bodywork.yaml``).
- ``cli``      — ``python -m bodywork_tpu.cli`` driver for every stage and
                 the multi-day simulation.

Planned (landing incrementally; see SURVEY.md §7 build plan):

- ``ops``      — Pallas TPU kernels for the hot compute paths.
- ``parallel`` — ``jax.sharding.Mesh`` utilities, data-parallel scoring and
                 dp+tp training-step sharding (reference has no distributed
                 backend; this is the TPU-native replacement).
"""

from bodywork_tpu.version import __version__

__all__ = ["__version__"]
