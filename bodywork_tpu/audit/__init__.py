"""Store integrity scrubbing: the audit/fsck subsystem.

The first subsystem that reasons about the artefact store AS A WHOLE
rather than one artefact class at a time. Three cooperating layers:

- :mod:`bodywork_tpu.audit.manifest` — write-time digest sidecars (and
  compressed replicas for small non-rebuildable classes) recorded under
  ``audit/`` by the transparent :class:`AuditedStore` wrapper
  ``store.open_store`` installs;
- :mod:`bodywork_tpu.audit.fsck` — the full-store scrub: every prefix
  in ``schema.ALL_PREFIXES`` audited against write-time evidence plus
  the cross-subsystem reference graph, findings graded by the
  rebuildable / restorable / data-loss / advisory taxonomy;
- :mod:`bodywork_tpu.audit.repair` — the planner that executes the safe
  subset: quarantine corrupt bytes (``quarantine/``, never deleted),
  restore digest-verified redundancy, rebuild derived artefacts, demote
  dangling references.

Proof: the at-rest bit-rot chaos soak (``chaos/bitrot.py``,
``cli chaos run-sim --bit-rot``) flips bytes across every prefix of a
finished simulation and requires 100% detection + classification, with
``--repair`` converging the store byte-identical to an uncorrupted twin
outside ``quarantine/``.
"""
from bodywork_tpu.audit.fsck import (
    ACTIONABLE,
    CHECKERS,
    FSCK_REPORT_SCHEMA,
    Finding,
    SEVERITIES,
    run_fsck,
)
from bodywork_tpu.audit.manifest import (
    AuditedStore,
    artefact_sha256,
    read_sidecar,
    write_sidecar,
)
from bodywork_tpu.audit.repair import REPAIR_ORDER, execute_repairs, quarantine

__all__ = [
    "ACTIONABLE",
    "AuditedStore",
    "CHECKERS",
    "FSCK_REPORT_SCHEMA",
    "Finding",
    "REPAIR_ORDER",
    "SEVERITIES",
    "artefact_sha256",
    "execute_repairs",
    "quarantine",
    "read_sidecar",
    "run_fsck",
    "write_sidecar",
]
