"""Full-store integrity scrub ("fsck"): find at-rest corruption NOW.

Every durability guarantee before this subsystem verifies *lazily at
read time*: the snapshot loader validates when training reads, registry
readers validate when the gate runs, resume verification re-hashes when
a day restarts. An artefact nobody reads stays unverified forever — so
silent at-rest corruption of a COLD artefact (an old dataset day, the
``previous`` alias checkpoint, a tail snapshot) is a latent outage that
detonates exactly when the resilience machinery needs it: a rollback or
trainstate rebuild lands on garbage. The scrubber closes that gap by
walking EVERY prefix in ``schema.ALL_PREFIXES`` on a schedule (``cli
fsck``, the k8s scrub CronJob) and verifying each artefact against
write-time evidence:

- raw-byte sha256 sidecars for datasets, checkpoints, and metrics
  (:mod:`bodywork_tpu.audit.manifest`), cross-checked against run-journal
  artefact digests and registry lineage digests;
- embedded ``doc_digest`` fields for journals, registry records, and the
  alias document (``utils.integrity``); trainstate's own payload digest;
- structural self-validation for snapshots (zip CRC + manifest row
  counts — a single-byte flip always changes the CRC32);
- the cross-subsystem reference graph: alias slots -> records ->
  checkpoints/metrics, snapshot manifests -> dataset days, journals ->
  stage artefacts.

Findings carry a severity from the repair planner's point of view:

- ``rebuildable`` — derived state with a deterministic rebuild path
  (snapshots re-compact from datasets; trainstate and journals are
  rebuilt by the next train/run);
- ``restorable`` — an independent redundant copy exists (dataset days
  restore from snapshot slices, checkpoints/metrics/registry documents
  from their sidecar replicas, dangling alias slots demote in one CAS)
  and every restore is DIGEST-VERIFIED before it lands;
- ``data_loss`` — no redundancy survives; the corrupt bytes are
  quarantined and reported, never silently "fixed";
- ``advisory`` — hygiene, not corruption (missing write-time digest on
  a legacy artefact, orphan sidecars, stale lineage digests).

``run_fsck(store, repair=True)`` executes the safe subset
(:mod:`bodywork_tpu.audit.repair`): corrupt bytes move to
``quarantine/`` (CAS-written, never deleted by the framework), derived
artefacts are rebuilt, replicas restored, dangling references demoted.
Metrics: ``bodywork_tpu_audit_scans_total{prefix}``,
``bodywork_tpu_audit_findings_total{prefix,severity,problem}``,
``bodywork_tpu_audit_repairs_total{prefix,action,outcome}``.

The checker registry :data:`CHECKERS` is guard-pinned (tests/test_audit.py)
to cover exactly ``schema.ALL_PREFIXES`` and the documented integrity
table (docs/RESILIENCE.md §11) — adding a prefix without an auditor, or
without documenting its guarantees, fails tier-1.

Deliberately jax-free: the scrubber runs on plain CPU pods (the scrub
CronJob) and must never pay — or require — an accelerator runtime.
"""
from __future__ import annotations

import dataclasses
import hashlib
import io
import json

from bodywork_tpu.audit.manifest import artefact_sha256, read_sidecar
from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore
from bodywork_tpu.store.schema import (
    ALL_PREFIXES,
    AUDIT_PREFIX,
    DATASETS_PREFIX,
    FLIGHTREC_PREFIX,
    MODEL_METRICS_PREFIX,
    MODELS_PREFIX,
    QUARANTINE_META_SUFFIX,
    QUARANTINE_PREFIX,
    REGISTRY_ALIAS_KEY,
    REGISTRY_PREFIX,
    REGISTRY_RECORDS_PREFIX,
    RUNS_PREFIX,
    SERVE_PREFIX,
    SNAPSHOTS_PREFIX,
    TENANTS_PREFIX,
    TEST_METRICS_PREFIX,
    TRAINSTATE_PREFIX,
    TUNING_PREFIX,
    audit_digest_key,
    audit_primary_key,
)
from bodywork_tpu.utils.integrity import verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("audit.fsck")

FSCK_REPORT_SCHEMA = "bodywork_tpu.fsck_report/1"

#: the severity taxonomy, most to least repairable (module docstring)
SEVERITIES = ("rebuildable", "restorable", "data_loss", "advisory")

#: severities an operator must care about (everything but hygiene)
ACTIONABLE = ("rebuildable", "restorable", "data_loss")

__all__ = [
    "ACTIONABLE",
    "CHECKERS",
    "FSCK_REPORT_SCHEMA",
    "Finding",
    "FsckContext",
    "SEVERITIES",
    "run_fsck",
]


@dataclasses.dataclass
class Finding:
    """One integrity defect at one key. ``repair`` names the planner
    action that can fix it (None = not auto-repairable: data loss, or
    an operator decision like a dangling production alias)."""

    key: str
    prefix: str
    problem: str
    severity: str
    detail: str = ""
    repair: str | None = None

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _get(store: ArtefactStore, key: str) -> bytes | None:
    try:
        return store.get_bytes(key)
    except ArtefactNotFound:
        return None  # listed-then-vanished: racing maintenance, skip


def _json_doc(data: bytes) -> dict | None:
    try:
        doc = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _csv_parses(data: bytes) -> bool:
    """Cheap structural sanity for CSV artefacts: decodable text whose
    rows all carry the header's column count. (The authoritative check
    is the digest; this only grades corruption of UNDIGESTED legacy
    artefacts.)"""
    try:
        text = data.decode("utf-8")
    except UnicodeDecodeError:
        return False
    lines = [ln for ln in text.splitlines() if ln]
    if not lines:
        return False
    width = lines[0].count(",")
    return all(ln.count(",") == width for ln in lines)


# -- trainstate validation (mirrors train/incremental.py, jax-free) --------

_TRAINSTATE_SCHEMA = "bodywork_tpu.trainstate/1"


def _trainstate_payload_digest(doc: dict) -> str:
    # mirror of train.incremental._payload_digest, duplicated here so
    # the scrubber never imports the training stack (which pulls jax);
    # tests/test_audit.py pins the two implementations equal
    payload = json.dumps(
        [doc["model_type"], doc["feature_dim"], doc["split"],
         doc["cum_g"], doc["cum_c"], doc["days"]],
        sort_keys=True,
    ).encode("utf-8")
    return "sha256:" + hashlib.sha256(payload).hexdigest()


def _trainstate_valid(doc: dict | None) -> bool:
    if doc is None or doc.get("schema") != _TRAINSTATE_SCHEMA:
        return False
    try:
        return doc.get("digest") == _trainstate_payload_digest(doc)
    except (KeyError, TypeError, ValueError):
        return False


# -- the shared scan context ----------------------------------------------


class FsckContext:
    """One scrub's shared evidence: listings per prefix, run-journal
    artefact digests, registry lineage digests, sidecar reads, and
    loadable snapshot manifests — each computed once, consulted by
    every checker."""

    def __init__(self, store: ArtefactStore):
        self.store = store
        self.keys = {p: store.list_keys(p) for p in ALL_PREFIXES}
        #: every listed key across all prefixes — existence checks
        #: answer from the listings already fetched instead of paying
        #: one store round-trip per key
        self.all_keys: set[str] = set().union(*self.keys.values())
        self._sidecars: dict[str, tuple] = {}
        self._journal_digests: dict[str, str] | None = None
        self._record_digests: dict[str, str] | None = None
        self._snapshots: list[tuple[str, dict]] | None = None

    def record_keys(self) -> list[str]:
        return [
            k for k in self.keys[REGISTRY_PREFIX]
            if k.startswith(REGISTRY_RECORDS_PREFIX)
        ]

    def sidecar(self, key: str):
        if key not in self._sidecars:
            self._sidecars[key] = read_sidecar(self.store, key)
        return self._sidecars[key]

    def journal_digests(self) -> dict[str, str]:
        """``{artefact key: digest}`` across every VALID journal's
        completed stages — independent write-time evidence that
        predates this subsystem's sidecars."""
        if self._journal_digests is None:
            out: dict[str, str] = {}
            for key in self.keys[RUNS_PREFIX]:
                doc = _json_doc(_get(self.store, key) or b"")
                if (
                    doc is None
                    or doc.get("schema") != "bodywork_tpu.run_journal/1"
                    or verify_doc(doc) is False
                ):
                    continue  # the runs/ checker reports it
                for entry in (doc.get("stages") or {}).values():
                    if entry.get("state") == "complete":
                        out.update(entry.get("artefacts") or {})
            self._journal_digests = out
        return self._journal_digests

    def record_digests(self) -> dict[str, str]:
        """``{model key: lineage digest}`` from every VALID registry
        record."""
        if self._record_digests is None:
            out = {}
            for key in self.record_keys():
                doc = _json_doc(_get(self.store, key) or b"")
                if (
                    doc is not None
                    and doc.get("schema") == "bodywork_tpu.registry_record/1"
                    and verify_doc(doc) is not False
                    and doc.get("model_key")
                    and doc.get("model_digest")
                ):
                    out[doc["model_key"]] = doc["model_digest"]
            self._record_digests = out
        return self._record_digests

    def snapshots(self) -> list[tuple[str, dict]]:
        """Every LOADABLE snapshot as ``(key, manifest)``, newest first
        — the dataset restore sources. Loading fully reads each
        artefact, which is the corruption check (zip CRC)."""
        if self._snapshots is None:
            import numpy as np

            out = []
            for key in reversed(self.keys[SNAPSHOTS_PREFIX]):
                raw = _get(self.store, key)
                if raw is None:
                    continue
                try:
                    with np.load(io.BytesIO(raw), allow_pickle=False) as npz:
                        manifest = json.loads(str(npz["manifest"][()]))
                        n_rows = sum(
                            e["rows"] for e in manifest["covered"]
                        )
                        if (
                            manifest.get("schema")
                            != "bodywork_tpu.history_snapshot/1"
                            or npz["X"].shape[0] != n_rows
                            or npz["y"].shape[0] != n_rows
                        ):
                            raise ValueError("manifest/array mismatch")
                except Exception:
                    continue  # the snapshots/ checker reports it
                out.append((key, manifest))
            self._snapshots = out
        return self._snapshots

    def snapshot_covered(self, key: str) -> bool:
        return any(
            any(e["key"] == key for e in manifest["covered"])
            for _k, manifest in self.snapshots()
        )

    def evidence(self, key: str) -> dict[str, str]:
        """Every self-valid write-time digest recorded for ``key``, by
        source. A source that is itself corrupt never testifies (its
        own checker reports it instead)."""
        out = {}
        doc, status = self.sidecar(key)
        if status == "ok":
            out["sidecar"] = doc["sha256"]
        digest = self.journal_digests().get(key)
        if digest:
            out["journal"] = digest
        if key.startswith(MODELS_PREFIX):
            digest = self.record_digests().get(key)
            if digest:
                out["record"] = digest
        return out


# -- per-prefix checkers ---------------------------------------------------


def _corruption_resolution(ctx: FsckContext, key: str):
    """``(severity, repair)`` for a corrupt/missing primary artefact —
    the repair-feasibility half of the taxonomy."""
    if key.startswith(DATASETS_PREFIX):
        if ctx.snapshot_covered(key):
            return "restorable", "restore_dataset"
        return "data_loss", None
    doc, status = ctx.sidecar(key)
    if status == "ok" and doc.get("replica"):
        return "restorable", "restore_replica"
    return "data_loss", None


def _check_digested_prefix(ctx: FsckContext, prefix: str) -> list[Finding]:
    """The shared scan for raw-byte-digested classes (datasets, models,
    both metrics families): re-hash each artefact against every
    write-time evidence source, then sweep for referenced-but-missing
    keys."""
    out = []
    present = set(ctx.keys[prefix])
    for key in ctx.keys[prefix]:
        data = _get(ctx.store, key)
        if data is None:
            continue
        actual = artefact_sha256(data)
        sources = ctx.evidence(key)
        if not sources:
            out.append(Finding(
                key, prefix, "undigested", "advisory",
                detail="no write-time digest recorded (pre-manifest "
                       "artefact); corruption here would be invisible",
                repair="backfill_digest",
            ))
            if not _csv_parses(data) and not key.startswith(MODELS_PREFIX):
                severity, repair = _corruption_resolution(ctx, key)
                out.append(Finding(
                    key, prefix, "unreadable", severity,
                    detail="undigested artefact fails structural parse",
                    repair=repair,
                ))
            continue
        if actual in sources.values():
            # healthy primary; a DISAGREEING stale source is that
            # source's defect, not the artefact's
            doc, status = ctx.sidecar(key)
            if status == "ok" and doc["sha256"] != actual:
                out.append(Finding(
                    audit_digest_key(key), AUDIT_PREFIX,
                    "stale_sidecar", "restorable",
                    detail=f"sidecar digest disagrees with a healthy "
                           f"{key!r} (journal/record evidence matches)",
                    repair="rebuild_sidecar",
                ))
            if (
                key.startswith(MODELS_PREFIX)
                and "record" in sources
                and sources["record"] != actual
            ):
                out.append(Finding(
                    key, prefix, "lineage_mismatch", "advisory",
                    detail="registry record digest is stale for a "
                           "checkpoint whose sidecar/journal evidence "
                           "matches",
                    repair="reregister_digest",
                ))
            continue
        severity, repair = _corruption_resolution(ctx, key)
        expected = sources.get("sidecar") or next(iter(sources.values()))
        out.append(Finding(
            key, prefix, "digest_mismatch", severity,
            detail=f"recorded {expected[:15]}… "
                   f"({'/'.join(sorted(sources))}) != actual "
                   f"{actual[:15]}…",
            repair=repair,
        ))
    # the reference graph: evidence for keys that no longer exist
    referenced = {
        k for k in ctx.journal_digests() if k.startswith(prefix)
    }
    referenced |= {
        audit_primary_key(s) for s in ctx.keys[AUDIT_PREFIX]
        if (audit_primary_key(s) or "").startswith(prefix)
    }
    if prefix == MODELS_PREFIX:
        referenced |= set(ctx.record_digests())
    for key in sorted(referenced - present):
        severity, repair = _corruption_resolution(ctx, key)
        out.append(Finding(
            key, prefix, "missing_artefact", severity,
            detail="referenced by journal/sidecar/record evidence but "
                   "absent from the store",
            repair=repair,
        ))
    return out


def _check_datasets(ctx: FsckContext) -> list[Finding]:
    return _check_digested_prefix(ctx, DATASETS_PREFIX)


def _check_models(ctx: FsckContext) -> list[Finding]:
    return _check_digested_prefix(ctx, MODELS_PREFIX)


def _check_model_metrics(ctx: FsckContext) -> list[Finding]:
    return _check_digested_prefix(ctx, MODEL_METRICS_PREFIX)


def _check_test_metrics(ctx: FsckContext) -> list[Finding]:
    return _check_digested_prefix(ctx, TEST_METRICS_PREFIX)


def _check_snapshots(ctx: FsckContext) -> list[Finding]:
    out = []
    loadable = {key for key, _m in ctx.snapshots()}
    dataset_keys = set(ctx.keys[DATASETS_PREFIX])
    for key in ctx.keys[SNAPSHOTS_PREFIX]:
        data = _get(ctx.store, key)
        if data is None:
            continue
        doc, status = ctx.sidecar(key)
        if status == "ok" and doc["sha256"] != artefact_sha256(data):
            # the raw-byte check: a flip in zip slack loads fine but is
            # still rot — derived state, so the resolution is the same
            # re-compaction as a structural failure
            out.append(Finding(
                key, SNAPSHOTS_PREFIX, "digest_mismatch", "rebuildable",
                detail="snapshot bytes no longer match the write-time "
                       "sidecar digest — re-compacted from the per-day "
                       "datasets",
                repair="rebuild_snapshot",
            ))
        elif key not in loadable:
            out.append(Finding(
                key, SNAPSHOTS_PREFIX, "unreadable", "rebuildable",
                detail="snapshot fails to load (zip CRC / manifest "
                       "validation) — derived state, re-compacted from "
                       "the per-day datasets",
                repair="rebuild_snapshot",
            ))
        elif status == "absent":
            out.append(Finding(
                key, SNAPSHOTS_PREFIX, "undigested", "advisory",
                detail="no write-time digest recorded (pre-manifest "
                       "snapshot); zip-slack rot here would be invisible",
                repair="backfill_digest",
            ))
    for key, manifest in ctx.snapshots():
        missing = [
            e["key"] for e in manifest["covered"]
            if e["key"] not in dataset_keys
        ]
        if missing:
            out.append(Finding(
                key, SNAPSHOTS_PREFIX, "missing_ref", "advisory",
                detail=f"manifest covers deleted dataset day(s) "
                       f"{missing[:3]} — stale, the compactor's next "
                       "write supersedes it",
            ))
    return out


def _check_trainstate(ctx: FsckContext) -> list[Finding]:
    out = []
    dataset_keys = set(ctx.keys[DATASETS_PREFIX])
    for key in ctx.keys[TRAINSTATE_PREFIX]:
        doc = _json_doc(_get(ctx.store, key) or b"")
        if not _trainstate_valid(doc):
            out.append(Finding(
                key, TRAINSTATE_PREFIX, "digest_mismatch", "rebuildable",
                detail="trainstate fails schema/payload-digest "
                       "validation — derived state, the next train run "
                       "rebuilds it from the datasets (one O(history) "
                       "refit, never a wrong model)",
                repair="drop_trainstate",
            ))
            continue
        from datetime import date as _date

        from bodywork_tpu.store.schema import dataset_key

        def _absent(day: str) -> bool:
            try:
                return dataset_key(_date.fromisoformat(day)) not in dataset_keys
            except ValueError:
                return True

        stale = [d for d in doc.get("days", {}) if _absent(d)]
        if stale:
            out.append(Finding(
                key, TRAINSTATE_PREFIX, "missing_ref", "advisory",
                detail=f"covers deleted dataset day(s) {stale[:3]}; "
                       "the next train run refolds from what exists",
            ))
    return out


def _check_runs(ctx: FsckContext) -> list[Finding]:
    out = []
    for key in ctx.keys[RUNS_PREFIX]:
        doc = _json_doc(_get(ctx.store, key) or b"")
        if (
            doc is None
            or doc.get("schema") != "bodywork_tpu.run_journal/1"
            or verify_doc(doc) is False
        ):
            out.append(Finding(
                key, RUNS_PREFIX, "unreadable", "rebuildable",
                detail="journal fails schema/doc-digest validation — "
                       "operational state; dropping it only forfeits "
                       "crash-resume for that day (the next run "
                       "re-executes and converges)",
                repair="drop_journal",
            ))
    return out


def _stale_registry_sidecar(ctx: FsckContext, key: str, data: bytes):
    """A HEALTHY (self-digest-valid) registry document whose sidecar
    records a different sha is carrying a stale replica — the crash
    window between the primary CAS and the sidecar write. Undetected,
    a later replica restore would silently roll the document back one
    write, so the scrub refreshes it from the (trustworthy) primary."""
    doc, status = ctx.sidecar(key)
    if status == "ok" and doc["sha256"] != artefact_sha256(data):
        return Finding(
            audit_digest_key(key), AUDIT_PREFIX, "stale_sidecar",
            "restorable",
            detail=f"sidecar replica lags the healthy {key!r} (a crash "
                   "between the CAS and the sidecar write); re-recorded "
                   "so a future restore cannot roll the document back",
            repair="rebuild_sidecar",
        )
    return None


def _check_registry(ctx: FsckContext) -> list[Finding]:
    out = []
    model_keys = set(ctx.keys[MODELS_PREFIX])
    for key in ctx.record_keys():
        raw = _get(ctx.store, key) or b""
        doc = _json_doc(raw)
        if (
            doc is not None
            and doc.get("schema") == "bodywork_tpu.registry_record/1"
            and verify_doc(doc) is not False
        ):
            stale = _stale_registry_sidecar(ctx, key, raw)
            if stale is not None:
                out.append(stale)
        if (
            doc is None
            or doc.get("schema") != "bodywork_tpu.registry_record/1"
            or verify_doc(doc) is False
        ):
            sidecar_doc, status = ctx.sidecar(key)
            restorable = status == "ok" and sidecar_doc.get("replica")
            out.append(Finding(
                key, REGISTRY_PREFIX, "unreadable",
                "restorable" if restorable else "data_loss",
                detail="record fails schema/doc-digest validation"
                       + ("" if restorable else
                          " and carries no sidecar replica — lineage "
                          "history lost"),
                repair="restore_replica" if restorable else None,
            ))
    # the alias document and its reference graph
    alias_raw = _get(ctx.store, REGISTRY_ALIAS_KEY)
    if alias_raw is not None:
        doc = _json_doc(alias_raw)
        if (
            doc is None
            or doc.get("schema") != "bodywork_tpu.registry_aliases/1"
            or verify_doc(doc) is False
        ):
            sidecar_doc, status = ctx.sidecar(REGISTRY_ALIAS_KEY)
            restorable = status == "ok" and sidecar_doc.get("replica")
            out.append(Finding(
                REGISTRY_ALIAS_KEY, REGISTRY_PREFIX, "unreadable",
                "restorable" if restorable else "data_loss",
                detail="alias document fails validation (serving "
                       "readers raise RegistryCorrupt)"
                       + ("; sidecar replica restores it — note the "
                          "replica may lag the last CAS by one write"
                          if restorable else
                          " and no sidecar replica survives"),
                repair="restore_replica" if restorable else None,
            ))
        else:
            stale = _stale_registry_sidecar(
                ctx, REGISTRY_ALIAS_KEY, alias_raw
            )
            if stale is not None:
                out.append(stale)
            production = doc.get("production")
            if production and production not in model_keys:
                out.append(Finding(
                    REGISTRY_ALIAS_KEY, REGISTRY_PREFIX, "dangling_alias",
                    "data_loss",
                    detail=f"production -> missing checkpoint "
                           f"{production!r}; NOT auto-repaired — "
                           "deciding what serves is an operator call "
                           "(rollback or promote; docs/RESILIENCE.md "
                           "§11 runbook)",
                ))
            previous = doc.get("previous")
            if previous and previous not in model_keys:
                out.append(Finding(
                    REGISTRY_ALIAS_KEY, REGISTRY_PREFIX, "dangling_alias",
                    "restorable",
                    detail=f"previous -> missing checkpoint "
                           f"{previous!r}; demoted (slot cleared in one "
                           "CAS) so a future rollback cannot land on it",
                    repair="clear_previous",
                ))
            canary = doc.get("canary")
            if canary and canary not in model_keys:
                out.append(Finding(
                    REGISTRY_ALIAS_KEY, REGISTRY_PREFIX, "dangling_alias",
                    "restorable",
                    detail=f"canary -> missing checkpoint {canary!r}; "
                           "repaired exactly like the reload watcher "
                           "would (one CAS + a canary_repaired event)",
                    repair="repair_canary",
                ))
    return out


def _check_audit(ctx: FsckContext) -> list[Finding]:
    out = []
    for sidecar_key in ctx.keys[AUDIT_PREFIX]:
        primary = audit_primary_key(sidecar_key)
        if primary is None:
            out.append(Finding(
                sidecar_key, AUDIT_PREFIX, "unexpected_key", "advisory",
                detail="not a well-formed digest-sidecar key",
            ))
            continue
        primary_exists = primary in ctx.all_keys
        doc, status = ctx.sidecar(primary)
        if status == "corrupt":
            out.append(Finding(
                sidecar_key, AUDIT_PREFIX, "unreadable",
                "restorable" if primary_exists else "advisory",
                detail="sidecar fails validation; "
                       + ("re-recorded from the primary bytes"
                          if primary_exists else "primary is gone too"),
                repair=(
                    "rebuild_sidecar" if primary_exists
                    else "drop_orphan_sidecar"
                ),
            ))
        elif not primary_exists:
            out.append(Finding(
                sidecar_key, AUDIT_PREFIX, "orphan_sidecar", "advisory",
                detail=f"primary {primary!r} no longer exists",
                repair="drop_orphan_sidecar",
            ))
    return out


def _check_flightrec(ctx: FsckContext) -> list[Finding]:
    """Flight-recorder dumps (``obs/tracing.py``): schema-tagged JSON
    with an embedded ``doc_digest`` plus (when written through an
    audited store) a raw-byte sidecar carrying a compressed replica —
    evidence with no producer to rebuild it, so the sidecar replica is
    the ONLY restore path and a dump rotting without one is data loss
    of forensics (reported, quarantined, never fabricated)."""
    from bodywork_tpu.obs.tracing import validate_flight_record

    out = []
    for key in ctx.keys[FLIGHTREC_PREFIX]:
        data = _get(ctx.store, key)
        if data is None:
            continue
        sidecar_doc, status = ctx.sidecar(key)
        valid = validate_flight_record(_json_doc(data))
        digest_ok = (
            status != "ok" or sidecar_doc["sha256"] == artefact_sha256(data)
        )
        if valid:
            if status == "absent":
                out.append(Finding(
                    key, FLIGHTREC_PREFIX, "undigested", "advisory",
                    detail="no write-time digest recorded (dump written "
                           "outside an audited store); whitespace rot "
                           "here would be invisible",
                    repair="backfill_digest",
                ))
            elif not digest_ok:
                # primary verifies its own embedded digest: the SIDECAR
                # is the stale half — restoring its replica would roll
                # the dump back, so re-record instead (registry rule)
                out.append(Finding(
                    audit_digest_key(key), AUDIT_PREFIX, "stale_sidecar",
                    "restorable",
                    detail=f"sidecar digest disagrees with a healthy "
                           f"{key!r} (doc digest verifies)",
                    repair="rebuild_sidecar",
                ))
            continue
        restorable = status == "ok" and sidecar_doc.get("replica")
        out.append(Finding(
            key, FLIGHTREC_PREFIX, "unreadable",
            "restorable" if restorable else "data_loss",
            detail="flight record fails schema/doc-digest validation"
                   + ("" if restorable else
                      " and no sidecar replica survives — the verdict's "
                      "forensic evidence is lost"),
            repair="restore_replica" if restorable else None,
        ))
    return out


def _check_tuning(ctx: FsckContext) -> list[Finding]:
    """Tuned serving-config documents (``tune/config.py``):
    schema-tagged JSON with an embedded ``doc_digest`` plus a raw-byte
    sidecar carrying a compressed replica. Rot is RESTORABLE while the
    replica survives; without one the document is merely rebuildable-
    by-deletion — serving already degrades to the built-in defaults on
    any validation failure, so dropping the corrupt document converges
    the store to the same state serving sees (re-running ``cli tune``
    re-fits it)."""
    from bodywork_tpu.registry.configlog import CONFIG_LOG_SCHEMA
    from bodywork_tpu.tune.config import TUNED_CONFIG_SCHEMA
    from bodywork_tpu.tune.costmodel import COST_MODEL_SCHEMA

    out = []
    for key in ctx.keys[TUNING_PREFIX]:
        data = _get(ctx.store, key)
        if data is None:
            continue
        sidecar_doc, status = ctx.sidecar(key)
        doc = _json_doc(data)
        # the tuning/ prefix holds three document kinds, dispatched by
        # basename: the config-lifecycle log (a live CAS pointer), the
        # learned cost model, and the tuned configs themselves —
        # validating a cost model against the tuned-config schema would
        # quarantine every healthy one
        basename = key.rsplit("/", 1)[-1]
        if basename == "config-log.json":
            expected_schema = CONFIG_LOG_SCHEMA
            shape_ok = doc is not None and isinstance(
                doc.get("history"), list
            )
        elif basename.startswith("cost-model-"):
            expected_schema = COST_MODEL_SCHEMA
            shape_ok = doc is not None and isinstance(
                doc.get("weights"), list
            )
        else:
            expected_schema = TUNED_CONFIG_SCHEMA
            shape_ok = doc is not None and (
                doc.get("knobs") is None or isinstance(doc["knobs"], dict)
            )
        # validity deliberately MATCHES the serving loader's integrity
        # checks (schema tag + doc_digest + top-level shape), NOT its
        # per-knob value validation: a digest-valid document holding a
        # knob this version rejects (or none at all) was WRITTEN that
        # way — e.g. an evidence-poor fit or a newer schema — and the
        # loader degrades per knob; fsck flagging it would restore-flap
        # (replica == primary) or quarantine a healthy document
        valid = (
            doc is not None
            and doc.get("schema") == expected_schema
            and verify_doc(doc) is not False
            and shape_ok
        )
        digest_ok = (
            status != "ok" or sidecar_doc["sha256"] == artefact_sha256(data)
        )
        if valid:
            if status == "absent":
                out.append(Finding(
                    key, TUNING_PREFIX, "undigested", "advisory",
                    detail="no write-time digest recorded (tuned config "
                           "written outside an audited store); "
                           "whitespace rot here would be invisible",
                    repair="backfill_digest",
                ))
            elif not digest_ok:
                # primary verifies its own embedded digest: the SIDECAR
                # is the stale half (registry rule) — re-record it
                out.append(Finding(
                    audit_digest_key(key), AUDIT_PREFIX, "stale_sidecar",
                    "restorable",
                    detail=f"sidecar digest disagrees with a healthy "
                           f"{key!r} (doc digest verifies)",
                    repair="rebuild_sidecar",
                ))
            continue
        restorable = status == "ok" and sidecar_doc.get("replica")
        out.append(Finding(
            key, TUNING_PREFIX, "unreadable",
            "restorable" if restorable else "rebuildable",
            detail="tuned config fails schema/doc-digest/knob validation"
                   + (" — restored from the sidecar replica"
                      if restorable else
                      " and no sidecar replica survives — derived "
                      "artefact: dropped (serving already degrades to "
                      "the built-in defaults; `cli tune` re-fits it)"),
            repair="restore_replica" if restorable else "drop_tuned_config",
        ))
    return out


def _check_quarantine(ctx: FsckContext) -> list[Finding]:
    out = []
    keys = set(ctx.keys[QUARANTINE_PREFIX])
    for key in sorted(keys):
        if key.endswith(QUARANTINE_META_SUFFIX):
            doc = _json_doc(_get(ctx.store, key) or b"")
            if doc is None or verify_doc(doc) is False:
                out.append(Finding(
                    key, QUARANTINE_PREFIX, "unreadable", "advisory",
                    detail="quarantine metadata fails validation; the "
                           "incident evidence is degraded",
                ))
            continue
        meta_key = key + QUARANTINE_META_SUFFIX
        if meta_key not in keys:
            out.append(Finding(
                key, QUARANTINE_PREFIX, "missing_ref", "advisory",
                detail="quarantined payload has no metadata document",
            ))
            continue
        meta = _json_doc(_get(ctx.store, meta_key) or b"")
        data = _get(ctx.store, key)
        if (
            meta is not None
            and verify_doc(meta) is not False
            and data is not None
            and meta.get("sha256")
            and artefact_sha256(data) != meta["sha256"]
        ):
            out.append(Finding(
                key, QUARANTINE_PREFIX, "digest_mismatch", "advisory",
                detail="quarantined payload no longer matches its "
                       "capture digest — the evidence itself rotted",
            ))
    return out


def _check_tenants(ctx: FsckContext) -> list[Finding]:
    """Tenant namespaces (``tenancy/namespace.py``): every
    ``tenants/<id>/`` subtree is a complete artefact store in
    miniature, so fsck RECURSES — a tenant-scoped view of the store is
    scanned with the same per-prefix checkers, and each finding
    resurfaces here under its tenant-qualified key. Repair actions are
    deliberately stripped from the recursed findings: the repair
    planner resolves restore evidence (snapshots, sidecars, journals)
    relative to its own store root, so repair must run IN tenant scope
    — ``cli fsck --tenant <id>`` — never from the root scan. Keys whose
    first segment fails tenant-id validation cannot have been written
    through a scoped store and are flagged as hygiene defects."""
    from bodywork_tpu.store.schema import validate_tenant_id
    from bodywork_tpu.tenancy.namespace import TenantStore, list_tenants

    out = []
    for tid in list_tenants(ctx.store):
        sub = FsckContext(TenantStore(ctx.store, tid))
        for prefix in ALL_PREFIXES:
            for f in CHECKERS[prefix](sub):
                out.append(dataclasses.replace(
                    f,
                    key=f"{TENANTS_PREFIX}{tid}/{f.key}",
                    prefix=TENANTS_PREFIX,
                    detail=(
                        f"[tenant {tid}] {f.detail}" if f.detail
                        else f"[tenant {tid}] repairable only in tenant "
                             f"scope: cli fsck --tenant {tid}"
                    ),
                    repair=None,
                ))
    for key in ctx.keys[TENANTS_PREFIX]:
        seg = key[len(TENANTS_PREFIX):].split("/", 1)[0]
        try:
            validate_tenant_id(seg)
        except ValueError:
            out.append(Finding(
                key, TENANTS_PREFIX, "invalid_tenant_id", "advisory",
                detail=f"first segment {seg!r} fails tenant-id "
                       "validation; no scoped store can have written "
                       "this key",
            ))
    return out


def _check_serve(ctx: FsckContext) -> list[Finding]:
    """Serving-plane operational state (``serve/leadership.py``): the
    dispatcher-leader lease document. Purely operational — it names the
    CURRENT leader, not any artefact — so every defect is rebuildable:
    the next election's CAS acquire repairs a corrupt document in
    place, and a deleted one merely forces a fresh election (fence
    restarts at 1, which clients accept — fences only guard against
    REGRESSION within a document's history)."""
    from bodywork_tpu.serve.leadership import LEADER_SCHEMA

    out = []
    for key in ctx.keys[SERVE_PREFIX]:
        doc = _json_doc(_get(ctx.store, key) or b"")
        if doc is None or doc.get("schema") != LEADER_SCHEMA:
            out.append(Finding(
                key, SERVE_PREFIX, "unreadable", "rebuildable",
                detail="serving-plane lease document fails validation; "
                       "operational state only — the next leadership "
                       "acquire CAS-repairs it in place (deleting it "
                       "just forces a fresh election)",
            ))
            continue
        if not isinstance(doc.get("fence"), int) or doc["fence"] < 0:
            out.append(Finding(
                key, SERVE_PREFIX, "unreadable", "rebuildable",
                detail="lease document carries no valid fence; the next "
                       "acquire rewrites it",
            ))
    return out


#: prefix -> auditor. Guard-pinned == schema.ALL_PREFIXES == the
#: docs/RESILIENCE.md §11 integrity table (tests/test_audit.py).
CHECKERS = {
    DATASETS_PREFIX: _check_datasets,
    MODELS_PREFIX: _check_models,
    MODEL_METRICS_PREFIX: _check_model_metrics,
    TEST_METRICS_PREFIX: _check_test_metrics,
    SNAPSHOTS_PREFIX: _check_snapshots,
    TRAINSTATE_PREFIX: _check_trainstate,
    RUNS_PREFIX: _check_runs,
    REGISTRY_PREFIX: _check_registry,
    TUNING_PREFIX: _check_tuning,
    AUDIT_PREFIX: _check_audit,
    QUARANTINE_PREFIX: _check_quarantine,
    FLIGHTREC_PREFIX: _check_flightrec,
    SERVE_PREFIX: _check_serve,
    TENANTS_PREFIX: _check_tenants,
}


def _count(name: str, help_text: str, **labels) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(name, help_text).inc(**labels)


def run_fsck(store: ArtefactStore, repair: bool = False) -> dict:
    """Scrub every prefix; optionally execute the safe repair subset.

    Returns the report document (schema
    ``bodywork_tpu.fsck_report/1``): per-prefix scan counts, every
    finding, repair outcomes, and the verdict pair ``clean`` (zero
    findings of any severity) / ``ok`` (zero ACTIONABLE findings left
    standing — with ``repair=True`` a finding whose repair succeeded no
    longer counts against it)."""
    ctx = FsckContext(store)
    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()
    for prefix in ALL_PREFIXES:
        _count(
            "bodywork_tpu_audit_scans_total",
            "Integrity-scrub prefix scans", prefix=prefix,
        )
        for finding in CHECKERS[prefix](ctx):
            if (finding.key, finding.problem) in seen:
                continue  # cross-checkers may converge on one defect
            seen.add((finding.key, finding.problem))
            findings.append(finding)
    for finding in findings:
        _count(
            "bodywork_tpu_audit_findings_total",
            "Integrity-scrub findings by prefix, severity, and problem",
            prefix=finding.prefix, severity=finding.severity,
            problem=finding.problem,
        )
        level = log.warning if finding.severity != "advisory" else log.info
        level(
            f"fsck {finding.severity}: {finding.problem} at "
            f"{finding.key} — {finding.detail}"
        )
    repairs: list[dict] = []
    if repair and findings:
        from bodywork_tpu.audit.repair import execute_repairs

        repairs = execute_repairs(ctx, findings)
        for entry in repairs:
            _count(
                "bodywork_tpu_audit_repairs_total",
                "Integrity-scrub repairs by prefix, action, and outcome",
                prefix=entry["prefix"], action=entry["action"],
                outcome=entry["outcome"],
            )
    repaired = {
        (r["key"], r["problem"]) for r in repairs
        if r["outcome"] == "repaired"
    }
    residual = [
        f for f in findings
        if f.severity in ACTIONABLE and (f.key, f.problem) not in repaired
    ]
    by_severity: dict[str, int] = {}
    for f in findings:
        by_severity[f.severity] = by_severity.get(f.severity, 0) + 1
    return {
        "schema": FSCK_REPORT_SCHEMA,
        "prefixes": {
            p: {"keys": len(ctx.keys[p])} for p in ALL_PREFIXES
        },
        "keys_scanned": sum(len(v) for v in ctx.keys.values()),
        "findings": [f.to_dict() for f in findings],
        "by_severity": by_severity,
        "repairs": repairs,
        "residual": [f.to_dict() for f in residual],
        "clean": not findings,
        "ok": not residual,
    }
