"""Write-time digest manifest: sidecar integrity records under ``audit/``.

Every durability layer shipped before this subsystem verifies *lazily at
read time* — an artefact nobody reads stays unverified forever. The
manifest closes the first half of that gap: for artefact classes that do
not already carry a content digest (per-day dataset CSVs, model
checkpoints, the two metrics CSV families, registry documents), a
sidecar JSON record is written alongside every write at
``audit/digests/<key>.json`` (:func:`bodywork_tpu.store.schema.audit_digest_key`)
recording the primary artefact's sha256 and size. The integrity
scrubber (:mod:`bodywork_tpu.audit.fsck`) re-hashes primaries against
these records on a schedule, so silent at-rest corruption of a COLD
artefact is found by the scrub loop, not by the rollback or rebuild
that lands on it months later.

For small classes with no other redundancy (checkpoints, metrics CSVs,
registry records, the alias document) the sidecar additionally embeds a
zlib-compressed REPLICA of the primary bytes — the redundancy the fsck
repair planner restores from, digest-verified, when the primary rots.
Dataset CSVs deliberately carry no replica: their redundancy is the
consolidated history snapshot (``data/snapshot.py``), and duplicating
the largest artefact class would double the store. Snapshots get a
digest sidecar but no replica — they are derived (rebuildable from
datasets), and while they partially self-validate (zip CRC + manifest
row counts), a byte flip landing in zip slack can be structurally
harmless, so only the raw-byte digest makes EVERY flip detectable.

Sidecar documents are DETERMINISTIC functions of the primary bytes
(canonical JSON, no wall clock, fixed zlib level), so the chaos
byte-identity guarantee extends over ``audit/digests/`` — with two
exceptions excluded from twin comparison
(``chaos.sim._COMPARE_EXCLUDED``): ``test-metrics/`` bytes embed a
wall-clock column and ``snapshots/`` bytes embed backend version
tokens, so those classes' sidecars legitimately differ between twins.

:class:`AuditedStore` is the transparent :class:`DelegatingStore`
wrapper that records sidecars on the write path; ``store.open_store``
installs it over every backend, so all CLI entrypoints and k8s pods
write the manifest without any stage knowing it exists.
"""
from __future__ import annotations

import base64
import json
import zlib

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, DelegatingStore
from bodywork_tpu.store.schema import (
    AUDIT_PREFIX,
    DATASETS_PREFIX,
    FLIGHTREC_PREFIX,
    MODEL_METRICS_PREFIX,
    MODELS_PREFIX,
    REGISTRY_PREFIX,
    SNAPSHOTS_PREFIX,
    TEST_METRICS_PREFIX,
    TUNING_PREFIX,
    audit_digest_key,
)
from bodywork_tpu.utils.integrity import sha256_digest, stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("audit.manifest")

DIGEST_SCHEMA = "bodywork_tpu.audit_digest/1"

#: classes whose raw ``put_bytes`` writes get a digest sidecar — the
#: artefact classes that carry no (complete) content digest of their
#: own: datasets, checkpoints, both metrics families, and snapshots
#: (whose zip CRC misses flips in structural slack)
PUT_SIDECAR_PREFIXES = (
    DATASETS_PREFIX,
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    TEST_METRICS_PREFIX,
    SNAPSHOTS_PREFIX,
    # flight-recorder dumps are one-shot evidence with no producer to
    # rebuild them — the sidecar (with replica, below) is their only
    # redundancy against at-rest rot
    FLIGHTREC_PREFIX,
    # tuned serving configs (tune/config.py): the traces they were
    # fitted from may be gone by scrub time, so the sidecar replica is
    # what makes at-rest rot restorable instead of a silent revert to
    # the hand-set defaults
    TUNING_PREFIX,
)

#: CAS-mutated classes that also get a sidecar, written after each
#: successful ``put_bytes_if_match`` (registry records + the alias
#: document, plus the tuning config-lifecycle log — a live CAS pointer
#: exactly like the alias doc, so the same stale-by-one-write rules
#: apply; journals are deliberately excluded — their bytes embed
#: lease wall-clocks, so sidecars would break the chaos twin
#: comparison, and they already embed a ``doc_digest``)
CAS_SIDECAR_PREFIXES = (REGISTRY_PREFIX, TUNING_PREFIX)

#: subset whose sidecars embed a compressed replica (small artefacts
#: with no other redundancy; datasets restore from snapshots instead)
REPLICA_PREFIXES = (
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    TEST_METRICS_PREFIX,
    REGISTRY_PREFIX,
    # dumps are ring-buffer bounded (a few hundred KB at most), so the
    # compressed replica is cheap insurance for unrebuildable evidence
    FLIGHTREC_PREFIX,
    # tuned configs are a few KB of knobs + decision trace
    TUNING_PREFIX,
)

#: fixed zlib level: replica bytes must be deterministic across
#: processes and platforms for the chaos twin comparison
_ZLIB_LEVEL = 6

__all__ = [
    "AuditedStore",
    "CAS_SIDECAR_PREFIXES",
    "DIGEST_SCHEMA",
    "PUT_SIDECAR_PREFIXES",
    "REPLICA_PREFIXES",
    "artefact_sha256",
    "read_sidecar",
    "replica_bytes",
    "sidecar_covered",
    "sidecar_doc",
    "write_sidecar",
]


def artefact_sha256(data: bytes) -> str:
    """Raw-byte content digest — the shared ``sha256:`` form
    (``utils.integrity.sha256_digest``) the run journal and registry
    lineage also delegate to, so evidence from all three sources
    cross-checks directly."""
    return sha256_digest(data)


def sidecar_covered(key: str) -> bool:
    """True when writes to ``key`` should record a digest sidecar."""
    return key.startswith(PUT_SIDECAR_PREFIXES + CAS_SIDECAR_PREFIXES) and (
        not key.startswith(AUDIT_PREFIX)
    )


def sidecar_doc(key: str, data: bytes) -> dict:
    doc = {
        "schema": DIGEST_SCHEMA,
        "key": key,
        "sha256": artefact_sha256(data),
        "size": len(data),
    }
    if key.startswith(REPLICA_PREFIXES):
        doc["replica_codec"] = "zlib+b64"
        doc["replica"] = base64.b64encode(
            zlib.compress(data, _ZLIB_LEVEL)
        ).decode("ascii")
    return stamp_doc(doc)


def write_sidecar(store: ArtefactStore, key: str, data: bytes) -> str:
    """Record (or refresh) the digest sidecar for ``key`` holding
    ``data``. Plain overwrite, not CAS: the sidecar is a pure function
    of the primary bytes, so concurrent writers racing on the primary
    converge on the sidecar too."""
    sidecar = audit_digest_key(key)
    store.put_bytes(
        sidecar,
        json.dumps(
            sidecar_doc(key, data), sort_keys=True, indent=1
        ).encode("utf-8"),
    )
    return sidecar


def read_sidecar(store: ArtefactStore, key: str):
    """``(doc_or_None, status)`` for ``key``'s sidecar: status is
    ``"ok"``, ``"absent"``, or ``"corrupt"`` (unparseable, wrong
    schema/key, or failed its own embedded digest)."""
    try:
        raw = store.get_bytes(audit_digest_key(key))
    except ArtefactNotFound:
        return None, "absent"
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None, "corrupt"
    if (
        not isinstance(doc, dict)
        or doc.get("schema") != DIGEST_SCHEMA
        or doc.get("key") != key
        or verify_doc(doc) is False
    ):
        return None, "corrupt"
    return doc, "ok"


def replica_bytes(doc: dict) -> bytes | None:
    """The replica payload carried by a valid sidecar doc, verified
    against the doc's own recorded digest — or None when the sidecar
    carries no replica or the decoded bytes do not hash to the recorded
    digest (a lying replica must never be restored)."""
    blob = doc.get("replica")
    if not blob or doc.get("replica_codec") != "zlib+b64":
        return None
    try:
        data = zlib.decompress(base64.b64decode(blob))
    except (ValueError, zlib.error):
        return None
    if artefact_sha256(data) != doc.get("sha256"):
        return None
    return data


class AuditedStore(DelegatingStore):
    """Transparent wrapper recording write-time digest sidecars.

    Sits OUTERMOST in the store composition (``open_store`` installs
    it), so the sidecar write rides the same resilience/chaos stack as
    the primary write it records. The primary write always lands first
    — never the reverse order, where a sidecar could describe bytes
    that were never written. A crash between the two leaves either a
    MISSING sidecar (first write of a key: the scrubber reports an
    advisory ``undigested`` finding and backfills) or a STALE one (an
    overwrite of an existing key). For journaled flows the run
    journal's digest arbitrates the stale case (the scrub trusts the
    primary and refreshes the sidecar); for a NON-journaled overwrite
    (a standalone ``cli train`` rerun) no independent evidence
    survives, so the scrub sides with the recorded evidence and may
    restore the prior write — the state the registry ledger last knew,
    since the crash also preceded re-registration. The next producer
    run converges either way.
    """

    def put_bytes(self, key: str, data: bytes) -> None:
        self._inner.put_bytes(key, data)
        if key.startswith(PUT_SIDECAR_PREFIXES):
            write_sidecar(self._inner, key, data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        token = self._inner.put_bytes_if_match(key, data, expected_token)
        if key.startswith(CAS_SIDECAR_PREFIXES):
            write_sidecar(self._inner, key, data)
        return token

    def delete(self, key: str) -> None:
        self._inner.delete(key)
        if sidecar_covered(key):
            try:
                self._inner.delete(audit_digest_key(key))
            except ArtefactNotFound:
                pass  # never recorded (pre-manifest artefact)
