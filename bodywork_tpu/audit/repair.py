"""The fsck repair planner: execute the SAFE subset, quarantine the rest.

Given a scan's findings (:mod:`bodywork_tpu.audit.fsck`), this module
applies every repair whose result can be trusted, in an order that
respects the store's dependency graph — restore the source-of-truth
classes first (dataset days from snapshot slices, replicas for
checkpoints/metrics/registry documents), then re-record derived
evidence (sidecars), then rebuild derived artefacts (snapshots — whose
re-compaction READS the freshly restored datasets), with
drop-and-rebuild classes (trainstate, journals) and alias demotions in
between. Three invariants:

1. **Corrupt bytes are never destroyed.** Before any overwrite or
   delete, the current bytes move to ``quarantine/<original key>`` with
   a metadata document — written through the CAS primitive, never
   deleted by the framework (retention is an operator decision).
2. **Restores are digest-verified.** A dataset rebuilt from a snapshot
   slice or a replica inflated from a sidecar is hashed against the
   recorded write-time digest BEFORE it lands; a mismatch fails the
   repair (outcome ``failed``) rather than writing unverified bytes.
3. **Data loss is reported, not repaired.** Findings with no surviving
   redundancy are quarantined (copy only — the damaged original stays
   in place, partially readable is better than gone) and surface in
   the report and metrics; nothing fabricates data.
"""
from __future__ import annotations

import json

from bodywork_tpu.audit.manifest import (
    artefact_sha256,
    read_sidecar,
    replica_bytes,
    write_sidecar,
)
from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, CasConflict
from bodywork_tpu.store.schema import (
    QUARANTINE_META_SUFFIX,
    REGISTRY_PREFIX,
    SNAPSHOTS_PREFIX,
    quarantine_key,
)
from bodywork_tpu.utils.dates import date_from_key
from bodywork_tpu.utils.integrity import stamp_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("audit.repair")

QUARANTINE_SCHEMA = "bodywork_tpu.quarantine/1"

#: execution order (module docstring): sources of truth first, derived
#: evidence next, derived artefacts last. ``None``-repair (data-loss)
#: findings are quarantine-only and run first of all.
REPAIR_ORDER = (
    "restore_dataset",
    "restore_replica",
    "rebuild_sidecar",
    "reregister_digest",
    "backfill_digest",
    "drop_orphan_sidecar",
    "drop_trainstate",
    "drop_journal",
    "drop_tuned_config",
    "clear_previous",
    "repair_canary",
    "rebuild_snapshot",
)

__all__ = ["REPAIR_ORDER", "QUARANTINE_SCHEMA", "execute_repairs", "quarantine"]


def _cas_put(store: ArtefactStore, key: str, data: bytes) -> None:
    """Create-or-overwrite through the CAS primitive (the discipline
    every mutable document class already rides): create-only first, and
    on conflict a conditional overwrite against the current token."""
    try:
        store.put_bytes_if_match(key, data, None)
    except CasConflict:
        store.put_bytes_if_match(key, data, store.version_token(key))


#: repeat-incident cap per key: each new quarantine of an already-
#: quarantined key takes the next free ``.N`` suffix instead of
#: overwriting prior evidence; past the cap the oldest contract wins
#: and the new incident is refused (a hot-looping repair must not grow
#: the store unboundedly)
_QUARANTINE_INCIDENT_CAP = 16


def quarantine(store: ArtefactStore, key: str, problem: str) -> bool:
    """Park ``key``'s current bytes at ``quarantine/<key>`` (or the
    next free ``.N``-suffixed slot for a repeat incident — quarantine
    entries are EVIDENCE and the framework never overwrites or deletes
    them) with a metadata document. Returns False when the key no
    longer exists (nothing to park). Never deletes the original —
    callers that replace or drop the primary do so themselves AFTER
    this returns."""
    try:
        data = store.get_bytes(key)
    except ArtefactNotFound:
        return False
    meta = stamp_doc({
        "schema": QUARANTINE_SCHEMA,
        "key": key,
        "problem": problem,
        "sha256": artefact_sha256(data),
        "size": len(data),
    })
    meta_bytes = json.dumps(meta, sort_keys=True, indent=1).encode("utf-8")
    base = quarantine_key(key)
    for n in range(_QUARANTINE_INCIDENT_CAP):
        slot = base if n == 0 else f"{base}.{n + 1}"
        try:
            store.put_bytes_if_match(slot, data, None)  # create-only
        except CasConflict:
            if store.get_bytes(slot) == data:
                return True  # same incident re-scrubbed: already parked
            continue  # a PRIOR incident holds the slot: next suffix
        _cas_put(store, slot + QUARANTINE_META_SUFFIX, meta_bytes)
        log.warning(
            f"quarantined {key} ({problem}, {len(data)} bytes) -> {slot}"
        )
        return True
    log.error(
        f"quarantine of {key} refused: {_QUARANTINE_INCIDENT_CAP} prior "
        "incidents already parked (evidence is never overwritten)"
    )
    return False


def _expected_digest(ctx, key: str) -> str | None:
    sources = ctx.evidence(key)
    return sources.get("sidecar") or next(iter(sources.values()), None)


def _snapshot_arrays(ctx, snap_key: str):
    """One full load per snapshot per scrub, however many dataset days
    restore from it (a multi-day rot would otherwise re-download and
    re-decompress the same artefact once per finding)."""
    import io as _io

    import numpy as np

    cache = ctx.__dict__.setdefault("_snapshot_arrays", {})
    if snap_key not in cache:
        raw = ctx.store.get_bytes(snap_key)
        with np.load(_io.BytesIO(raw), allow_pickle=False) as npz:
            cache[snap_key] = (npz["X"], npz["y"])
    return cache[snap_key]


def _restore_dataset(ctx, finding) -> tuple[str, str]:
    """Rebuild one dataset day from the newest loadable snapshot slice
    covering it, digest-verified against the write-time record. The
    CSV writer is the same deterministic ``Dataset.to_dataframe``
    round-trip that produced the original, so a healthy slice
    reproduces the original bytes exactly."""
    import io as _io

    from bodywork_tpu.data.io import Dataset

    expected = _expected_digest(ctx, finding.key)
    for snap_key, manifest in ctx.snapshots():
        entries = manifest["covered"]
        if not any(e["key"] == finding.key for e in entries):
            continue
        X, y = _snapshot_arrays(ctx, snap_key)
        offset = 0
        for entry in entries:
            if entry["key"] == finding.key:
                ds = Dataset(
                    X[offset:offset + entry["rows"]],
                    y[offset:offset + entry["rows"]],
                    date_from_key(finding.key),
                )
                buf = _io.StringIO()
                ds.to_dataframe().to_csv(buf, header=True, index=False)
                data = buf.getvalue().encode("utf-8")
                if expected is not None and artefact_sha256(data) != expected:
                    continue  # stale slice: try an older snapshot
                quarantine(ctx.store, finding.key, finding.problem)
                ctx.store.put_bytes(finding.key, data)
                return "repaired", f"restored from {snap_key}"
            offset += entry["rows"]
    return "failed", "no snapshot slice reproduces the recorded digest"


def _registry_doc_valid(data: bytes) -> bool:
    from bodywork_tpu.utils.integrity import verify_doc

    try:
        doc = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return False
    return isinstance(doc, dict) and verify_doc(doc) is not False


def _restore_replica(ctx, finding) -> tuple[str, str]:
    doc, status = read_sidecar(ctx.store, finding.key)
    if status != "ok":
        return "failed", "sidecar no longer readable"
    data = replica_bytes(doc)
    if data is None:
        return "failed", "sidecar replica missing or fails its digest"
    if finding.key.startswith(REGISTRY_PREFIX):
        # registry documents are mutated EXCLUSIVELY through the CAS
        # primitive, and a CONCURRENT writer (a promote, the SLO
        # watchdog) may have already replaced the corrupt bytes with a
        # fresh valid document between the scan and this repair: re-read
        # under a token, confirm the corruption still stands, and CAS
        # against exactly that token — losing the race fails cleanly
        # (re-run fsck) instead of overwriting a healthy newer write
        # with the stale replica
        token = ctx.store.version_token(finding.key)
        try:
            current = ctx.store.get_bytes(finding.key)
        except ArtefactNotFound:
            current = None
        if current is not None and _registry_doc_valid(current):
            return "repaired", (
                "no longer corrupt (a concurrent writer already "
                "replaced the document); nothing restored"
            )
        quarantine(ctx.store, finding.key, finding.problem)
        try:
            ctx.store.put_bytes_if_match(finding.key, data, token)
        except CasConflict:
            return "failed", "lost the alias/record race; re-run fsck"
    else:
        quarantine(ctx.store, finding.key, finding.problem)
        ctx.store.put_bytes(finding.key, data)
    return "repaired", f"restored {len(data)} bytes from sidecar replica"


def _rebuild_sidecar(ctx, finding) -> tuple[str, str]:
    from bodywork_tpu.store.schema import audit_primary_key

    primary = audit_primary_key(finding.key)
    if primary is None:
        return "failed", "not a sidecar key"
    try:
        data = ctx.store.get_bytes(primary)
    except ArtefactNotFound:
        return "failed", f"primary {primary!r} vanished"
    journal_digest = ctx.journal_digests().get(primary)
    if journal_digest is not None and artefact_sha256(data) != journal_digest:
        return "failed", (
            "primary bytes fail the journal digest; re-recording would "
            "bless corruption"
        )
    if primary.startswith(REGISTRY_PREFIX) and not _registry_doc_valid(data):
        # registry primaries carry their own doc_digest: a sidecar must
        # never be re-recorded from a primary that fails it (both
        # halves rotted = data loss, not a refresh)
        return "failed", (
            "registry primary fails its own doc digest; re-recording "
            "would bless corruption"
        )
    quarantine(ctx.store, finding.key, finding.problem)
    write_sidecar(ctx.store, primary, data)
    detail = "re-recorded from primary bytes"
    if journal_digest is None:
        detail += " (no independent evidence; digest re-registered as-is)"
    return "repaired", detail


def _reregister_digest(ctx, finding) -> tuple[str, str]:
    from bodywork_tpu.registry import records as rec

    try:
        data = ctx.store.get_bytes(finding.key)
    except ArtefactNotFound:
        return "failed", "checkpoint vanished"
    digest = artefact_sha256(data)

    def _mutate(record):
        if record is None:
            return None
        record["model_digest"] = digest
        record["history"].append(
            {"event": "digest_reregistered", "day": None,
             "reason": "fsck: record digest was stale for a verified "
                       "checkpoint"}
        )
        return record

    written = rec.update_record(ctx.store, finding.key, _mutate)
    if written is None:
        return "failed", "record unreadable"
    return "repaired", f"record digest re-registered as {digest[:15]}…"


def _backfill_digest(ctx, finding) -> tuple[str, str]:
    try:
        data = ctx.store.get_bytes(finding.key)
    except ArtefactNotFound:
        return "failed", "artefact vanished"
    write_sidecar(ctx.store, finding.key, data)
    return "repaired", "write-time digest recorded (future scrubs can " \
                       "now see corruption here)"


def _drop_orphan_sidecar(ctx, finding) -> tuple[str, str]:
    try:
        ctx.store.delete(finding.key)
    except ArtefactNotFound:
        pass
    return "repaired", "orphan sidecar removed"


def _drop_and_quarantine(ctx, finding) -> tuple[str, str]:
    quarantine(ctx.store, finding.key, finding.problem)
    try:
        ctx.store.delete(finding.key)
    except ArtefactNotFound:
        pass
    return "repaired", "quarantined and dropped (derived/operational " \
                       "state; rebuilt by its producer's next run)"


def _clear_previous(ctx, finding) -> tuple[str, str]:
    from bodywork_tpu.registry import records as rec

    doc, token = rec.read_aliases(ctx.store, with_token=True)
    if doc is None or not doc.get("previous"):
        return "repaired", "slot already clear"
    new_doc = {
        **doc,
        "previous": None,
        "rev": doc.get("rev", 0) + 1,
        "last_op": "fsck_clear_previous",
    }
    try:
        rec.write_aliases(ctx.store, new_doc, token)
    except CasConflict:
        return "failed", "lost the alias race; re-run fsck"
    return "repaired", "dangling previous slot demoted (one CAS)"


def _repair_canary(ctx, finding) -> tuple[str, str]:
    from bodywork_tpu.registry.manager import ModelRegistry

    doc = ModelRegistry(ctx.store).canary_repair(
        reason="fsck: canary slot points at a missing checkpoint"
    )
    return "repaired", (
        "dangling canary slot cleared" if doc is not None
        else "slot already clear"
    )


def execute_repairs(ctx, findings) -> list[dict]:
    """Apply the safe repair subset in :data:`REPAIR_ORDER`; quarantine
    (copy-only) every data-loss finding. Returns one outcome entry per
    finding handled: ``{key, prefix, problem, action, outcome, detail}``
    with outcome ``repaired`` / ``failed`` / ``quarantined``."""
    handlers = {
        "restore_dataset": _restore_dataset,
        "restore_replica": _restore_replica,
        "rebuild_sidecar": _rebuild_sidecar,
        "reregister_digest": _reregister_digest,
        "backfill_digest": _backfill_digest,
        "drop_orphan_sidecar": _drop_orphan_sidecar,
        "drop_trainstate": _drop_and_quarantine,
        "drop_journal": _drop_and_quarantine,
        # a replica-less corrupt tuned config: serving already degrades
        # to the built-in defaults on it, so dropping converges the
        # store to what serving sees (`cli tune` re-fits it)
        "drop_tuned_config": _drop_and_quarantine,
        "clear_previous": _clear_previous,
        "repair_canary": _repair_canary,
    }
    out: list[dict] = []

    def _entry(finding, action, outcome, detail):
        out.append({
            "key": finding.key, "prefix": finding.prefix,
            "problem": finding.problem, "action": action,
            "outcome": outcome, "detail": detail,
        })
        level = log.info if outcome == "repaired" else log.warning
        level(f"fsck repair {action} {finding.key}: {outcome} — {detail}")

    # data loss first: park the evidence, change nothing
    for finding in findings:
        if finding.repair is None and finding.severity == "data_loss":
            parked = quarantine(ctx.store, finding.key, finding.problem)
            _entry(
                finding, "quarantine", "quarantined",
                "corrupt bytes copied to quarantine/ (original left in "
                "place)" if parked else "key absent; nothing to park",
            )
    rebuild_snapshots = [
        f for f in findings if f.repair == "rebuild_snapshot"
    ]
    for action in REPAIR_ORDER:
        if action == "rebuild_snapshot":
            continue  # batched below
        for finding in findings:
            if finding.repair != action:
                continue
            try:
                outcome, detail = handlers[action](ctx, finding)
            except Exception as exc:  # noqa: BLE001 — a repair must
                # never abort the scrub; the finding stays residual
                outcome, detail = "failed", repr(exc)
            _entry(finding, action, outcome, detail)
    if rebuild_snapshots:
        # drop every corrupt snapshot, then ONE re-compaction over the
        # (now restored) datasets rebuilds coverage
        from bodywork_tpu.data.snapshot import write_snapshot

        for finding in rebuild_snapshots:
            if finding.key.startswith(SNAPSHOTS_PREFIX):
                quarantine(ctx.store, finding.key, finding.problem)
                try:
                    ctx.store.delete(finding.key)
                except ArtefactNotFound:
                    pass
        try:
            written = write_snapshot(ctx.store)
            outcome = "repaired" if written else "failed"
            detail = (
                f"re-compacted to {written}" if written
                else "nothing consolidatable"
            )
        except Exception as exc:  # noqa: BLE001
            outcome, detail = "failed", repr(exc)
        for finding in rebuild_snapshots:
            _entry(finding, "rebuild_snapshot", outcome, detail)
    return out
