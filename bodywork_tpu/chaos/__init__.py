"""Deterministic fault-injection harness (docs/RESILIENCE.md).

Failure as a first-class, testable input: a seeded
:class:`~bodywork_tpu.chaos.plan.FaultPlan` drives a transparent
:class:`~bodywork_tpu.chaos.store.FaultInjectingStore` wrapper and a
flaky scoring-service mode, and
:func:`~bodywork_tpu.chaos.sim.run_chaos_sim` proves the resilience
layer (``utils/retry.py`` + ``store/resilient.py`` + degraded-mode
serving) by requiring a faulted multi-day simulation to produce final
artefacts byte-identical to a fault-free twin. CLI:
``python -m bodywork_tpu.cli chaos run-sim --seed N --days D --store DIR``.
"""
from bodywork_tpu.chaos.plan import (
    FaultPlan,
    InjectedFault,
    activate,
    get_active_plan,
)
from bodywork_tpu.chaos.store import FaultInjectingStore
from bodywork_tpu.chaos.http import FlakyScoringMiddleware, flaky_serve_stage
from bodywork_tpu.chaos.kill import (
    KillSwitch,
    SimulatedCrash,
    arm_from_env,
    hit_kill_point,
)
from bodywork_tpu.chaos.canary import (
    CANARY_SCENARIOS,
    run_canary_chaos,
    sabotage_checkpoint_nan,
)
from bodywork_tpu.chaos.bitrot import inject_bit_rot, run_bit_rot_sim
from bodywork_tpu.chaos.sim import (
    chaos_pipeline_spec,
    compare_stores,
    run_chaos_sim,
    run_crash_sim,
    sweep_points,
)

__all__ = [
    "CANARY_SCENARIOS",
    "FaultPlan",
    "InjectedFault",
    "run_canary_chaos",
    "sabotage_checkpoint_nan",
    "KillSwitch",
    "SimulatedCrash",
    "activate",
    "arm_from_env",
    "get_active_plan",
    "hit_kill_point",
    "FaultInjectingStore",
    "FlakyScoringMiddleware",
    "flaky_serve_stage",
    "chaos_pipeline_spec",
    "compare_stores",
    "inject_bit_rot",
    "run_bit_rot_sim",
    "run_chaos_sim",
    "run_crash_sim",
    "sweep_points",
]
