"""At-rest bit-rot chaos: silent disk corruption as a seeded, swept input.

PR 4 made *in-flight* failure deterministic (faults injected at store
ops); the crash soak made *process death* deterministic. This module
covers the last silent failure mode: bytes rotting ON DISK while nobody
reads them. The injector flips seeded bytes inside a finished store's
artefacts **in place, with file timestamps preserved**, so no store op
ever fires, no version token changes, and no read-time validator is
consulted — the corruption is invisible to every lazy check in the
system. The only thing that can find it is the integrity scrub
(:mod:`bodywork_tpu.audit.fsck`), which is exactly the point.

``run_bit_rot_sim`` (``cli chaos run-sim --bit-rot``) is the acceptance
harness: run the same N-day simulation into two audited stores (the
twins are byte-identical by the determinism the chaos soak already
proves), rot seeded keys across EVERY populated prefix of one twin,
then require

1. **100% detection**: every injected corruption surfaces as a
   non-advisory fsck finding, classified by the severity taxonomy;
2. **self-healing convergence**: ``run_fsck(repair=True)`` leaves the
   victim byte-identical to the healthy twin outside ``quarantine/``
   (and the journal/snapshot operational checks still pass);
3. **zero silent passes**: a post-repair scrub reports no actionable
   findings.

Injection rules that keep the sweep honest rather than unwinnable:

- flips land on non-whitespace bytes (a whitespace-to-whitespace flip
  inside a canonically-digested JSON document changes no content — it
  would be injecting nothing);
- a rotted key PROTECTS its redundancy partner (a primary protects its
  digest sidecar and vice versa; a rotted dataset day protects the
  latest snapshot it restores from; a rotted latest snapshot protects
  the dataset days only it could restore) — rotting both halves of a
  redundancy pair is engineering data loss on purpose, which the
  taxonomy already covers and the convergence bar cannot;
- every populated prefix gets at least one rotted key (seeded forced
  pick) so a sweep exercises every auditor, not just the lucky ones.
"""
from __future__ import annotations

import os
import random
from datetime import date
from pathlib import Path

from bodywork_tpu.chaos.plan import FaultPlan
from bodywork_tpu.store.filesystem import FilesystemStore
from bodywork_tpu.store.schema import (
    ALL_PREFIXES,
    DATASETS_PREFIX,
    SNAPSHOTS_PREFIX,
    TRAINSTATE_PREFIX,
    audit_digest_key,
    audit_primary_key,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.bitrot")

__all__ = ["inject_bit_rot", "run_bit_rot_sim"]

_WHITESPACE = b" \t\r\n"


def _flip_bytes(root: Path, key: str, plan: FaultPlan) -> list[int] | None:
    """Apply seeded in-place byte flips to ``root/key``, preserving the
    file's timestamps (true bit rot does not touch mtime, so version
    tokens — and therefore every token-keyed cache and staleness check
    — keep trusting the artefact). Returns the flipped positions, or
    None when the file holds no flippable byte."""
    path = root / key
    data = path.read_bytes()
    eligible = [i for i, b in enumerate(data) if b not in _WHITESPACE]
    if not eligible:
        return None
    rng = random.Random(f"{plan.seed}|bit_rot_bytes|{key}")
    n = 1 + rng.randrange(plan.bit_rot_max_flips)
    positions = sorted(rng.sample(eligible, min(n, len(eligible))))
    st = path.stat()
    with open(path, "r+b") as f:
        for pos in positions:
            f.seek(pos)
            f.write(bytes([data[pos] ^ rng.randrange(1, 256)]))
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
    return positions


def _protect_partners(
    key: str, protected: set, store: FilesystemStore
) -> None:
    """Mark the redundancy partners a rotted ``key`` must leave intact
    (module docstring): its sidecar/primary, and across the
    dataset <-> snapshot restore axis."""
    protected.add(audit_digest_key(key))
    primary = audit_primary_key(key)
    if primary is not None:
        protected.add(primary)
    if key.startswith(DATASETS_PREFIX):
        hist = store.history(SNAPSHOTS_PREFIX)
        if hist:
            protected.add(hist[-1][0])  # the restore source
    if key.startswith(SNAPSHOTS_PREFIX):
        hist = store.history(SNAPSHOTS_PREFIX)
        if hist and key == hist[-1][0]:
            # rotting the LATEST snapshot: the older kept one may not
            # cover the newest day, so dataset rot is now off the table
            protected.update(store.list_keys(DATASETS_PREFIX))


def inject_bit_rot(
    store: FilesystemStore,
    plan: FaultPlan,
    ensure_per_prefix: bool = True,
) -> list[dict]:
    """Seeded at-rest corruption sweep over ``store`` (module
    docstring). Returns one entry per rotted key:
    ``{"key", "prefix", "positions"}``."""
    root = Path(store.root)
    protected: set[str] = set()
    injected: list[dict] = []

    def _rot(key: str, prefix: str, forced: bool) -> bool:
        positions = _flip_bytes(root, key, plan)
        if positions is None:
            return False
        _protect_partners(key, protected, store)
        injected.append(
            {"key": key, "prefix": prefix, "positions": positions,
             "forced": forced}
        )
        return True

    rotted = {p: 0 for p in ALL_PREFIXES}
    scope = plan.bit_rot_prefixes or ALL_PREFIXES
    # one pass per prefix, probabilistic rots then (if none landed) a
    # forced seeded pick — IN ALL_PREFIXES ORDER, which is load-bearing:
    # datasets rot before snapshots are considered, so the latest
    # snapshot is already protected as their restore source and a forced
    # snapshot rot falls on an older kept one
    for prefix in ALL_PREFIXES:
        keys = store.list_keys(prefix)
        for key in keys:
            if key in protected or not plan.bit_rot_decision(key):
                continue
            if _rot(key, prefix, forced=False):
                rotted[prefix] += 1
        if not ensure_per_prefix or rotted[prefix] or not keys:
            continue
        if not any(s.startswith(prefix) or prefix.startswith(s)
                   for s in scope):
            continue  # the plan scoped this prefix OUT: forcing a rot
            # here would override bit_rot_prefixes
        eligible = [k for k in keys if k not in protected]
        if not eligible:
            log.info(
                f"bit rot skips {prefix}: every key protects another "
                "rotted key's redundancy"
            )
            continue
        rng = random.Random(f"{plan.seed}|bit_rot_force|{prefix}")
        if _rot(rng.choice(sorted(eligible)), prefix, forced=True):
            rotted[prefix] += 1
    log.info(
        "bit rot injected: "
        + ", ".join(f"{p}={n}" for p, n in rotted.items() if n)
    )
    return injected


def run_bit_rot_sim(
    root: str | Path,
    start: date,
    days: int,
    plan: FaultPlan,
    model_type: str = "linear",
    scoring_mode: str = "batch",
    drift=None,
    train_mode: str = "full",
) -> dict:
    """The at-rest corruption acceptance soak (module docstring). Runs
    the twins under ``root/healthy`` and ``root/victim`` (which must be
    fresh), rots the victim, and returns the detection + repair +
    byte-identity summary."""
    from bodywork_tpu.audit.fsck import run_fsck
    from bodywork_tpu.audit.manifest import AuditedStore
    from bodywork_tpu.chaos.sim import _apply_train_mode, compare_stores
    from bodywork_tpu.data.snapshot import write_snapshot
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline

    root = Path(root)
    healthy_dir, victim_dir = root / "healthy", root / "victim"
    for d in (healthy_dir, victim_dir):
        if d.exists() and any(d.iterdir()):
            raise ValueError(
                f"bit-rot sim target {d} already holds artefacts; point "
                "--store at a fresh directory (the comparison needs two "
                "clean stores)"
            )
    stores = {}
    for name, d in (("healthy", healthy_dir), ("victim", victim_dir)):
        fs = FilesystemStore(d)
        audited = AuditedStore(fs)
        log.info(f"bit-rot sim: {name} run ({days} day(s)) -> {d}")
        LocalRunner(
            _apply_train_mode(
                default_pipeline(model_type, scoring_mode), train_mode
            ),
            audited,
            drift=drift,
        ).run_simulation(start, days)
        # one final compaction so the LATEST snapshot covers every day —
        # the restore source the dataset repair path depends on
        write_snapshot(audited)
        stores[name] = (fs, audited)
    healthy_fs, _healthy = stores["healthy"]
    victim_fs, victim = stores["victim"]

    plan.reset()  # the injector replays stream position 0, like activate()
    injected = inject_bit_rot(victim_fs, plan)

    # ONE scrub detects AND repairs (its findings are the detection
    # record — the scan runs before any repair mutates the store); a
    # second, detect-only scrub then proves nothing is left
    repair_report = run_fsck(victim, repair=True)
    flagged = {
        f["key"] for f in repair_report["findings"]
        if f["severity"] != "advisory"
    }
    undetected = sorted({e["key"] for e in injected} - flagged)
    post = run_fsck(victim, repair=False)
    # trainstate's repair policy is drop-and-rebuild-on-next-train
    # (derived state), so an incremental-mode soak excludes it from the
    # byte comparison — the healthy twin still holds its document
    extra = (TRAINSTATE_PREFIX,) if train_mode == "incremental" else ()
    comparison = compare_stores(healthy_fs, victim_fs, extra_excluded=extra)
    classified = {
        (f["key"], f["severity"]) for f in repair_report["findings"]
    }
    summary = {
        "days": days,
        "seed": plan.seed,
        "plan": plan.to_dict(),
        "injected": len(injected),
        "injected_keys": [e["key"] for e in injected],
        "injected_by_prefix": _by_prefix(injected),
        "detected": len(injected) - len(undetected),
        "undetected": undetected,
        "findings_by_severity": repair_report["by_severity"],
        "classified": sorted(f"{k} [{s}]" for k, s in classified),
        "repairs": repair_report["repairs"],
        "post_repair_residual": post["residual"],
        "comparison": comparison,
        "ok": (
            bool(injected)
            and not undetected
            and repair_report["ok"]
            and post["ok"]
            and comparison["ok"]
        ),
    }
    return summary


def _by_prefix(injected: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for entry in injected:
        out[entry["prefix"]] = out.get(entry["prefix"], 0) + 1
    return out
