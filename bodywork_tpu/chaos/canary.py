"""Canary release-safety acceptance: sabotage the canary, prove the loop.

The closed live-traffic release loop (canary routing + prediction-sanity
firewall + SLO watchdog, ISSUE 8) claims four things this module turns
into a seeded, reproducible PASS/FAIL:

1. A sabotaged canary — NaN weights in its checkpoint, or chaos-injected
   latency addressed to its stream — is auto-aborted via EXACTLY ONE
   compare-and-swap of the alias document, within the configured breach
   window (counted in requests).
2. Zero sanity-violating predictions are ever serialized: every response
   body a client received parses finite and inside the production
   model's training-label band.
3. The production stream is untouched throughout: every request the
   canary run answered from production is byte-identical to what a
   canary-free twin app answered for the same request, and the
   production checkpoint's bytes never change.
4. A healthy canary auto-promotes at window end, in one CAS.

Everything is a pure function of ``(seed, scenario, knobs)``: the
request stream is seeded, canary routing is a request hash, chaos
latency draws ride the fault plan's deterministic streams, and watchdog
verdicts are pure functions of windowed metric deltas — re-running a
scenario replays the identical abort at the identical poll
(``routing_digest`` in the summary pins it).

Exposed as ``cli chaos canary --store DIR --scenario nan|latency|healthy``.
"""
from __future__ import annotations

import hashlib
import io
import json
from datetime import date, timedelta

import numpy as np

from bodywork_tpu.store.base import ArtefactStore, DelegatingStore
from bodywork_tpu.store.schema import REGISTRY_ALIAS_KEY
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.canary")

__all__ = ["CANARY_SCENARIOS", "run_canary_chaos", "sabotage_checkpoint_nan"]

#: the sabotage scenarios the acceptance run covers (cli choices pinned
#: to this by tests/test_canary.py)
CANARY_SCENARIOS = ("nan", "latency", "healthy")

#: fixed simulated start day — part of what makes (seed, scenario)
#: fully determine the run
_START_DAY = date(2026, 1, 1)


def sabotage_checkpoint_nan(store: ArtefactStore, key: str) -> None:
    """Overwrite every floating-point weight leaf of a checkpoint with
    NaN, in place — the stage-4 live-scoring failure mode (a model that
    passed every offline gate and then emits garbage on real traffic),
    injected at the artefact layer so the WHOLE serving path (load,
    warm, route, predict, firewall) runs against it."""
    data = store.get_bytes(key)
    with np.load(io.BytesIO(data)) as npz:
        arrays = {name: npz[name] for name in npz.files}
    for name, arr in arrays.items():
        if np.issubdtype(arr.dtype, np.floating):
            arrays[name] = np.full_like(arr, np.nan)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    store.put_bytes(key, buf.getvalue())
    log.warning(f"sabotaged checkpoint {key}: all float leaves -> NaN")


class _AliasCasCountingStore(DelegatingStore):
    """Counts CAS writes against the alias document — the witness that
    an auto-abort/promote is exactly ONE compare-and-swap."""

    def __init__(self, inner: ArtefactStore):
        super().__init__(inner)
        self.alias_cas_writes = 0

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        if key == REGISTRY_ALIAS_KEY:
            self.alias_cas_writes += 1
        return self._inner.put_bytes_if_match(key, data, expected_token)


def _seed_two_model_registry(store: ArtefactStore, samples_per_day: int):
    """Two trained checkpoints on a fresh store: day 1's promoted to
    production, day 2's left a registered candidate (the canary-to-be).
    Returns ``(production_key, candidate_key)``."""
    from bodywork_tpu.data import Dataset, generate_day, persist_dataset
    from bodywork_tpu.data.drift_config import DriftConfig
    from bodywork_tpu.registry import ModelRegistry
    from bodywork_tpu.train import train_on_history

    drift = DriftConfig(n_samples=samples_per_day)
    keys = []
    for offset in (0, 1):
        day = _START_DAY + timedelta(days=offset)
        X, y = generate_day(day, drift)
        persist_dataset(store, Dataset(X, y, day))
        result = train_on_history(
            store, "linear", rows_per_day=samples_per_day
        )
        keys.append(result.model_artefact_key)
    production_key, candidate_key = keys
    ModelRegistry(store).promote(
        production_key, day=_START_DAY, reason="canary-chaos baseline"
    )
    return production_key, candidate_key


def _drive(
    app,
    twin_app,
    watcher,
    xs: np.ndarray,
    poll_every: int,
    bounds: tuple[float, float],
) -> dict:
    """Fire the seeded request stream at the canary'd app and its
    canary-free twin, polling the watcher (and therefore the SLO
    watchdog) every ``poll_every`` requests. Returns the per-request
    trace the checks below consume."""
    from bodywork_tpu.serve.app import MODEL_KEY_HEADER

    client = app.test_client()
    twin_client = twin_app.test_client()
    trace = {
        "bodies": [], "twin_bodies": [], "keys": [], "statuses": [],
        "violating_serialized": 0, "abort_at": None, "promote_at": None,
    }
    for i, x in enumerate(xs):
        payload = {"X": [float(x)]}
        response = client.post("/score/v1", json=payload)
        twin_response = twin_client.post("/score/v1", json=payload)
        body = response.get_data()
        trace["bodies"].append(body)
        trace["twin_bodies"].append(twin_response.get_data())
        trace["keys"].append(response.headers.get(MODEL_KEY_HEADER))
        trace["statuses"].append(response.status_code)
        if response.status_code == 200:
            prediction = json.loads(body)["prediction"]
            lo, hi = bounds
            if not np.isfinite(prediction) or not lo <= prediction <= hi:
                trace["violating_serialized"] += 1
        if (i + 1) % poll_every == 0:
            watcher.check_once()
            state = (app.slo_state or {}).get("state")
            if state == "breached" and trace["abort_at"] is None:
                trace["abort_at"] = i + 1
            if state == "promoted" and trace["promote_at"] is None:
                trace["promote_at"] = i + 1
    return trace


def run_canary_chaos(
    store: ArtefactStore,
    scenario: str = "nan",
    seed: int = 0,
    n_requests: int = 240,
    fraction: float = 0.35,
    samples_per_day: int = 96,
    poll_every: int = 20,
    policy=None,
    trace_fraction: float = 0.5,
) -> dict:
    """One seeded canary release-safety scenario against a FRESH store.
    Returns the acceptance summary (``summary["ok"]`` is the verdict);
    see the module docstring for what each scenario proves.

    The drive runs with request tracing configured at
    ``trace_fraction`` head sampling under the scenario's own seed
    (``obs/tracing.py``), so the watchdog's verdict ships a
    flight-recorder dump — the summary carries the dump key(s), the
    sampled trace ids (a pure function of (seed, request bytes):
    replays reproduce them), and how many sampled canary traces show
    the firewall-fallback child span. ``trace_fraction=0`` runs the
    scenario tracing-off, byte-identical by the header-only rule."""
    from bodywork_tpu.chaos.plan import FaultPlan, activate
    from bodywork_tpu.models.checkpoint import load_model
    from bodywork_tpu.obs.tracing import configured_tracing
    from bodywork_tpu.ops.slo import SloPolicy, SloWatchdog
    from bodywork_tpu.registry import ModelRegistry, read_aliases
    from bodywork_tpu.registry.records import load_record
    from bodywork_tpu.serve.app import as_bounds, create_app
    from bodywork_tpu.serve.reload import CheckpointWatcher

    if scenario not in CANARY_SCENARIOS:
        raise ValueError(
            f"unknown scenario {scenario!r}; expected one of "
            f"{CANARY_SCENARIOS}"
        )
    expected_exposure = n_requests * fraction
    if expected_exposure < 30:
        # too few canary-routed requests for ANY verdict to be
        # meaningful: the healthy scenario could never reach its promote
        # threshold and the verdict would report a release-loop FAILURE
        # when nothing is wrong — refuse up front with the fix
        raise ValueError(
            f"expected canary exposure {expected_exposure:.0f} requests "
            f"(= requests x fraction) is below 30; raise --requests or "
            "--fraction"
        )
    if store.list_keys(""):
        # a reused store replays against stale records/aliases (e.g. a
        # prior run's rejected candidate blocks canary_start) and the
        # PASS/FAIL verdict would measure debris, not the release loop
        raise ValueError(
            "canary chaos needs a FRESH store; the given one already "
            "holds artefacts"
        )
    production_key, candidate_key = _seed_two_model_registry(
        store, samples_per_day
    )
    registry = ModelRegistry(store)
    registry.canary_start(
        candidate_key, fraction=fraction, seed=seed,
        day=_START_DAY + timedelta(days=1),
    )
    if scenario == "nan":
        sabotage_checkpoint_nan(store, candidate_key)
    production_bytes_before = store.get_bytes(production_key)

    if policy is None:
        # scale the watchdog to the run: the breach window is a third of
        # the offered requests, so "auto-aborts within the window" is a
        # real bound, not vacuously the whole run; the promote threshold
        # sits at 60% of the EXPECTED canary exposure (>= 18 given the
        # exposure floor above) so routing variance cannot starve the
        # healthy scenario at ANY allowed fraction
        window = max(30, n_requests // 3)
        policy = SloPolicy(
            window_requests=window,
            min_requests=10,
            min_latency_samples=8,
            max_p99_latency_ratio=3.0,
            promote_after_requests=max(10, int(expected_exposure * 0.6)),
        )

    # production serving app + its canary-free twin (the twin shares the
    # warmed predictor — read-only — so the comparison isolates ROUTING,
    # not compile noise)
    model, model_date = load_model(store, production_key)
    production_record = load_record(store, production_key) or {}
    bounds_doc = production_record.get("prediction_bounds")
    app = create_app(
        model, model_date, buckets=(1,), warmup=True,
        model_key=production_key, model_source="production",
        model_bounds=bounds_doc,
    )
    twin_app = create_app(
        model, model_date, predictor=app.predictor, warmup=False,
        model_key=production_key, model_source="production",
        model_bounds=bounds_doc,
    )
    # all registry mutations from here on ride the counting wrapper: the
    # one-CAS claim is counted, not assumed
    counting = _AliasCasCountingStore(store)
    watchdog = SloWatchdog(counting, [app], policy=policy)
    watcher = CheckpointWatcher(
        app, counting, poll_interval_s=3600.0,
        served_key=production_key, buckets=(1,), slo_watchdog=watchdog,
    )
    watcher.check_once()  # loads + warms the canary, arms the watchdog

    rng = np.random.default_rng(seed)
    xs = rng.uniform(0.0, 100.0, n_requests)
    # routing is a pure request hash, so the harness can know — without
    # any server cooperation — which requests ROUTED to the canary (the
    # answering header says production after a firewall fallback): the
    # abort budget below is counted in canary-routed requests, the same
    # unit the watchdog's breach window uses
    from bodywork_tpu.serve.app import routes_to_canary

    routed_to_canary = [
        routes_to_canary(seed, fraction, np.asarray([x], dtype=np.float32))
        for x in xs
    ]
    bounds = as_bounds(bounds_doc) or (-np.inf, np.inf)
    plan = FaultPlan(seed=seed, canary_latency_p=1.0, canary_latency_s=0.05)
    # the drive (and the final reconcile poll — the watchdog's dump
    # must see the tracer's recorder) runs under scoped tracing config
    with configured_tracing(trace_fraction, seed=seed):
        if scenario == "latency":
            with activate(plan):
                trace = _drive(app, twin_app, watcher, xs, poll_every, bounds)
        else:
            trace = _drive(app, twin_app, watcher, xs, poll_every, bounds)
        watcher.check_once()  # final reconcile (covers n % poll_every != 0)
    state = (app.slo_state or {}).get("state")
    if state == "breached" and trace["abort_at"] is None:
        trace["abort_at"] = n_requests
    if state == "promoted" and trace["promote_at"] is None:
        trace["promote_at"] = n_requests

    # -- the checks --------------------------------------------------------
    doc = read_aliases(store) or {}
    record = load_record(store, candidate_key) or {}
    production_compared = production_mismatched = 0
    compare_until = (
        trace["promote_at"] if trace["promote_at"] is not None else n_requests
    )
    for i in range(min(compare_until, n_requests)):
        if trace["keys"][i] == production_key:
            production_compared += 1
            if trace["bodies"][i] != trace["twin_bodies"][i]:
                production_mismatched += 1
    routing_digest = hashlib.sha256(
        b"|".join(
            (k or "none").encode() + b":" + str(s).encode()
            for k, s in zip(trace["keys"], trace["statuses"])
        )
    ).hexdigest()
    # flight-recorder evidence (ISSUE 13 e2e): the verdict's dump(s)
    # under obs/flightrec/, the sampled trace ids (deterministic from
    # (seed, request bytes) — a replay reproduces this exact set), and
    # how many sampled canary-routed traces carry the firewall-fallback
    # child span (the NaN scenario's per-request proof that production
    # answered for the sabotaged canary)
    from bodywork_tpu.obs.tracing import iter_flight_records

    flight_records = list(iter_flight_records(store))
    sampled_trace_ids: set[str] = set()
    fallback_span_traces = 0
    for _key, flight_doc in flight_records:
        for t in flight_doc["traces"]:
            sampled_trace_ids.add(t["trace_id"])
            if (t.get("meta") or {}).get("stream") == "canary" and any(
                s["name"] == "firewall-fallback" for s in t["spans"]
            ):
                fallback_span_traces += 1
    summary = {
        "scenario": scenario,
        "seed": seed,
        "n_requests": n_requests,
        "fraction": fraction,
        "window_requests": policy.window_requests,
        "production_key": production_key,
        "canary_key": candidate_key,
        "aborted": doc.get("last_op") == "canary_abort",
        "promoted": doc.get("production") == candidate_key
        and doc.get("last_op") == "canary_promote",
        "abort_at_request": trace["abort_at"],
        "promote_at_request": trace["promote_at"],
        "alias_cas_writes": counting.alias_cas_writes,
        "violating_responses_serialized": trace["violating_serialized"],
        "production_responses_compared": production_compared,
        "production_responses_mismatched": production_mismatched,
        "production_checkpoint_byte_identical": (
            store.get_bytes(production_key) == production_bytes_before
        ),
        "canary_record_status": record.get("status"),
        "routing_digest": routing_digest,
        "trace_fraction": trace_fraction,
        "flight_record_keys": [k for k, _d in flight_records],
        "sampled_trace_ids": sorted(sampled_trace_ids),
        "fallback_span_traces": fallback_span_traces,
    }
    # budget: the breach must land within one window of CANARY-ROUTED
    # requests past the point the canary went live (plus one poll of
    # slack) — the same unit the watchdog's breach window slides by, so
    # the bound stays meaningful at any --fraction
    canary_routed_at_abort = (
        sum(routed_to_canary[: trace["abort_at"]])
        if trace["abort_at"] is not None else None
    )
    summary["canary_routed_at_abort"] = canary_routed_at_abort
    budget = policy.window_requests + poll_every
    if scenario in ("nan", "latency"):
        summary["ok"] = bool(
            summary["aborted"]
            and not summary["promoted"]
            and canary_routed_at_abort is not None
            and canary_routed_at_abort <= budget
            and summary["alias_cas_writes"] == 1
            and summary["violating_responses_serialized"] == 0
            and production_mismatched == 0
            and summary["production_checkpoint_byte_identical"]
            and record.get("status") == "rejected"
        )
    else:  # healthy
        summary["ok"] = bool(
            summary["promoted"]
            and not summary["aborted"]
            and summary["alias_cas_writes"] == 1
            and summary["violating_responses_serialized"] == 0
            and production_mismatched == 0
            and summary["production_checkpoint_byte_identical"]
            and record.get("status") == "production"
        )
    app.close()
    twin_app.close()
    verdict = "PASS" if summary["ok"] else "FAIL"
    log.info(
        f"canary chaos [{scenario}] {verdict}: aborted={summary['aborted']} "
        f"promoted={summary['promoted']} cas={summary['alias_cas_writes']} "
        f"violations_serialized={summary['violating_responses_serialized']}"
    )
    return summary
