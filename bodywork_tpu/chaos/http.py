"""Flaky scoring-service mode: probabilistic 503/429/latency on /score/v1*.

:class:`FlakyScoringMiddleware` is a WSGI middleware that consults the
fault plan once per scoring request and either injects latency, answers
with a deterministic 503/429 (plus a ``Retry-After`` header — the hint
the tester's scoring client must honour), or passes through untouched.
Health, metrics, and every non-scoring route always pass through: the
harness breaks the data path, not the probes that operators (and the
runner's health gate) rely on to see the breakage.

:func:`flaky_serve_stage` is the chaos simulation's drop-in replacement
for the canonical serve stage: it starts the real service, then wraps
the handle's in-process app object — the object the test stage's
``InProcessScoringClient`` scores through — in the middleware. The
socket-facing server keeps serving the unwrapped app, so the runner's
HTTP health gate sees the true service.
"""
from __future__ import annotations

import json

from bodywork_tpu.chaos.plan import FaultPlan, get_active_plan
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.http")

_STATUS_LINES = {
    503: "503 SERVICE UNAVAILABLE",
    429: "429 TOO MANY REQUESTS",
}


class FlakyScoringMiddleware:
    def __init__(self, app, plan: FaultPlan):
        self._app = app
        self.plan = plan

    def __call__(self, environ, start_response):
        path = environ.get("PATH_INFO", "")
        if path.startswith("/score/v1"):
            self.plan.http_latency(path)
            status = self.plan.http_error(path)
            if status is not None:
                # injected refusals share the shed counter under their
                # OWN reason label, so a dashboard can always tell
                # chaos-injected 503/429s from real admission sheds
                # (serve.admission counts reason="admission")
                from bodywork_tpu.serve.admission import count_shed

                count_shed("chaos")
                body = json.dumps(
                    {"error": f"injected fault: HTTP {status}"}
                ).encode()
                start_response(
                    _STATUS_LINES[status],
                    [
                        ("Content-Type", "application/json"),
                        ("Content-Length", str(len(body))),
                        ("Retry-After", str(self.plan.http_retry_after_s)),
                    ],
                )
                return [body]
        return self._app(environ, start_response)

    def test_client(self):
        """Same shape as ``ScoringApp.test_client`` — what the test
        stage's ``InProcessScoringClient`` constructs its client from."""
        from werkzeug.test import Client

        return Client(self)


def flaky_serve_stage(ctx, **args):
    """The canonical serve stage with the active fault plan's flaky mode
    layered over the in-process scoring path (used by
    ``chaos.sim.chaos_pipeline_spec``)."""
    from bodywork_tpu.pipeline.stages import serve_stage

    handle = serve_stage(ctx, **args)
    plan = get_active_plan()
    if plan is not None:
        handle.app = FlakyScoringMiddleware(handle.app, plan)
        log.info(
            f"flaky scoring mode armed (p_error={plan.http_error_p}, "
            f"p_latency={plan.http_latency_p})"
        )
    return handle
