"""Seeded process-kill points: chaos for the process itself.

PR 4 made *in-call* failure a deterministic input (transient errors,
torn writes, corrupt reads). This module extends the same philosophy to
the failure mode the resilience layer cannot absorb — the process dying
— so that crash-RESUME (``pipeline/journal.py``) is provable the same
way: a :class:`KillSwitch` holds a schedule of kill points and, when an
execution stream reaches one, terminates the run.

Determinism follows the chaos plan's rule: a kill point addresses a
DECISION STREAM, not a global event count, so background threads (the
runner's prefetch/compactor workers) cannot shift it:

- ``{"kind": "stage_boundary", "n": N}`` — the Nth step boundary of the
  runner's (single-threaded) day loop, counted across the whole run:
  ``run_day`` hits one boundary before each DAG step and one after the
  last, so an S-step pipeline over D days has ``D * (S + 1)`` boundary
  points.
- ``{"kind": "store_op", "op": OP, "key": KEY, "n": N}`` — the Nth
  execution of store primitive ``OP`` against ``KEY`` (the plan's
  per-``(op, key)`` stream addressing), fired BEFORE the op touches the
  backend — a mid-stage kill with the artefact not yet (re)written.

Two actions:

- ``exit`` (default) — ``os._exit(EXIT_KILLED)``: no atexit, no
  flushes, no finally blocks — the in-process equivalent of SIGKILL /
  OOM-kill. This is what the subprocess crash soak
  (``chaos.sim.run_crash_sim``) uses.
- ``raise`` — raise :class:`SimulatedCrash` (a ``BaseException``) so an
  IN-PROCESS test can approximate process death cheaply: the runner
  propagates it without retrying or journaling completion, and the test
  then builds a fresh runner over the same store to "restart". (Unlike
  a real kill, ``finally`` blocks still run — service teardown etc. —
  which only makes the approximation stricter about journal state,
  since nothing on the unwind path writes ``complete`` marks.)

Armed either programmatically (:func:`install`) or from the environment
(:func:`arm_from_env`, env ``BODYWORK_TPU_CRASH_SCHEDULE`` = the JSON
point list) — the latter is how the crash soak's child runners receive
their schedule.
"""
from __future__ import annotations

import json
import os
import threading

from bodywork_tpu.store.base import ArtefactStore, DelegatingStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.kill")

__all__ = [
    "EXIT_KILLED",
    "KillSwitch",
    "KillSwitchStore",
    "SimulatedCrash",
    "arm_from_env",
    "get_kill_switch",
    "hit_kill_point",
    "install",
    "parse_schedule",
    "uninstall",
    "wrap_store",
]

#: exit code of a kill-switch termination — distinct from every
#: documented CLI code (0/1/2/4/5/6/143) so the crash harness can tell
#: "killed as scheduled" from any real failure.
EXIT_KILLED = 86

ENV_SCHEDULE = "BODYWORK_TPU_CRASH_SCHEDULE"

_KINDS = ("stage_boundary", "store_op")

#: store primitives the ``store_op`` kind counts (payload ops only;
#: metadata probes — version_token/exists — are polled too often to be
#: useful kill anchors and would bloat every stream's n-space)
COUNTED_STORE_OPS = (
    "put_bytes",
    "put_bytes_if_match",
    "get_bytes",
    "list_keys",
    "delete",
    "get_many",
)


class SimulatedCrash(BaseException):
    """In-process stand-in for process death (``action="raise"``).
    Deliberately a ``BaseException``: no retry/recovery layer may treat
    it as a failure to absorb — the runner propagates it raw."""


def parse_schedule(raw) -> list[dict]:
    """Validate a schedule (JSON string or already-parsed list) into the
    canonical point list. Unknown kinds/fields are rejected by name —
    a typo'd kill point silently never firing would make a crash soak
    vacuously pass."""
    if isinstance(raw, str):
        raw = json.loads(raw)
    if not isinstance(raw, list):
        raise ValueError("crash schedule must be a JSON list of points")
    points = []
    for point in raw:
        if not isinstance(point, dict):
            raise ValueError(f"crash point must be an object, got {point!r}")
        kind = point.get("kind")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown crash-point kind {kind!r}; known: {_KINDS}"
            )
        n = point.get("n")
        if not isinstance(n, int) or n < 0:
            raise ValueError(f"crash point needs an int n >= 0, got {point!r}")
        allowed = {"kind", "n"} | (
            {"op", "key"} if kind == "store_op" else set()
        )
        unknown = set(point) - allowed
        if unknown:
            raise ValueError(
                f"unknown crash-point field(s) {sorted(unknown)} in {point!r}"
            )
        if kind == "store_op":
            if point.get("op") not in COUNTED_STORE_OPS:
                raise ValueError(
                    f"store_op crash point needs op in {COUNTED_STORE_OPS}, "
                    f"got {point.get('op')!r}"
                )
            if not isinstance(point.get("key"), str) or not point["key"]:
                raise ValueError(
                    f"store_op crash point needs a non-empty key: {point!r}"
                )
        points.append(dict(point))
    return points


class KillSwitch:
    """Deterministic process-termination schedule (module docstring)."""

    def __init__(self, schedule, action: str = "exit",
                 exit_code: int = EXIT_KILLED):
        assert action in ("exit", "raise"), action
        self.action = action
        self.exit_code = exit_code
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        #: stream -> armed n values
        self._points: dict[str, set[int]] = {}
        for point in parse_schedule(schedule):
            stream = self._stream(point["kind"], point.get("op"),
                                  point.get("key"))
            self._points.setdefault(stream, set()).add(point["n"])
        #: points that fired (stream, n) — lets a harness assert the
        #: schedule was actually reached (raise mode only; exit mode
        #: reports through the process exit code)
        self.fired: list[tuple[str, int]] = []

    @staticmethod
    def _stream(kind: str, op: str | None = None, key: str | None = None) -> str:
        if kind == "store_op":
            return f"store|{op}|{key}"
        return kind

    def hit(self, kind: str, op: str | None = None,
            key: str | None = None) -> None:
        stream = self._stream(kind, op, key)
        with self._lock:
            n = self._counts.get(stream, 0)
            self._counts[stream] = n + 1
            armed = n in self._points.get(stream, ())
            if armed:
                self.fired.append((stream, n))
        if not armed:
            return
        if self.action == "exit":
            # SIGKILL semantics: no flush, no atexit, no finally — the
            # journal must already hold everything a restart needs
            os._exit(self.exit_code)
        raise SimulatedCrash(f"kill point {stream}:{n}")


_ACTIVE: KillSwitch | None = None
_ACTIVE_LOCK = threading.Lock()


def install(switch: KillSwitch | None) -> KillSwitch | None:
    """Install (or, with None, clear) the process-wide kill switch;
    returns the previous one so tests can restore it."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        previous, _ACTIVE = _ACTIVE, switch
    return previous


def uninstall() -> None:
    install(None)


def get_kill_switch() -> KillSwitch | None:
    return _ACTIVE


def hit_kill_point(kind: str, op: str | None = None,
                   key: str | None = None) -> None:
    """The zero-cost-when-unarmed hook instrumented code calls."""
    switch = _ACTIVE
    if switch is not None:
        switch.hit(kind, op=op, key=key)


def arm_from_env() -> KillSwitch | None:
    """Install a kill switch from ``BODYWORK_TPU_CRASH_SCHEDULE`` (the
    crash soak's child-runner channel). A malformed schedule RAISES —
    the soak must never run vacuously against a typo."""
    raw = os.environ.get(ENV_SCHEDULE, "").strip()
    if not raw:
        return None
    switch = KillSwitch(raw, action="exit")
    install(switch)
    log.warning(f"crash kill switch armed from env: {raw}")
    return switch


class KillSwitchStore(DelegatingStore):
    """Transparent wrapper feeding every counted store primitive through
    the active kill switch BEFORE delegating (a fired point leaves the
    op un-executed — death mid-stage with the artefact unwritten)."""

    def _hit(self, op: str, key: str) -> None:
        hit_kill_point("store_op", op=op, key=key)

    def put_bytes(self, key: str, data: bytes) -> None:
        self._hit("put_bytes", key)
        self._inner.put_bytes(key, data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        self._hit("put_bytes_if_match", key)
        return self._inner.put_bytes_if_match(key, data, expected_token)

    def get_bytes(self, key: str) -> bytes:
        self._hit("get_bytes", key)
        return self._inner.get_bytes(key)

    def list_keys(self, prefix: str = "") -> list[str]:
        self._hit("list_keys", prefix)
        return self._inner.list_keys(prefix)

    def delete(self, key: str) -> None:
        self._hit("delete", key)
        self._inner.delete(key)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        if keys:
            self._hit("get_many", keys[0])
        return self._inner.get_many(keys)


def wrap_store(store: ArtefactStore) -> ArtefactStore:
    """Wrap ``store`` with the kill-switch counter when a switch is
    armed; otherwise return it untouched (the common path)."""
    if _ACTIVE is None:
        return store
    return KillSwitchStore(store)
