"""Seeded, deterministic fault plans — the chaos subsystem's contract.

A :class:`FaultPlan` holds per-op fault probabilities (transient store
errors, injected latency, crash-after-partial-write, payload corruption,
flaky HTTP 503/429 responses) plus the seed that makes every decision
reproducible. Decisions are NOT drawn from one shared RNG stream —
concurrent threads (the runner's prefetch/compactor workers) would make
the draw order, and therefore the whole run, timing-dependent. Instead
each decision is a pure function of ``(seed, kind, stream, n)`` where
``stream`` identifies the op/key and ``n`` counts that stream's
decisions: the fault sequence seen by any sequential op stream is
byte-reproducible under the same seed regardless of what other threads
are doing. (``random.Random`` seeds strings via SHA-512, so the mapping
is stable across processes and Python's hash randomisation.)

``max_consecutive`` caps how many times in a row one OP STREAM may fail
(a forced-clean execution follows). The cap is enforced across every
failing fault kind together — a put stream's transient and torn-write
faults share one streak, and a ``get_many`` batch is a single failure
unit with its own stream — because independent per-kind caps would
compose: two capped transient faults followed by a first torn-write
fault is three consecutive failures, one more than either cap admits.
With the default cap of 2 — below the retry policy's 3 attempts —
every retried op is GUARANTEED to succeed within its budget, which is
what lets a seeded soak assert bit-exact final artefacts rather than
"usually survives". Set it to 0 (unlimited) to drive breaker-opening
scenarios.

Fault injections are counted as
``bodywork_tpu_chaos_faults_injected_total{kind}`` and appended to
``plan.injected_log`` (the determinism tests' pinned sequence).
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import random
import threading
import time

from bodywork_tpu.utils.retry import TransientError

__all__ = [
    "FaultPlan",
    "InjectedFault",
    "activate",
    "get_active_plan",
]


class InjectedFault(TransientError):
    """A chaos-injected transient failure. Subclasses
    :class:`~bodywork_tpu.utils.retry.TransientError`, so the resilience
    layer classifies it exactly like a real 503/connection drop."""


_PROBABILITY_FIELDS = (
    "store_transient_p",
    "store_latency_p",
    "torn_write_p",
    "corrupt_read_p",
    "bit_rot_p",
    "http_error_p",
    "http_latency_p",
    "canary_latency_p",
)


@dataclasses.dataclass
class FaultPlan:
    """Per-op fault probabilities under one seed. All ``*_p`` fields are
    probabilities in [0, 1]; latencies are seconds (kept small so soaks
    stay fast)."""

    seed: int = 0
    #: store ops (put/get/list/delete/get_many): raise InjectedFault
    store_transient_p: float = 0.0
    #: store ops: sleep store_latency_s before the op
    store_latency_p: float = 0.0
    store_latency_s: float = 0.002
    #: put_bytes: persist a PREFIX of the payload, then raise (the
    #: crash-after-partial-write the retry must repair by rewriting)
    torn_write_p: float = 0.0
    #: get_bytes: return truncated payload — only for keys under
    #: corrupt_prefixes, because corrupting a read whose consumer has no
    #: integrity check silently changes results instead of testing
    #: recovery. Safe defaults: the snapshot loader validates and falls
    #: back (``snapshots/``), and registry readers validate the JSON
    #: schema and re-read under the consecutive cap (``registry/`` —
    #: records degrade to absent-with-counter past the budget, the alias
    #: document raises and callers keep current state; see
    #: ``registry/records.py``). The cap (default 2) below the registry
    #: read budget (3 attempts) is what keeps chaos-run gate decisions
    #: byte-identical to the fault-free twin's. ``trainstate/`` readers
    #: (``train/incremental.py``) digest-verify the document under the
    #: same 3-attempt budget; past it they degrade to a full-refit
    #: rebuild — derived state, so corruption can cost one O(history)
    #: day but never a wrong model.
    #:
    #: Contrast with the AT-REST knob below: ``corrupt_prefixes`` scopes
    #: IN-FLIGHT read corruption, so it stays restricted to prefixes
    #: whose readers validate; ``bit_rot_prefixes`` scopes at-rest
    #: corruption, whose detector is the fsck scrub — which audits
    #: EVERY prefix — so its default is the whole store. The two knobs
    #: share this one plan format (and the flag > plan > env
    #: precedence), so a run-sim soak and an fsck soak reproduce from
    #: the same JSON document.
    #: ``tuning/`` readers (``tune/config.py``) schema+digest-validate
    #: and degrade to the built-in serving defaults on any failure —
    #: corruption can cost the tuned knob values for one boot, never a
    #: crash or a wrong artefact, so the prefix is in-flight-corruption
    #: safe by the same argument as ``trainstate/``.
    corrupt_read_p: float = 0.0
    corrupt_prefixes: tuple[str, ...] = (
        "snapshots/", "registry/", "runs/", "trainstate/", "tuning/"
    )
    #: AT-REST bit rot (``chaos/bitrot.py``, ``cli chaos run-sim
    #: --bit-rot``): per-KEY seeded decision over a FINISHED store's
    #: artefacts — bytes flip on disk with timestamps preserved, so no
    #: in-flight hook ever fires and only the integrity scrub
    #: (``cli fsck``) can see it. ``bit_rot_p`` is the per-key rot
    #: probability (the harness additionally forces at least one rotted
    #: key per populated prefix so a sweep always exercises every
    #: auditor); ``bit_rot_max_flips`` bounds the seeded byte flips per
    #: rotted key; ``bit_rot_prefixes`` scopes the damage — empty means
    #: every prefix in ``schema.ALL_PREFIXES``.
    bit_rot_p: float = 0.0
    bit_rot_max_flips: int = 3
    bit_rot_prefixes: tuple[str, ...] = ()
    #: scoring service /score/v1* requests: answer 503 or 429 (split
    #: evenly, deterministically) with a Retry-After header
    http_error_p: float = 0.0
    http_retry_after_s: float = 0.0
    #: scoring service: sleep http_latency_s before handling
    http_latency_p: float = 0.0
    http_latency_s: float = 0.002
    #: adversity addressed to the CANARY stream only: scoring requests
    #: that routed to the live canary sleep canary_latency_s before
    #: dispatch (production-routed requests never consult this) — the
    #: fault the SLO watchdog's p99-latency-ratio breach exists to
    #: catch. Per-canary-model-key decision streams, so a seeded run
    #: replays identical adversity regardless of interleaving.
    canary_latency_p: float = 0.0
    canary_latency_s: float = 0.05
    #: max consecutive faults per (kind, stream) before a forced success;
    #: 0 = unlimited (lets tests hold a backend down to open the breaker)
    max_consecutive: int = 2
    #: process-kill points (``chaos.kill``): a list of
    #: ``{"kind": "stage_boundary", "n": N}`` /
    #: ``{"kind": "store_op", "op": OP, "key": KEY, "n": N}`` objects.
    #: Consumed by the crash soak (``chaos.sim.run_crash_sim`` /
    #: ``cli chaos run-sim --crash-schedule``), which runs each point in
    #: a SUBPROCESS runner (``os._exit`` kills the interpreter) and then
    #: restarts it to prove crash-resume convergence. Like every other
    #: plan field the points are addressed to pure decision streams, so
    #: background-thread interleaving cannot move a kill.
    crash_schedule: tuple = ()

    def __post_init__(self):
        for field in _PROBABILITY_FIELDS:
            p = getattr(self, field)
            if not isinstance(p, (int, float)) or not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"fault plan {field} must be a probability in [0, 1], "
                    f"got {p!r}"
                )
        if self.max_consecutive < 0:
            raise ValueError("max_consecutive must be >= 0 (0 = unlimited)")
        if self.bit_rot_max_flips < 1:
            raise ValueError("bit_rot_max_flips must be >= 1")
        self.corrupt_prefixes = tuple(self.corrupt_prefixes)
        self.bit_rot_prefixes = tuple(self.bit_rot_prefixes)
        if self.crash_schedule:
            from bodywork_tpu.chaos.kill import parse_schedule

            self.crash_schedule = tuple(
                parse_schedule(list(self.crash_schedule))
            )
        self._lock = threading.Lock()
        #: decision count per (kind, stream)
        self._draws: dict[tuple, int] = {}
        #: consecutive-fault count per (kind, stream)
        self._consecutive: dict[tuple, int] = {}
        #: every injected fault, in decision order: (kind, stream, n)
        self.injected_log: list[tuple[str, str, int]] = []

    def reset(self) -> None:
        """Clear all decision history (draw counters, streaks, the
        injected-fault log). A reused plan object must start each run
        from stream position 0 or the 'same seed => same adversity'
        contract silently breaks; :func:`activate` resets on entry so
        every activated run is a fresh replay."""
        with self._lock:
            self._draws.clear()
            self._consecutive.clear()
            self.injected_log.clear()

    # -- (de)serialisation (CLI --plan files / env knobs) ------------------

    def to_dict(self) -> dict:
        return {
            f.name: (
                list(v) if isinstance(v := getattr(self, f.name), tuple) else v
            )
            for f in dataclasses.fields(self)
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "FaultPlan":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - known
        if unknown:
            raise ValueError(
                f"unknown fault-plan field(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        return cls(**doc)

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"fault plan {path} must be a JSON object")
        return cls.from_dict(doc)

    @classmethod
    def default(cls, seed: int = 0) -> "FaultPlan":
        """The stock soak plan: every fault kind armed, capped so the
        retry budget always wins (docs/RESILIENCE.md §6)."""
        return cls(
            seed=seed,
            store_transient_p=0.15,
            store_latency_p=0.10,
            torn_write_p=0.15,
            corrupt_read_p=0.5,
            http_error_p=0.4,
            http_latency_p=0.2,
        )

    # -- the decision core -------------------------------------------------

    def _decide(self, kind: str, stream: str, p: float) -> bool:
        return self._decide_n(kind, stream, p)[0]

    def _decide_n(
        self, kind: str, stream: str, p: float, capped: bool = True
    ) -> tuple[bool, int]:
        """One seeded decision on the (kind, stream) draw stream. With
        ``capped`` the per-(kind, stream) consecutive cap applies here;
        store failure kinds pass ``capped=False`` because their cap is
        enforced jointly per OP stream by :meth:`store_fault` (caps on
        independent kinds would compose past the retry budget)."""
        if p <= 0.0:
            return False, 0
        with self._lock:
            key = (kind, stream)
            n = self._draws.get(key, 0)
            self._draws[key] = n + 1
            if (
                capped
                and self.max_consecutive
                and self._consecutive.get(key, 0) >= self.max_consecutive
            ):
                self._consecutive[key] = 0
                return False, n  # forced success: the cap bounding adversity
            hit = random.Random(f"{self.seed}|{kind}|{stream}|{n}").random() < p
            self._consecutive[key] = self._consecutive.get(key, 0) + 1 if hit else 0
            if hit:
                self.injected_log.append((kind, stream, n))
                if kind != "http_error":  # counted as http_503/http_429
                    _count_fault(kind)
            return hit, n

    def store_fault(self, op: str, key: str) -> str | None:
        """The ONE failure decision per store-op execution: ``None``
        (clean), ``"transient"``, or (``put_bytes`` only)
        ``"torn_write"``. All failing kinds share a single per-op-stream
        consecutive streak, so ``max_consecutive`` bounds TOTAL
        consecutive failures of the op — the property that keeps every
        retried op inside its attempt budget."""
        stream = f"store|{op}|{key}"
        gate = ("fail", stream)
        with self._lock:
            if (
                self.max_consecutive
                and self._consecutive.get(gate, 0) >= self.max_consecutive
            ):
                self._consecutive[gate] = 0
                return None  # forced-clean execution (no draws consumed)
        hit_transient, _ = self._decide_n(
            "transient", stream, self.store_transient_p, capped=False
        )
        hit_torn = False
        if not hit_transient and op == "put_bytes":
            hit_torn, _ = self._decide_n(
                "torn_write", stream, self.torn_write_p, capped=False
            )
        with self._lock:
            if hit_transient or hit_torn:
                self._consecutive[gate] = self._consecutive.get(gate, 0) + 1
            else:
                self._consecutive[gate] = 0
        if hit_transient:
            return "transient"
        return "torn_write" if hit_torn else None

    # -- store-op hooks (FaultInjectingStore) ------------------------------

    def store_latency(self, op: str, key: str) -> None:
        if self._decide("latency", f"store|{op}|{key}", self.store_latency_p):
            time.sleep(self.store_latency_s)

    def corrupt_read(self, key: str, data: bytes) -> bytes:
        if not key.startswith(tuple(self.corrupt_prefixes)):
            return data
        if self._decide("corrupt", f"store|get_bytes|{key}", self.corrupt_read_p):
            return data[: max(1, len(data) // 2)]
        return data

    # -- at-rest hooks (chaos.bitrot) --------------------------------------

    def bit_rot_decision(self, key: str) -> bool:
        """ONE seeded at-rest rot decision per stored key — consumed by
        the bit-rot injector (``chaos.bitrot.inject_bit_rot``) over a
        finished store, never by an in-flight op. Per-key streams, so a
        sweep replays identically whatever order keys are visited in."""
        prefixes = self.bit_rot_prefixes
        if not prefixes:
            from bodywork_tpu.store.schema import ALL_PREFIXES

            prefixes = ALL_PREFIXES
        if not key.startswith(tuple(prefixes)):
            return False
        return self._decide("bit_rot", f"atrest|{key}", self.bit_rot_p)

    # -- HTTP hooks (FlakyScoringMiddleware) -------------------------------

    def http_latency_delay(self, path: str) -> float | None:
        """Decide-only variant of :meth:`http_latency` for the asyncio
        front-end (``serve.aio``): returns the injected delay in seconds
        (to ``await asyncio.sleep`` — a ``time.sleep`` would stall the
        whole event loop) or None. Same draw stream as the blocking
        form, so either engine replays one seed identically."""
        if self._decide("http_latency", f"http|{path}", self.http_latency_p):
            return self.http_latency_s
        return None

    def http_latency(self, path: str) -> None:
        if self.http_latency_delay(path) is not None:
            time.sleep(self.http_latency_s)

    def canary_latency_delay(self, model_key: str) -> float | None:
        """Canary-stream latency injection: the delay in seconds to
        apply before a canary-routed dispatch, or None. Decide-only so
        the asyncio engine can ``await`` it (the threaded engine sleeps
        via :meth:`canary_latency`); same draw stream either way, so one
        seed replays identically on both engines."""
        if self._decide(
            "canary_latency", f"canary|{model_key}", self.canary_latency_p
        ):
            return self.canary_latency_s
        return None

    def canary_latency(self, model_key: str) -> None:
        if self.canary_latency_delay(model_key) is not None:
            time.sleep(self.canary_latency_s)

    def http_error(self, path: str) -> int | None:
        """503, 429, or None — one decision per scoring request."""
        stream = f"http|{path}"
        hit, n = self._decide_n("http_error", stream, self.http_error_p)
        if not hit:
            return None
        status = (
            503
            if random.Random(f"{self.seed}|http_status|{stream}|{n}").random()
            < 0.5
            else 429
        )
        _count_fault(f"http_{status}")
        return status


def _count_fault(kind: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_chaos_faults_injected_total",
        "Chaos-injected faults by kind",
    ).inc(kind=kind)


#: the process-wide active plan (``activate``); read by the flaky serve
#: stage so a chaos simulation's in-process service picks up the plan
#: without threading it through the pipeline spec
_ACTIVE: FaultPlan | None = None
_ACTIVE_LOCK = threading.Lock()


@contextlib.contextmanager
def activate(plan: FaultPlan):
    """Install ``plan`` as the process-wide active plan for the duration
    of a chaos run (``chaos.sim.run_chaos_sim`` wraps the faulted
    simulation in this). Entry RESETS the plan's decision history, so a
    reused plan object replays the same seeded adversity every run."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a fault plan is already active")
        plan.reset()
        _ACTIVE = plan
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None


def get_active_plan() -> FaultPlan | None:
    return _ACTIVE
