"""Chaos soak: a seeded faulted simulation proven against a clean twin.

``run_chaos_sim`` runs the SAME N-day pipeline simulation twice under
one roof:

1. **baseline** — the canonical pipeline on a plain filesystem store;
2. **faulted** — the same pipeline with the full resilience stack over
   a fault-injecting store (``real <- FaultInjectingStore <-
   ResilientStore``) and the scoring service in flaky mode
   (``chaos.http.flaky_serve_stage``), all driven by one seeded
   :class:`~bodywork_tpu.chaos.plan.FaultPlan`.

then compares the two stores' FINAL artefacts:

- ``datasets/``, ``models/``, ``model-metrics/`` must be
  **byte-identical** — training, generation, and checkpointing are
  deterministic, so any divergence means a fault leaked into results;
- ``test-metrics/`` must be identical after dropping the
  ``mean_response_time`` column (the one wall-clock-dependent field; in
  particular ``n_failures`` must match the baseline's zeros — the
  scoring client's status retries must have absorbed every injected
  503/429);
- the latest ``snapshots/`` artefact must be loadable (not torn) and
  cover the same day keys and row counts (snapshot bytes embed backend
  version tokens, which legitimately differ between stores);
- no torn artefacts: no leftover atomic-write temp files, snapshot
  validation passes.

Passing proves the resilience layer end to end: every injected
transient error, latency spike, crash-after-partial-write, corrupt
snapshot read, and flaky scoring response was absorbed without touching
the results. The fault plan's ``max_consecutive`` cap (kept below the
retry policy's attempt budget) is what makes this a guarantee instead
of a probability — see docs/RESILIENCE.md.
"""
from __future__ import annotations

from datetime import date
from pathlib import Path

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.filesystem import FilesystemStore
from bodywork_tpu.store.resilient import ResilientStore
from bodywork_tpu.store.schema import SNAPSHOTS_PREFIX, TEST_METRICS_PREFIX
from bodywork_tpu.chaos.plan import FaultPlan, activate
from bodywork_tpu.chaos.store import FaultInjectingStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.sim")

__all__ = ["chaos_pipeline_spec", "compare_stores", "run_chaos_sim"]

#: counters whose per-run delta the summary reports
_FAULT_COUNTER = "bodywork_tpu_chaos_faults_injected_total"
_RETRY_COUNTERS = (
    "bodywork_tpu_store_retries_total",
    "bodywork_tpu_scoring_client_retries_total",
)


def chaos_pipeline_spec(model_type: str = "linear",
                        scoring_mode: str = "batch"):
    """The canonical daily pipeline with the serve stage swapped for the
    flaky-mode wrapper (identical spec otherwise, so the faulted run's
    work plan matches the baseline's exactly)."""
    from bodywork_tpu.pipeline import default_pipeline

    spec = default_pipeline(model_type, scoring_mode)
    spec.stages["stage-2-serve-model"].executable = (
        "bodywork_tpu.chaos.http:flaky_serve_stage"
    )
    return spec


def _strip_csv_column(data: bytes, column: str) -> bytes:
    """Remove one column from CSV bytes, textually (no float reparsing —
    every surviving byte still has to match exactly)."""
    lines = data.decode("utf-8").splitlines()
    if not lines:
        return data
    header = lines[0].split(",")
    if column not in header:
        return data
    idx = header.index(column)
    out = []
    for line in lines:
        fields = line.split(",")
        del fields[idx]
        out.append(",".join(fields))
    return ("\n".join(out) + "\n").encode("utf-8")


def _snapshot_coverage(store: ArtefactStore):
    """``[(day key, rows), ...]`` of the latest loadable snapshot, or
    None when no snapshot loads (absent or torn)."""
    from bodywork_tpu.data.snapshot import load_latest_snapshot

    snap = load_latest_snapshot(store, record_outcome=False)
    if snap is None:
        return None
    return sorted((e["key"], e["rows"]) for e in snap.entries)


def compare_stores(baseline: ArtefactStore, chaos: ArtefactStore) -> dict:
    """Final-artefact comparison (module docstring has the rules)."""
    base_keys = [
        k for k in baseline.list_keys() if not k.startswith(SNAPSHOTS_PREFIX)
    ]
    chaos_keys = [
        k for k in chaos.list_keys() if not k.startswith(SNAPSHOTS_PREFIX)
    ]
    missing = sorted(set(base_keys) - set(chaos_keys))
    extra = sorted(set(chaos_keys) - set(base_keys))
    mismatched: list[str] = []
    matched = 0
    for key in sorted(set(base_keys) & set(chaos_keys)):
        a = baseline.get_bytes(key)
        b = chaos.get_bytes(key)
        if key.startswith(TEST_METRICS_PREFIX):
            a = _strip_csv_column(a, "mean_response_time")
            b = _strip_csv_column(b, "mean_response_time")
        if a == b:
            matched += 1
        else:
            mismatched.append(key)
    torn: list[str] = []
    for store in (baseline, chaos):
        root = getattr(store, "root", None)
        if root is not None:
            torn.extend(
                str(p.relative_to(root))
                for p in Path(root).rglob(".tmp-*")
                # CAS sidecar locks are deliberately persistent (the
                # flock protocol must never unlink them — filesystem.py
                # _acquire_cas_lock), not abandoned write temp files
                if not p.name.startswith(".tmp-lock.")
            )
    base_cov = _snapshot_coverage(baseline)
    chaos_cov = _snapshot_coverage(chaos)
    snapshot_ok = base_cov == chaos_cov and (
        base_cov is not None or not baseline.list_keys(SNAPSHOTS_PREFIX)
    )
    if chaos_cov is None and chaos.list_keys(SNAPSHOTS_PREFIX):
        torn.append(f"{SNAPSHOTS_PREFIX} (latest snapshot unreadable)")
    return {
        "matched": matched,
        "missing": missing,
        "extra": extra,
        "mismatched": mismatched,
        "torn": torn,
        "snapshot_ok": snapshot_ok,
        "ok": not (missing or extra or mismatched or torn) and snapshot_ok,
    }


def _counter_values(name: str) -> dict[tuple, float]:
    from bodywork_tpu.obs import get_registry

    metric = get_registry().get(name)
    if metric is None:
        return {}
    return {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in metric.snapshot_samples()
    }


def _counter_delta(name: str, before: dict) -> dict[str, float]:
    out = {}
    for labels, value in _counter_values(name).items():
        delta = value - before.get(labels, 0.0)
        if delta:
            out[",".join(f"{k}={v}" for k, v in labels)] = delta
    return out


def run_chaos_sim(
    root: str | Path,
    start: date,
    days: int,
    plan: FaultPlan,
    model_type: str = "linear",
    scoring_mode: str = "batch",
    drift=None,
) -> dict:
    """Run the baseline and faulted simulations under ``root`` (in
    ``baseline/`` and ``chaos/`` subdirectories, which must not already
    hold artefacts) and return the comparison + fault/retry summary."""
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline

    root = Path(root)
    baseline_dir, chaos_dir = root / "baseline", root / "chaos"
    for d in (baseline_dir, chaos_dir):
        if d.exists() and any(d.iterdir()):
            raise ValueError(
                f"chaos sim target {d} already holds artefacts; point "
                "--store at a fresh directory (the comparison needs two "
                "clean stores)"
            )
    before = {
        name: _counter_values(name)
        for name in (_FAULT_COUNTER, *_RETRY_COUNTERS)
    }

    log.info(f"chaos sim: baseline run ({days} day(s)) -> {baseline_dir}")
    baseline_store = FilesystemStore(baseline_dir)
    LocalRunner(
        default_pipeline(model_type, scoring_mode), baseline_store,
        drift=drift,
    ).run_simulation(start, days)

    log.info(
        f"chaos sim: faulted run (seed={plan.seed}) -> {chaos_dir}"
    )
    real_store = FilesystemStore(chaos_dir)
    wrapped = ResilientStore(FaultInjectingStore(real_store, plan))
    with activate(plan):
        LocalRunner(
            chaos_pipeline_spec(model_type, scoring_mode), wrapped,
            drift=drift,
        ).run_simulation(start, days)

    comparison = compare_stores(baseline_store, real_store)
    summary = {
        "days": days,
        "seed": plan.seed,
        "plan": plan.to_dict(),
        "comparison": comparison,
        "faults_injected": _counter_delta(_FAULT_COUNTER, before[_FAULT_COUNTER]),
        "retries": {
            name: _counter_delta(name, before[name])
            for name in _RETRY_COUNTERS
        },
        "breaker_state": wrapped.breaker.state,
        "ok": comparison["ok"],
    }
    return summary
