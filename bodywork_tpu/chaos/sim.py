"""Chaos soak: a seeded faulted simulation proven against a clean twin.

``run_chaos_sim`` runs the SAME N-day pipeline simulation twice under
one roof:

1. **baseline** — the canonical pipeline on a plain filesystem store;
2. **faulted** — the same pipeline with the full resilience stack over
   a fault-injecting store (``real <- FaultInjectingStore <-
   ResilientStore``) and the scoring service in flaky mode
   (``chaos.http.flaky_serve_stage``), all driven by one seeded
   :class:`~bodywork_tpu.chaos.plan.FaultPlan`.

then compares the two stores' FINAL artefacts:

- ``datasets/``, ``models/``, ``model-metrics/`` must be
  **byte-identical** — training, generation, and checkpointing are
  deterministic, so any divergence means a fault leaked into results;
- ``test-metrics/`` must be identical after dropping the
  ``mean_response_time`` column (the one wall-clock-dependent field; in
  particular ``n_failures`` must match the baseline's zeros — the
  scoring client's status retries must have absorbed every injected
  503/429);
- the latest ``snapshots/`` artefact must be loadable (not torn) and
  cover the same day keys and row counts (snapshot bytes embed backend
  version tokens, which legitimately differ between stores);
- no torn artefacts: no leftover atomic-write temp files, snapshot
  validation passes.

Passing proves the resilience layer end to end: every injected
transient error, latency spike, crash-after-partial-write, corrupt
snapshot read, and flaky scoring response was absorbed without touching
the results. The fault plan's ``max_consecutive`` cap (kept below the
retry policy's attempt budget) is what makes this a guarantee instead
of a probability — see docs/RESILIENCE.md.
"""
from __future__ import annotations

from datetime import date
from pathlib import Path

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.filesystem import FilesystemStore
from bodywork_tpu.store.resilient import ResilientStore
from bodywork_tpu.store.schema import (
    AUDIT_DIGESTS_PREFIX,
    FLIGHTREC_PREFIX,
    QUARANTINE_PREFIX,
    RUNS_PREFIX,
    SERVE_PREFIX,
    SNAPSHOTS_PREFIX,
    TEST_METRICS_PREFIX,
)
from bodywork_tpu.chaos.plan import FaultPlan, activate
from bodywork_tpu.chaos.store import FaultInjectingStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("chaos.sim")

__all__ = [
    "chaos_pipeline_spec",
    "compare_stores",
    "run_chaos_sim",
    "run_crash_sim",
    "sweep_points",
]

#: counters whose per-run delta the summary reports
_FAULT_COUNTER = "bodywork_tpu_chaos_faults_injected_total"
_RETRY_COUNTERS = (
    "bodywork_tpu_store_retries_total",
    "bodywork_tpu_scoring_client_retries_total",
)


def _apply_train_mode(spec, train_mode: str):
    """PIN the train stages' mode arg — always set explicitly, even for
    ``full``: an absent arg falls through to BODYWORK_TPU_TRAIN_MODE in
    ``train_stage``, and an exported env knob silently overriding an
    explicit ``--train-mode full`` would soak (and report PASS for) the
    wrong mode."""
    from bodywork_tpu.train.trainer import TRAIN_MODES

    if train_mode not in TRAIN_MODES:
        raise ValueError(
            f"unknown train mode {train_mode!r}; expected one of "
            f"{TRAIN_MODES}"
        )
    for stage in spec.stages.values():
        if stage.executable.endswith(":train_stage"):
            stage.args = {**stage.args, "mode": train_mode}
    return spec


def chaos_pipeline_spec(model_type: str = "linear",
                        scoring_mode: str = "batch",
                        train_mode: str = "full"):
    """The canonical daily pipeline with the serve stage swapped for the
    flaky-mode wrapper (identical spec otherwise, so the faulted run's
    work plan matches the baseline's exactly)."""
    from bodywork_tpu.pipeline import default_pipeline

    spec = _apply_train_mode(
        default_pipeline(model_type, scoring_mode), train_mode
    )
    spec.stages["stage-2-serve-model"].executable = (
        "bodywork_tpu.chaos.http:flaky_serve_stage"
    )
    return spec


def _strip_csv_column(data: bytes, column: str) -> bytes:
    """Remove one column from CSV bytes, textually (no float reparsing —
    every surviving byte still has to match exactly)."""
    lines = data.decode("utf-8").splitlines()
    if not lines:
        return data
    header = lines[0].split(",")
    if column not in header:
        return data
    idx = header.index(column)
    out = []
    for line in lines:
        fields = line.split(",")
        del fields[idx]
        out.append(",".join(fields))
    return ("\n".join(out) + "\n").encode("utf-8")


def _snapshot_coverage(store: ArtefactStore):
    """``[(day key, rows), ...]`` of the latest loadable snapshot, or
    None when no snapshot loads (absent or torn)."""
    from bodywork_tpu.data.snapshot import load_latest_snapshot

    snap = load_latest_snapshot(store, record_outcome=False)
    if snap is None:
        return None
    return sorted((e["key"], e["rows"]) for e in snap.entries)


def _journals_ok(store: ArtefactStore) -> bool:
    """Every ``runs/`` journal must parse and be day-complete — the
    OPERATIONAL check replacing byte comparison for this prefix (lease
    owners and expiry wall-clocks legitimately differ between twins)."""
    import json

    from bodywork_tpu.pipeline.journal import JOURNAL_SCHEMA

    for key in store.list_keys(RUNS_PREFIX):
        try:
            doc = json.loads(store.get_bytes(key).decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return False
        if not isinstance(doc, dict) or doc.get("schema") != JOURNAL_SCHEMA:
            return False
        if doc.get("status") != "complete":
            return False
    return True


#: prefixes excluded from the byte-identity comparison: snapshots embed
#: backend version tokens (coverage-compared instead), journals embed
#: lease identities and wall-clocks (validity-checked instead),
#: quarantine/ holds per-incident evidence only one twin can have, and
#: the audit sidecars OF test-metrics and snapshots record digests over
#: bytes that embed a wall-clock column / backend tokens respectively
#: (the metrics themselves are compared with the column stripped, the
#: snapshots coverage-compared; their sidecars hash the raw bytes)
_COMPARE_EXCLUDED = (
    SNAPSHOTS_PREFIX,
    RUNS_PREFIX,
    QUARANTINE_PREFIX,
    AUDIT_DIGESTS_PREFIX + TEST_METRICS_PREFIX,
    AUDIT_DIGESTS_PREFIX + SNAPSHOTS_PREFIX,
    # flight-recorder dumps are verdict evidence only one twin can hold
    # (the faulted twin runs with tracing enabled; the baseline runs
    # tracing-off) — excluded WITH their sidecars, exactly like
    # quarantine/. Everything else must stay byte-identical with
    # tracing on: trace ids ride only a response header.
    FLIGHTREC_PREFIX,
    AUDIT_DIGESTS_PREFIX + FLIGHTREC_PREFIX,
    # the serving-plane leader lease embeds owner host:pid:nonce and
    # wall-clock expiry — operational state, never artefact data; each
    # twin elects its own dispatcher so the docs can never match
    SERVE_PREFIX,
    AUDIT_DIGESTS_PREFIX + SERVE_PREFIX,
)


def compare_stores(
    baseline: ArtefactStore,
    chaos: ArtefactStore,
    extra_excluded: tuple = (),
) -> dict:
    """Final-artefact comparison (module docstring has the rules).
    ``extra_excluded`` adds caller-specific prefix exclusions (the
    bit-rot soak excludes ``trainstate/`` when the repair policy for it
    is drop-and-rebuild-next-run)."""
    excluded = _COMPARE_EXCLUDED + tuple(extra_excluded)
    base_keys = [
        k for k in baseline.list_keys() if not k.startswith(excluded)
    ]
    chaos_keys = [
        k for k in chaos.list_keys() if not k.startswith(excluded)
    ]
    missing = sorted(set(base_keys) - set(chaos_keys))
    extra = sorted(set(chaos_keys) - set(base_keys))
    mismatched: list[str] = []
    matched = 0
    for key in sorted(set(base_keys) & set(chaos_keys)):
        a = baseline.get_bytes(key)
        b = chaos.get_bytes(key)
        if key.startswith(TEST_METRICS_PREFIX):
            a = _strip_csv_column(a, "mean_response_time")
            b = _strip_csv_column(b, "mean_response_time")
        if a == b:
            matched += 1
        else:
            mismatched.append(key)
    torn: list[str] = []
    for store in (baseline, chaos):
        root = getattr(store, "root", None)
        if root is not None:
            torn.extend(
                str(p.relative_to(root))
                for p in Path(root).rglob(".tmp-*")
                # CAS sidecar locks are deliberately persistent (the
                # flock protocol must never unlink them — filesystem.py
                # _acquire_cas_lock), not abandoned write temp files
                if not p.name.startswith(".tmp-lock.")
            )
    base_cov = _snapshot_coverage(baseline)
    chaos_cov = _snapshot_coverage(chaos)
    snapshot_ok = base_cov == chaos_cov and (
        base_cov is not None or not baseline.list_keys(SNAPSHOTS_PREFIX)
    )
    if chaos_cov is None and chaos.list_keys(SNAPSHOTS_PREFIX):
        torn.append(f"{SNAPSHOTS_PREFIX} (latest snapshot unreadable)")
    journal_ok = _journals_ok(baseline) and _journals_ok(chaos)
    return {
        "matched": matched,
        "missing": missing,
        "extra": extra,
        "mismatched": mismatched,
        "torn": torn,
        "snapshot_ok": snapshot_ok,
        "journal_ok": journal_ok,
        "ok": (
            not (missing or extra or mismatched or torn)
            and snapshot_ok
            and journal_ok
        ),
    }


def _counter_values(name: str) -> dict[tuple, float]:
    from bodywork_tpu.obs import get_registry

    metric = get_registry().get(name)
    if metric is None:
        return {}
    return {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in metric.snapshot_samples()
    }


def _counter_delta(name: str, before: dict) -> dict[str, float]:
    out = {}
    for labels, value in _counter_values(name).items():
        delta = value - before.get(labels, 0.0)
        if delta:
            out[",".join(f"{k}={v}" for k, v in labels)] = delta
    return out


def run_chaos_sim(
    root: str | Path,
    start: date,
    days: int,
    plan: FaultPlan,
    model_type: str = "linear",
    scoring_mode: str = "batch",
    drift=None,
    train_mode: str = "full",
) -> dict:
    """Run the baseline and faulted simulations under ``root`` (in
    ``baseline/`` and ``chaos/`` subdirectories, which must not already
    hold artefacts) and return the comparison + fault/retry summary.

    ``train_mode="incremental"`` runs BOTH twins through the
    incremental-training path (``train/incremental.py``), putting the
    ``trainstate/`` sufficient-statistics artefact in the byte-identity
    comparison's scope — corrupt reads of it (it is in the default
    ``corrupt_prefixes``) must degrade to a rebuild that converges to
    the same bytes as the fault-free twin's."""
    from bodywork_tpu.obs.tracing import configured_tracing
    from bodywork_tpu.pipeline import LocalRunner, default_pipeline

    root = Path(root)
    baseline_dir, chaos_dir = root / "baseline", root / "chaos"
    for d in (baseline_dir, chaos_dir):
        if d.exists() and any(d.iterdir()):
            raise ValueError(
                f"chaos sim target {d} already holds artefacts; point "
                "--store at a fresh directory (the comparison needs two "
                "clean stores)"
            )
    before = {
        name: _counter_values(name)
        for name in (_FAULT_COUNTER, *_RETRY_COUNTERS)
    }

    log.info(f"chaos sim: baseline run ({days} day(s)) -> {baseline_dir}")
    baseline_store = FilesystemStore(baseline_dir)
    with configured_tracing(0.0):  # the tracing-OFF twin
        LocalRunner(
            _apply_train_mode(
                default_pipeline(model_type, scoring_mode), train_mode
            ),
            baseline_store,
            drift=drift,
        ).run_simulation(start, days)

    log.info(
        f"chaos sim: faulted run (seed={plan.seed}) -> {chaos_dir}"
    )
    real_store = FilesystemStore(chaos_dir)
    wrapped = ResilientStore(FaultInjectingStore(real_store, plan))
    # the faulted twin runs with request tracing ON at full head
    # sampling (obs.tracing): the byte-identity comparison below is
    # therefore ALSO the proof that tracing never leaks into response
    # bodies or store artefacts outside obs/flightrec/
    with activate(plan), configured_tracing(1.0, seed=plan.seed):
        LocalRunner(
            chaos_pipeline_spec(model_type, scoring_mode, train_mode),
            wrapped,
            drift=drift,
        ).run_simulation(start, days)

    comparison = compare_stores(baseline_store, real_store)
    summary = {
        "days": days,
        "seed": plan.seed,
        "plan": plan.to_dict(),
        "comparison": comparison,
        "faults_injected": _counter_delta(_FAULT_COUNTER, before[_FAULT_COUNTER]),
        "retries": {
            name: _counter_delta(name, before[name])
            for name in _RETRY_COUNTERS
        },
        "breaker_state": wrapped.breaker.state,
        # the faulted twin ran with tracing at full head sampling while
        # the baseline ran tracing-off — the comparison above is the
        # tracing byte-identity proof (ISSUE 13 acceptance)
        "tracing": {"faulted_sample_fraction": 1.0, "baseline": "off"},
        "ok": comparison["ok"],
    }
    return summary


# -- the crash soak: process death as a swept input ------------------------

#: restart attempts after a kill before giving up on the lease handover
#: (the harness shrinks the lease TTL, so the dead twin's lease expires
#: well inside one child's interpreter start-up; the retries absorb an
#: unusually fast restart racing the clock)
_RESTART_ATTEMPTS = 20
_RESTART_WAIT_S = 0.5


def sweep_points(
    days: int,
    n_steps: int,
    artefact_keys=(),
    seed: int = 0,
    store_op_samples: int = 2,
) -> list[dict]:
    """Enumerate the every-boundary kill schedule for a ``days``-day sim
    over an ``n_steps``-step DAG: one ``stage_boundary`` point per step
    barrier (``run_day`` hits one before each step plus one after the
    last, so ``days * (n_steps + 1)`` in all) plus ``store_op_samples``
    seeded MID-STAGE points — the first ``put_bytes`` of a result
    artefact key drawn from ``artefact_keys`` by the same pure
    ``(seed, kind, op, n)`` addressing every chaos decision uses (the
    kill lands before the op executes: death with the artefact
    unwritten)."""
    import random

    points: list[dict] = [
        {"kind": "stage_boundary", "n": n}
        for n in range(days * (n_steps + 1))
    ]
    eligible = sorted(
        k for k in artefact_keys
        if not k.startswith((RUNS_PREFIX, SNAPSHOTS_PREFIX))
        and not k.startswith("registry/")
    )
    if eligible and store_op_samples > 0:
        rng = random.Random(seed)
        for key in rng.sample(eligible, min(store_op_samples, len(eligible))):
            points.append(
                {"kind": "store_op", "op": "put_bytes", "key": key, "n": 0}
            )
    return points


def _runner_cmd(store_dir, start: date, days: int, model_type: str,
                scoring_mode: str, samples_per_day: int | None) -> list[str]:
    import sys

    cmd = [
        sys.executable, "-m", "bodywork_tpu.cli", "run-sim",
        "--store", str(store_dir), "--days", str(days),
        "--date", str(start), "--model", model_type, "--mode", scoring_mode,
    ]
    if samples_per_day is not None:
        cmd += ["--samples-per-day", str(samples_per_day)]
    return cmd


def _run_child(cmd: list[str], env: dict, timeout_s: float) -> tuple[int, str]:
    """Run one child runner; returns ``(exit code, output tail)``."""
    import subprocess

    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout_s
    )
    tail = ((proc.stdout or "") + "\n" + (proc.stderr or ""))[-2000:]
    return proc.returncode, tail


def run_crash_sim(
    root: str | Path,
    start: date,
    days: int,
    seed: int = 0,
    points: list[dict] | None = None,
    store_op_samples: int = 2,
    model_type: str = "linear",
    scoring_mode: str = "batch",
    samples_per_day: int | None = None,
    lease_ttl_s: float = 0.5,
    child_timeout_s: float = 900.0,
) -> dict:
    """The crash-resume soak (``cli chaos run-sim --crash-schedule``):
    prove that killing the runner PROCESS at any point converges.

    One uninterrupted twin runs the N-day sim in a subprocess under
    ``root/baseline``. Then, per kill point (every stage boundary plus
    seeded mid-stage store-op points by default — :func:`sweep_points`),
    a fresh store gets a child runner armed with that single point via
    ``BODYWORK_TPU_CRASH_SCHEDULE``; the child must die there
    (``os._exit`` — exit code :data:`chaos.kill.EXIT_KILLED`, no
    cleanup, the in-process equivalent of OOM-kill), and an unarmed
    restart must take over the shrunken lease, resume from the journal,
    and finish with final artefacts BYTE-IDENTICAL to the baseline
    (``compare_stores``: the PR 4 acceptance bar, now covering process
    death). A point the child sails past without dying fails the sweep
    — a kill that never fires would prove nothing, vacuously."""
    import json as _json
    import os as _os
    import time as _time

    from bodywork_tpu.chaos.kill import EXIT_KILLED, parse_schedule
    from bodywork_tpu.pipeline import default_pipeline
    from bodywork_tpu.pipeline.journal import LEASE_LOST_EXIT

    root = Path(root)
    baseline_dir = root / "baseline"
    if root.exists() and any(root.iterdir()):
        # a reused root is worse than a reused baseline: stale crash-NNN
        # stores hold completed journals, so the armed child would
        # resume-noop past its kill point and fail as "never fired"
        raise ValueError(
            f"crash sim target {root} already holds artefacts; point "
            "--store at a fresh directory"
        )
    base_env = {
        k: v for k, v in _os.environ.items()
        if k not in ("BODYWORK_TPU_CRASH_SCHEDULE",)
    }
    base_env["BODYWORK_TPU_RUN_LEASE_TTL_S"] = str(lease_ttl_s)
    # children must import THIS checkout's bodywork_tpu even when it is
    # not installed (dev tree, CI): prepend the package's parent dir
    import bodywork_tpu as _pkg

    pkg_root = str(Path(_pkg.__file__).resolve().parents[1])
    base_env["PYTHONPATH"] = _os.pathsep.join(
        p for p in (pkg_root, base_env.get("PYTHONPATH")) if p
    )
    cmd = _runner_cmd(baseline_dir, start, days, model_type, scoring_mode,
                      samples_per_day)
    log.info(f"crash sim: uninterrupted twin ({days} day(s)) -> {baseline_dir}")
    code, tail = _run_child(cmd, base_env, child_timeout_s)
    if code != 0:
        raise RuntimeError(
            f"crash sim baseline run failed (exit {code}):\n{tail}"
        )
    baseline_store = FilesystemStore(baseline_dir)

    if points is None:
        n_steps = len(default_pipeline(model_type, scoring_mode).dag)
        points = sweep_points(
            days, n_steps, baseline_store.list_keys(), seed=seed,
            store_op_samples=store_op_samples,
        )
    else:
        points = parse_schedule(list(points))

    results = []
    for i, point in enumerate(points):
        crash_dir = root / f"crash-{i:03d}"
        cmd = _runner_cmd(crash_dir, start, days, model_type, scoring_mode,
                          samples_per_day)
        kill_env = dict(
            base_env,
            BODYWORK_TPU_CRASH_SCHEDULE=_json.dumps([point]),
        )
        code, tail = _run_child(cmd, kill_env, child_timeout_s)
        entry = {"point": point, "kill_exit": code, "ok": False}
        if code != EXIT_KILLED:
            # exit 0 = the point never fired (vacuous; fails the sweep),
            # anything else = the child died of something OTHER than the
            # scheduled kill
            entry["error"] = (
                "kill point never fired" if code == 0
                else f"child failed before the kill point (exit {code})"
            )
            entry["tail"] = tail
            results.append(entry)
            log.error(f"crash point {point}: {entry['error']}")
            continue
        # restart, unarmed: must take over the expired lease and resume
        for attempt in range(_RESTART_ATTEMPTS):
            code, tail = _run_child(cmd, base_env, child_timeout_s)
            if code != LEASE_LOST_EXIT:
                break
            _time.sleep(_RESTART_WAIT_S)
        entry["restart_exit"] = code
        if code != 0:
            entry["error"] = f"restart did not converge (exit {code})"
            entry["tail"] = tail
            results.append(entry)
            log.error(f"crash point {point}: {entry['error']}")
            continue
        comparison = compare_stores(baseline_store, FilesystemStore(crash_dir))
        entry["comparison"] = comparison
        entry["ok"] = comparison["ok"]
        results.append(entry)
        log.info(
            f"crash point {i + 1}/{len(points)} {point}: "
            + ("converged byte-identical" if entry["ok"]
               else f"DIVERGED {comparison}")
        )
    return {
        "days": days,
        "seed": seed,
        "points": len(points),
        "results": results,
        "ok": bool(results) and all(r["ok"] for r in results),
    }
