"""Transparent fault-injecting store wrapper.

:class:`FaultInjectingStore` wraps any backend and injects the active
:class:`~bodywork_tpu.chaos.plan.FaultPlan`'s store faults at the
primitive ops. It derives from
:class:`~bodywork_tpu.store.base.DelegatingStore`, so it composes with
the rest of the wrapper stack exactly like the epoch guard does — and
because it declares no ``backend_label``, the real backend's
``bodywork_tpu_store_ops_total`` instrumentation keeps counting each
delegated call once, at the backend.

Injection semantics, chosen so every fault is *recoverable by the layer
above* (the point of the harness is to prove recovery, not to corrupt
state invisibly):

- **transient** faults raise BEFORE the op touches the backend — a
  retried op re-runs cleanly (a delete can never half-apply);
- **torn writes** persist a payload PREFIX and then raise a transient
  error — the retry's full rewrite repairs it (and if every retry were
  exhausted, the final-artefact comparison would catch the torn bytes);
- **corrupt reads** truncate the returned payload, only for key
  prefixes whose consumers carry an integrity check
  (``plan.corrupt_prefixes``: the snapshot loader validates row counts
  and falls back; registry readers validate the JSON schema and re-read
  under the consecutive cap — ``registry/records.py``);
- **latency** sleeps briefly before the op;
- ``version_token``/``version_tokens``/``exists`` get latency only:
  the token contract is "never raise".

Each op execution takes exactly ONE failure decision
(``plan.store_fault``): all failing kinds share the op stream's
consecutive-failure streak, so the plan's ``max_consecutive`` cap bounds
total consecutive failures — independent per-kind caps would compose
past the retry budget. ``get_many`` is likewise a single failure unit
(one decision per batch execution, matching the resilience layer's
retry-the-whole-batch semantics), with corruption still applied per key;
it reads sequentially so fault streams stay deterministic, trading the
backend's parallel fan-out for reproducibility — the right trade inside
a chaos run.
"""
from __future__ import annotations

from bodywork_tpu.chaos.plan import FaultPlan, InjectedFault
from bodywork_tpu.store.base import ArtefactStore, DelegatingStore

__all__ = ["FaultInjectingStore"]


class FaultInjectingStore(DelegatingStore):
    def __init__(self, inner: ArtefactStore, plan: FaultPlan):
        super().__init__(inner)
        self.plan = plan

    def _maybe_fail(self, op: str, key: str) -> None:
        if self.plan.store_fault(op, key) == "transient":
            raise InjectedFault(f"injected transient store error: {op} {key!r}")

    def put_bytes(self, key: str, data: bytes) -> None:
        self.plan.store_latency("put_bytes", key)
        fault = self.plan.store_fault("put_bytes", key)
        if fault == "transient":
            raise InjectedFault(
                f"injected transient store error: put_bytes {key!r}"
            )
        if fault == "torn_write":
            self._inner.put_bytes(key, data[: max(1, len(data) // 2)])
            raise InjectedFault(
                f"injected crash after partial write of {key!r}"
            )
        self._inner.put_bytes(key, data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        # transient-BEFORE faults only (no torn variant: the backend CAS
        # is atomic — tmp+rename / if_generation_match — so there is no
        # partial-payload state to simulate, and an applied-then-failed
        # injection would surface as a CasConflict the filesystem
        # backend cannot disambiguate, breaking the byte-identical
        # soak). The resilience layer's retry absorbs these within the
        # consecutive cap like any other op.
        self.plan.store_latency("put_bytes_if_match", key)
        self._maybe_fail("put_bytes_if_match", key)
        return self._inner.put_bytes_if_match(key, data, expected_token)

    def get_bytes(self, key: str) -> bytes:
        self.plan.store_latency("get_bytes", key)
        self._maybe_fail("get_bytes", key)
        return self.plan.corrupt_read(key, self._inner.get_bytes(key))

    def list_keys(self, prefix: str = "") -> list[str]:
        self.plan.store_latency("list_keys", prefix)
        self._maybe_fail("list_keys", prefix)
        return self._inner.list_keys(prefix)

    def delete(self, key: str) -> None:
        self.plan.store_latency("delete", key)
        self._maybe_fail("delete", key)
        self._inner.delete(key)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        # ONE failure decision for the whole batch (its stream is stable
        # across same-batch retries), then sequential per-key reads with
        # per-key corruption — the batch is the retry layer's failure
        # unit, so per-key transient streams would let N independent
        # caps compose past one batch's retry budget
        if keys:
            batch_id = f"{keys[0]}..{keys[-1]}|{len(keys)}"
            self.plan.store_latency("get_many", batch_id)
            self._maybe_fail("get_many", batch_id)
        return {
            key: self.plan.corrupt_read(key, self._inner.get_bytes(key))
            for key in keys
        }

    def exists(self, key: str) -> bool:
        self.plan.store_latency("exists", key)
        return self._inner.exists(key)

    def version_token(self, key: str):
        self.plan.store_latency("version_token", key)
        return self._inner.version_token(key)

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        self.plan.store_latency("version_tokens", keys[0] if keys else "")
        return self._inner.version_tokens(keys)
