"""Command-line interface to the framework (SURVEY.md §7 step 7).

The reference has no CLI — each stage is ``python <script>.py`` and the
pipeline is driven by the external ``bodywork`` tool. Here the framework is
its own driver:

    python -m bodywork_tpu.cli generate  --store DIR [--date D]
    python -m bodywork_tpu.cli train     --store DIR [--model linear|mlp] [--mode full|incremental]
    python -m bodywork_tpu.cli serve     --store DIR [--port P]
    python -m bodywork_tpu.cli test      --store DIR --scoring-url URL
    python -m bodywork_tpu.cli run-day   --store DIR [--date D]
    python -m bodywork_tpu.cli run-sim   --store DIR --days N [--model ...]
    python -m bodywork_tpu.cli run-ab    --store DIR --days N [--models a,b]
    python -m bodywork_tpu.cli run-stage --store DIR --stage NAME ...
    python -m bodywork_tpu.cli report    --store DIR
    python -m bodywork_tpu.cli compact   --store DIR [--dry-run]
    python -m bodywork_tpu.cli deploy    --out DIR [--store-path P] [--image I]
    python -m bodywork_tpu.cli chaos run-sim --store DIR --days N [--seed S] [--plan F] [--bit-rot]
    python -m bodywork_tpu.cli chaos canary  --store DIR --scenario nan|latency|healthy
    python -m bodywork_tpu.cli fsck      --store DIR [--repair] [--json]
    python -m bodywork_tpu.cli registry list|show|promote|rollback|gate --store DIR ...
    python -m bodywork_tpu.cli registry canary start|stop|promote|status --store DIR ...
    python -m bodywork_tpu.cli traffic run --url URL [--rate R] [--duration S] ...
    python -m bodywork_tpu.cli trace show|tail|export --store DIR ...
    python -m bodywork_tpu.cli tune     --store DIR [--traffic-log F] [--dry-run]

Every command exits 0 on success and 1 with a logged error otherwise — the
exit-code contract the reference implements per-script
(``stage_1_train_model.py:170-178``) and the orchestrator relies on.
``run-day`` extends it with documented non-error codes (docs/RESILIENCE.md):
5 = run lease lost to another runner, 6 = resumed-noop (day already
complete, journal-verified), 143 = graceful SIGTERM unwind; ``report
--fail-on-drift`` exits 4, ``fsck`` exits 7 when actionable integrity
findings remain, ``registry rollback`` exits 8 when the restore target
fails pre-verification, ``trace`` exits 9 when the requested trace or
dump is not recorded, and a chaos kill switch exits 86.
"""
from __future__ import annotations

import argparse
import os
import sys
from datetime import date

from bodywork_tpu.utils.dates import parse_date
from bodywork_tpu.utils.errors import init_error_monitoring
from bodywork_tpu.utils.logging import configure_logger, get_logger

log = get_logger("cli")


def _store(args):
    from bodywork_tpu.store import open_store
    from bodywork_tpu.tenancy.namespace import scoped_store

    return scoped_store(open_store(args.store), _tenant_id(args))


def _tenant_id(args) -> str:
    """Resolve the command's tenant: ``--tenant`` flag > env
    ``BODYWORK_TPU_TENANT`` > the default (root) namespace. The flag is
    validated STRICTLY — a typo'd ``--tenant`` must fail loudly, never
    silently read/write the root namespace — while the env degrades to
    default with a warning (the stages convention for malformed env).
    Both funnel through ``schema.validate_tenant_id``, so flag, env,
    and key charset can never drift apart (guard-pinned by
    tests/test_tenancy.py)."""
    from bodywork_tpu.store.schema import validate_tenant_id
    from bodywork_tpu.tenancy.namespace import tenant_from_env

    tenant = getattr(args, "tenant", None)
    if tenant is not None:
        validate_tenant_id(tenant)
        return tenant
    return tenant_from_env()


def _date(args) -> date:
    return parse_date(args.date) if args.date else date.today()


def _pipeline_spec(args):
    """The pipeline spec for orchestration commands: an explicit ``--spec``
    YAML wins (this is how in-cluster pods receive the deploy-time spec via
    ConfigMap); otherwise the default pipeline built from CLI options."""
    from bodywork_tpu.pipeline import PipelineSpec, default_pipeline

    if getattr(args, "spec", None):
        from pathlib import Path

        return PipelineSpec.from_yaml(Path(args.spec).read_text())
    return default_pipeline(args.model, args.mode)


def cmd_generate(args) -> int:
    from bodywork_tpu.data import Dataset, generate_day, persist_dataset

    d = _date(args)
    X, y = generate_day(d)
    key = persist_dataset(_store(args), Dataset(X, y, d))
    print(key)
    return 0


def cmd_train(args) -> int:
    from bodywork_tpu.train import train_on_history
    from bodywork_tpu.utils.profiling import maybe_trace

    with maybe_trace(args.profile_dir, label="train"):
        result = train_on_history(
            _store(args),
            args.model,
            mesh_data=args.mesh_data,
            mesh_model=args.mesh_model,
            mode=args.mode,
        )
    fallback = (
        f" fallback={result.fallback_reason}" if result.fallback_reason else ""
    )
    print(
        f"{result.model_artefact_key} MAPE={result.metrics['MAPE']:.4f} "
        f"r2={result.metrics['r_squared']:.4f} mode={result.mode} "
        f"rows_touched={result.rows_touched}{fallback}"
    )
    return 0


def _bucket_list(raw: str) -> tuple[int, ...]:
    """argparse type for --buckets: comma-separated POSITIVE ints (a zero
    or negative bucket would fail warmup or 500 every request at runtime
    — reject it at the parser with a clear message instead)."""
    try:
        buckets = tuple(int(b) for b in raw.split(",") if b.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"buckets must be comma-separated integers, got {raw!r}"
        )
    if not buckets or any(b <= 0 for b in buckets):
        raise argparse.ArgumentTypeError(
            f"buckets must be positive integers, got {raw!r}"
        )
    return buckets


def _positive_int(raw: str) -> int:
    """argparse type for --window: a zero window silently disables the
    drift gate (tail(0) is empty -> never drifted) and a negative one
    means "all but the first N" — reject both at the parser (exit 2,
    usage error) instead of at the rule (exit 1)."""
    try:
        value = int(raw)
    except ValueError:
        raise argparse.ArgumentTypeError(f"must be an integer, got {raw!r}")
    if value < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {raw}")
    return value


def _env_choice(name: str, choices: tuple, default: str):
    """Parser-build-time env default for an enum flag: an unknown value
    is ignored with a stderr note (same contract as :func:`_env_number`)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    value = raw.strip()
    if value not in choices:
        print(
            f"warning: ignoring {name}={raw!r} (expected one of "
            f"{', '.join(choices)})",
            file=sys.stderr,
        )
        return default
    return value


def _env_number(name: str, cast, minimum):
    """Parser-build-time env default: a malformed or out-of-range value
    is IGNORED with a stderr note rather than crashing every subcommand
    at build_parser() (these env vars only concern `serve`). The flag's
    own argparse type still validates explicit command-line values."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    try:
        value = cast(raw)
    except ValueError:
        value = None
    if value is None or value < minimum:
        print(
            f"warning: ignoring {name}={raw!r} (need a number >= {minimum})",
            file=sys.stderr,
        )
        return None
    return value


def _env_paths(name: str) -> list:
    """Parser-build-time env default for a repeatable path flag: a
    colon-separated list (the PATH convention — the k8s Deployment
    cannot repeat a flag through one env var). Empty segments drop."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return []
    return [p for p in raw.split(":") if p.strip()]


def _env_flag(name: str) -> bool:
    """Parser-build-time env default for a boolean flag: malformed
    values degrade to False with a stderr note (the same contract as
    :func:`_env_choice` — a typo'd deploy knob must not crash every
    subcommand, and the stages env parser degrades identically)."""
    raw = os.environ.get(name)
    if raw is None:
        return False
    value = raw.strip().lower()
    if value in ("1", "true", "yes", "on"):
        return True
    if value in ("", "0", "false", "no", "off"):
        return False
    print(
        f"warning: ignoring {name}={raw!r} (expected a boolean like "
        "1/0/true/false)",
        file=sys.stderr,
    )
    return False


def _serve_dispatcher_role(args, transport: str, watch, batch_window) -> int:
    """``serve --role dispatcher``: the device-owning half of the
    cross-host split, standalone — serves the socket row-queue instead
    of HTTP (the k8s dispatcher Deployment's entrypoint). No supervisor
    wraps it here; the Deployment's restartPolicy is the respawn loop,
    and the front-ends' reconnect backoff is the heal path."""
    from bodywork_tpu.serve.dispatch import dispatcher_main
    from bodywork_tpu.serve.netqueue import (
        DEFAULT_DISPATCHER_PORT,
        parse_dispatcher_addr,
    )

    if transport == "shm":
        log.error("--role dispatcher needs --transport tcp or unix "
                  "(remote front-ends cannot attach to this process's "
                  "shared memory)")
        return 1
    addr = args.dispatcher_addr
    if not addr:
        if transport == "unix":
            log.error("--role dispatcher with --transport unix needs "
                      "--dispatcher-addr (the socket path to bind)")
            return 1
        # tcp default: every interface on the well-known port, which is
        # what the dispatcher k8s Service targets
        addr = f"0.0.0.0:{DEFAULT_DISPATCHER_PORT}"
    try:
        parsed = parse_dispatcher_addr(transport, addr)
    except ValueError as exc:
        log.error(str(exc))
        return 1
    # dispatcher_main installs its own SIGTERM -> clean-exit handler
    # (it is the same entrypoint the fleet supervisor spawns), so no
    # graceful_sigterm wrapper here
    dispatcher_main(
        args.store, None, None,
        engine=args.engine,
        watch_interval_s=watch,
        buckets=args.buckets,
        batch_window_ms=batch_window,
        batch_max_rows=args.batch_max_rows,
        dtype=args.dtype,
        tuned_config=args.tuned_config,
        transport=transport,
        dispatcher_addr=parsed,
    )
    return 0


def cmd_serve(args) -> int:
    from bodywork_tpu.utils.shutdown import (
        SIGTERM_EXIT,
        ShutdownRequested,
        graceful_sigterm,
    )

    watch = args.reload_interval if args.reload_interval > 0 else None
    # None = unset (a tuned config may fill it), 0 = EXPLICIT coalescing
    # off (beats the tuned document — explicit always wins), > 0 = on;
    # a negative value degrades to unset as before
    batch_window = (
        args.batch_window_ms
        if args.batch_window_ms is not None and args.batch_window_ms >= 0
        else None
    )
    if args.batch_max_rows and not batch_window and not args.tuned_config:
        # max-rows alone would silently serve unbatched — the window is
        # the coalescer's on-switch
        log.warning(
            "--batch-max-rows has no effect without --batch-window-ms; "
            "request coalescing stays OFF"
        )
    frontends = getattr(args, "frontends", None)
    transport = getattr(args, "transport", "shm")
    role = getattr(args, "role", "auto")
    standby = bool(getattr(args, "standby", False))
    if frontends is not None and frontends >= 1 and args.workers > 1:
        # two incompatible scale-out topologies: replicas each own a
        # model; front-ends share the one dispatcher's
        log.error("--frontends and --workers are mutually exclusive "
                  "scale-out modes; pick one")
        return 1
    if transport != "shm" and role == "auto" and not frontends:
        # the socket transports carry the front-end -> dispatcher
        # handoff; --workers replicas have no such handoff to move
        log.error("--transport tcp/unix requires --frontends N "
                  "(or a split --role)")
        return 1
    if standby and transport == "shm":
        log.error("--standby needs --transport tcp or unix: the shm "
                  "queue is single-host, where the supervisor respawn "
                  "is already the takeover path")
        return 1
    if role == "dispatcher":
        if not standby:
            return _serve_dispatcher_role(args, transport, watch,
                                          batch_window)
        # --role dispatcher --standby: the active/standby PAIR under
        # one supervisor — no local HTTP, two warm candidates, CAS
        # lease arbitration (serve.leadership). Falls through to the
        # MultiProcessService branch with frontends=0.
        from bodywork_tpu.serve.netqueue import DEFAULT_DISPATCHER_PORT

        if not args.dispatcher_addr:
            if transport == "unix":
                log.error("--role dispatcher with --transport unix "
                          "needs --dispatcher-addr (the socket path "
                          "to bind)")
                return 1
            args.dispatcher_addr = f"0.0.0.0:{DEFAULT_DISPATCHER_PORT}"
        frontends = 0
    if role == "frontend":
        if standby:
            # front-ends need no flag to ride a failover: the standby
            # pair announces itself through the lease/fence alone
            log.warning("--standby concerns the dispatcher side; "
                        "ignoring it for --role frontend")
            standby = False
        if transport == "shm":
            log.error("--role frontend needs --transport tcp or unix "
                      "(a remote dispatcher is not reachable over "
                      "shared memory)")
            return 1
        if not args.dispatcher_addr:
            log.error("--role frontend needs --dispatcher-addr "
                      "(the dispatcher Service/host to connect to)")
            return 1
        frontends = frontends or 1
    if (args.workers and args.workers > 1) or (
        frontends is not None and (frontends >= 1 or standby)
    ):
        # real OS-process replicas on one SO_REUSEPORT port (the local
        # materialisation of the reference's `replicas: 2` Deployment);
        # single-device engines only — each worker owns its own params.
        # --frontends instead splits roles: N parse/admission processes
        # + one device-owning dispatcher behind a shared-memory queue
        if (args.mesh_data and args.mesh_data > 1) or args.mesh_model > 1:
            log.error(
                "--workers/--frontends is per-process serving; drop "
                "--mesh-data/--mesh-model"
            )
            return 1
        from bodywork_tpu.serve import MultiProcessService

        svc = MultiProcessService(
            args.store, host=args.host, port=args.port,
            workers=args.workers, engine=args.engine,
            watch_interval_s=watch, buckets=args.buckets,
            batch_window_ms=batch_window,
            batch_max_rows=args.batch_max_rows,
            metrics=args.metrics,
            server_engine=args.server_engine,
            max_pending=args.max_pending,
            retry_after_max_s=args.retry_after_max_s,
            dtype=args.dtype,
            tuned_config=args.tuned_config,
            frontends=frontends,
            transport=transport,
            dispatcher_addr=getattr(args, "dispatcher_addr", None),
            external_dispatcher=(role == "frontend"),
            standby=standby,
        ).start()
        if svc.metrics_url:
            log.info(f"aggregated metrics at {svc.metrics_url}")
        try:
            # SIGTERM (k8s pod stop): unwind the wait and terminate the
            # replica processes INSIDE the armed watchdog, so a wedged
            # worker join is force-bounded to the grace deadline (the
            # kubelet's SIGKILL must never win the teardown race)
            with graceful_sigterm() as sigterm_fired:
                try:
                    svc.wait()
                except ShutdownRequested:
                    log.warning("SIGTERM: stopping serving replicas")
                    svc.stop()
            return SIGTERM_EXIT if sigterm_fired.is_set() else 0
        except KeyboardInterrupt:
            return 0
        finally:
            svc.stop()
    from bodywork_tpu.serve import serve_latest_model

    # single-process path: serve_latest_model catches ShutdownRequested
    # itself — admission drains (429 + Retry-After on new work) before
    # the listener closes — and returns; a SIGTERM landing BEFORE
    # serve_forever (model load / XLA compile at startup) unwinds to
    # the except here instead. `fired` tells us it was a SIGTERM unwind
    # rather than a normal stop.
    with graceful_sigterm() as sigterm_fired:
        try:
            serve_latest_model(
                _store(args),
                host=args.host,
                port=args.port,
                block=True,
                mesh_data=args.mesh_data,
                mesh_model=args.mesh_model,
                engine=args.engine,
                watch_interval_s=watch,
                buckets=args.buckets,
                batch_window_ms=batch_window,
                batch_max_rows=args.batch_max_rows,
                server_engine=args.server_engine,
                max_pending=args.max_pending,
                retry_after_max_s=args.retry_after_max_s,
                dtype=args.dtype,
                tuned_config=args.tuned_config,
                online_tune=bool(getattr(args, "online_tune", False)),
                tune_request_logs=tuple(
                    getattr(args, "tune_request_log", None) or ()
                ),
                tune_results_logs=tuple(
                    getattr(args, "tune_results_log", None) or ()
                ),
                cost_budget_s=getattr(args, "cost_budget_s", None),
            )
        except ShutdownRequested:
            log.warning("SIGTERM during service startup; exiting")
    return SIGTERM_EXIT if sigterm_fired.is_set() else 0


def _tune_status(store) -> int:
    """``cli tune status``: the active tuned config — digest, the
    observation window it was fitted from, per-knob source (tuned /
    default / env-override) — plus the applied/reverted lifecycle
    history from the config log. Exit 1 on a CORRUPT document (config
    log or named tuned config): status is the operator's audit read,
    and "corrupt" must never render as "defaults"."""
    import json
    import os

    from bodywork_tpu.registry.configlog import (
        ConfigLogCorrupt,
        read_config_log,
    )
    from bodywork_tpu.tune.config import (
        KNOB_DEFAULTS,
        TUNED_KNOB_ENV,
        _resolve_ref,
        load_tuned_config,
    )

    try:
        log_doc = read_config_log(store)
    except ConfigLogCorrupt as exc:
        log.error(str(exc))
        return 1
    # the active document: the config log's say when one exists (the
    # online controller's apply/revert ledger), else the newest tuned
    # document (what a `--tuned-config latest` boot would serve)
    active_key = None
    if log_doc is not None and log_doc.get("active"):
        active_key = log_doc["active"]["key"]
    else:
        active_key = _resolve_ref(store, "latest")
    knobs = digest = doc = None
    if active_key is not None:
        knobs, digest, doc = load_tuned_config(store, active_key)
        if doc is None:
            # the key EXISTS as the active config but does not load:
            # that is corruption (load_tuned_config already warned with
            # the specific failure), not "no config"
            log.error(
                f"active tuned config {active_key!r} is unreadable or "
                "fails validation"
            )
            return 1
    per_knob = {}
    for knob, env_name in TUNED_KNOB_ENV.items():
        if os.environ.get(env_name, "").strip():
            per_knob[knob] = {
                "source": "env-override",
                "value": os.environ[env_name].strip(),
            }
        elif knobs is not None and knob in knobs:
            per_knob[knob] = {"source": "tuned", "value": knobs[knob]}
        else:
            default = KNOB_DEFAULTS.get(knob)
            per_knob[knob] = {
                "source": "default",
                "value": list(default) if isinstance(default, tuple)
                else default,
            }
    out = {
        "active": (
            {
                "key": active_key,
                "digest": digest,
                "observations": doc.get("observations"),
                "cost_model": doc.get("cost_model"),
            }
            if doc is not None else None
        ),
        "knobs": per_knob,
        "config_log": (
            {
                "rev": log_doc.get("rev"),
                "last_op": log_doc.get("last_op"),
                "previous": (
                    log_doc["previous"]["digest"]
                    if log_doc.get("previous") else None
                ),
                "history": log_doc.get("history"),
            }
            if log_doc is not None else None
        ),
    }
    print(json.dumps(out, indent=2))
    return 0


def cmd_tune(args) -> int:
    """Fit the serving knobs from observed traces (ROADMAP item 5,
    docs/PERF.md §config 13): ingest traffic request/results logs, obs
    snapshots, and day reports into one observation table, probe the
    serving checkpoint's per-bucket dispatch-cost curve, fit the cost
    model, and persist the tuned config under ``tuning/`` — the
    document ``serve --tuned-config latest`` (or the deployed
    BODYWORK_TPU_TUNED_CONFIG env knob) consumes. stdout is exactly ONE
    JSON document (key, digest, knobs, decision trace).

    ``cli tune status`` instead reports the ACTIVE config: digest,
    fitted observation window, per-knob source, and the online
    controller's apply/revert history (:func:`_tune_status`)."""
    from bodywork_tpu.obs.spans import SpanRecorder, write_chrome_trace
    from bodywork_tpu.tune.collect import (
        ObservationTable,
        ingest_day_report,
        ingest_obs_snapshot,
        ingest_request_log,
        ingest_results_log,
        probe_dispatch_costs,
    )
    from bodywork_tpu.tune.config import KNOB_DEFAULTS, write_tuned_config
    from bodywork_tpu.tune.model import fit_tuned_config

    configure_logger(stream=sys.stderr)
    import json

    store = _store(args)
    if getattr(args, "action", "fit") == "status":
        return _tune_status(store)
    table = ObservationTable()
    try:
        for path in args.traffic_log or ():
            n = ingest_request_log(table, path)
            log.info(f"ingested {n} scheduled requests from {path}")
        for path in args.results_log or ():
            n = ingest_results_log(table, path)
            log.info(f"ingested {n} request outcomes from {path}")
        for path in args.obs_snapshot or ():
            ingest_obs_snapshot(table, path)
            log.info(f"ingested obs snapshot {path}")
        for path in args.day_report or ():
            ingest_day_report(table, path)
            log.info(f"ingested day report {path}")
    except (OSError, ValueError, KeyError) as exc:
        log.error(f"trace ingestion failed: {exc}")
        return 1
    if args.probe:
        try:
            table.dispatch_cost_s = probe_dispatch_costs(
                store, tuple(args.probe_buckets), reps=args.probe_reps
            )
            table.sources.append("dispatch_probe")
        except Exception as exc:
            # no serviceable checkpoint (empty store) or a device fault:
            # the probe is one evidence source, not a precondition
            log.warning(f"dispatch-cost probe unavailable ({exc!r}); "
                        "fitting from passive traces only")
    cost_model_out = None
    if table.dispatch_cost_s and not args.dry_run:
        # a measured cost curve is also training data for the LEARNED
        # cost model (tune/costmodel.py): fit + persist it alongside
        # the tuned config so the online controller and the cost-priced
        # shed can price what the probe never measured. Best-effort —
        # a thin curve (< MIN_SAMPLES rungs) just skips.
        try:
            from bodywork_tpu.models.checkpoint import (
                load_model,
                resolve_serving_key,
            )
            from bodywork_tpu.tune.costmodel import (
                fit_cost_model,
                samples_from_probe,
                write_cost_model,
            )

            serving_key, _src = resolve_serving_key(store)
            model, _day = load_model(store, serving_key)
            samples = samples_from_probe(
                table.dispatch_cost_s, model.n_features or 1
            )
            cm_doc = fit_cost_model(samples)
            cm_key, cm_digest = write_cost_model(store, cm_doc, _date(args))
            cost_model_out = {
                "key": cm_key, "digest": cm_digest,
                "holdout": cm_doc["holdout"],
            }
            log.info(
                f"cost model -> {cm_key} (holdout mean rel err "
                f"{cm_doc['holdout']['mean_rel_err']:.3f})"
            )
        except Exception as exc:
            log.warning(f"cost-model fit skipped ({exc!r})")
    if not table.sources:
        log.error(
            "nothing to tune from: no traces ingested and no probe — "
            "pass --traffic-log/--results-log/--obs-snapshot/"
            "--day-report or point --store at a store with a "
            "serviceable checkpoint"
        )
        return 1
    recorder = SpanRecorder(label="tune")
    doc = fit_tuned_config(table, recorder=recorder)
    if args.trace_out:
        write_chrome_trace(args.trace_out, recorder.spans())
        log.info(f"decision trace -> {args.trace_out}")
    out: dict = {
        "knobs": doc["knobs"],
        "defaults": {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in KNOB_DEFAULTS.items()
        },
        "decisions": doc["decisions"],
        "observations": doc["observations"],
        "cost_model": cost_model_out,
    }
    if args.dry_run:
        out["key"] = None
        out["dry_run"] = True
    elif not doc["knobs"]:
        # every knob kept its default (evidence insufficient): there is
        # nothing for serving to consume, and an empty document would
        # only make `--tuned-config latest` degrade with a warning —
        # report the (still useful) decision trace, persist nothing
        log.warning(
            "no knob left its default (insufficient evidence) — "
            "nothing persisted; see the decision trace for what was "
            "missing"
        )
        out["key"] = None
        out["nothing_fitted"] = True
    else:
        try:
            key, digest = write_tuned_config(store, doc, day=_date(args))
        except (OSError, ValueError) as exc:
            log.error(f"failed to persist tuned config: {exc}")
            return 1
        out["key"] = key
        out["digest"] = digest
    print(json.dumps(out, indent=2))
    return 0


def cmd_traffic_run(args) -> int:
    """Open-loop load run (docs/PERF.md §config 9): generate — or replay
    — a seeded request log and drive it at its scheduled arrival times
    against a live scoring service."""
    from bodywork_tpu.traffic import (
        TrafficConfig,
        generate_request_log,
        read_request_log,
        run_open_loop,
        write_request_log,
    )
    from bodywork_tpu.traffic.runner import format_report

    # stdout carries exactly ONE JSON document (the load report) so the
    # command composes with jq/scripts; logs go to stderr, as bench.py
    configure_logger(stream=sys.stderr)

    try:
        if args.log_in:
            config, requests = read_request_log(args.log_in)
            log.info(
                f"replaying {len(requests)} requests from {args.log_in} "
                f"(seed {config.seed}, {config.arrival})"
            )
        else:
            config = TrafficConfig(
                rate_rps=args.rate,
                duration_s=args.duration,
                arrival=args.arrival,
                batch_fraction=args.batch_fraction,
                batch_rows=args.batch_rows,
                seed=args.seed,
                burst_multiplier=args.burst_multiplier,
            )
            requests = generate_request_log(config)
        if args.log_out:
            write_request_log(args.log_out, config, requests)
        if args.url is None:
            if not args.log_out:
                log.error("nothing to do: need --url (drive) or "
                          "--log-out (generate only)")
                return 1
            return 0
        report = run_open_loop(
            args.url, requests, timeout_s=args.timeout,
            results_log=args.results_out,
            transport_kind=getattr(args, "transport", "json"),
            shards=getattr(args, "shards", 1),
        )
        print(format_report(report))
        return 0
    except (OSError, ValueError) as exc:
        log.error(f"traffic run failed: {exc}")
        return 1


def cmd_test(args) -> int:
    from bodywork_tpu.monitor import (
        HttpScoringClient,
        run_service_test,
        scoring_endpoint,
    )

    client = HttpScoringClient(scoring_endpoint(args.scoring_url, args.mode))
    metrics = run_service_test(_store(args), client, mode=args.mode)
    print(metrics.to_string(index=False))
    return 0


def _derived_report_path(trace_out: str) -> str:
    """``day.trace.json`` -> ``day.report.json`` (or ``<path>.report.json``
    when the trace path has no ``.trace.json`` suffix)."""
    if trace_out.endswith(".trace.json"):
        return trace_out[: -len(".trace.json")] + ".report.json"
    return trace_out + ".report.json"


#: date-keyed trace/report files kept per {date} template (the daily-loop
#: CronJob writes one pair per day onto the shared store volume; without
#: a cap nothing in the system would ever prune them)
TRACE_RETENTION = 30


def _prune_templated(template: str, keep: int = TRACE_RETENTION) -> None:
    """Drop all but the newest ``keep`` files matching a ``{date}``
    template. ISO dates sort lexicographically, so the glob's sorted
    order IS chronological. Only templated paths are pruned — an
    explicit one-off path is the operator's to manage."""
    import glob

    # escape the operator-controlled path FIRST: a '[v2]' or '*' in the
    # store path must stay literal, or retention silently never fires
    # (or worse, matches files outside the intended set)
    files = sorted(glob.glob(glob.escape(template).replace("{date}", "*")))
    for old in files[:-keep]:
        try:
            os.remove(old)
        except OSError:  # concurrent pruner / already gone: not our problem
            pass


def cmd_run_day(args) -> int:
    """One simulated day, crash-resumable by default (the daily CronJob
    pod's entrypoint). Exit codes — documented in docs/RESILIENCE.md:
    0 success, 1 stage failure/error, 2 usage, 3 backend unreachable,
    5 lease lost (another runner owns the day — stop, retry later),
    6 resumed-noop (journal says the day already completed and every
    artefact digest verified; nothing re-ran), 143 graceful SIGTERM."""
    from bodywork_tpu.chaos.kill import arm_from_env, wrap_store
    from bodywork_tpu.pipeline import LocalRunner
    from bodywork_tpu.pipeline.journal import (
        LEASE_LOST_EXIT,
        RESUMED_NOOP_EXIT,
        LeaseLost,
    )
    from bodywork_tpu.utils.shutdown import (
        SIGTERM_EXIT,
        ShutdownRequested,
        graceful_sigterm,
    )

    arm_from_env()  # the crash soak's child-runner kill schedule
    runner = LocalRunner(_pipeline_spec(args), wrap_store(_store(args)))
    d = _date(args)
    try:
        with graceful_sigterm():
            runner.bootstrap(d)
            result = runner.run_day(d, resume=not args.no_resume)
    except LeaseLost as exc:
        log.error(f"{exc}; exiting {LEASE_LOST_EXIT} (lease lost)")
        return LEASE_LOST_EXIT
    except ShutdownRequested:
        log.warning(
            "run-day interrupted by SIGTERM; journal marks the day "
            "'interrupted' — the next run resumes from it"
        )
        # the journal writes are already durable; skip interpreter
        # finalization, which SEGFAULTS when a daemon stage thread is
        # still inside an XLA compile (verified live: exit -11 instead
        # of 143 without this)
        os._exit(SIGTERM_EXIT)
    if result.noop:
        print(f"day {d}: already complete (resumed as a no-op)")
        return RESUMED_NOOP_EXIT
    if result.skipped_stages:
        print(
            f"day {d}: resumed — skipped "
            f"{', '.join(result.skipped_stages)} (journal-verified)"
        )
    print(f"day {d}: {result.wall_clock_s:.3f}s")
    for name, secs in result.stage_seconds.items():
        print(f"  {name}: {secs:.3f}s")
    fsck_report = None
    if args.scrub:
        # detect-only integrity scrub after the day converges; findings
        # ride the day report (and the audit counters) so the daily
        # CronJob doubles as a scrub cadence without a second pod
        from bodywork_tpu.audit import run_fsck

        fsck_report = run_fsck(runner.store, repair=False)
        by_sev = fsck_report["by_severity"]
        print(
            "fsck: "
            + (
                ", ".join(f"{s}={n}" for s, n in sorted(by_sev.items()))
                or "clean"
            )
        )
    if args.trace_out or args.report_out:
        from bodywork_tpu.obs.spans import (
            day_report,
            write_chrome_trace,
            write_day_report,
        )

        # a literal "{date}" in either path becomes the simulated day —
        # lets the (date-agnostic) daily-loop CronJob command emit
        # date-keyed trace artefacts on the shared store
        trace_out = (
            args.trace_out.replace("{date}", str(d))
            if args.trace_out else None
        )
        report_out = (
            args.report_out.replace("{date}", str(d))
            if args.report_out else None
        )
        if trace_out:
            # the FULL runner timeline (bootstrap + every span), so the
            # Perfetto view shows setup cost next to the day's stages
            path = write_chrome_trace(
                trace_out, runner.recorder.spans(),
                process_name=f"run-day {d}",
            )
            print(f"trace: {path}")
        report_path = report_out or _derived_report_path(trace_out)
        path = write_day_report(
            report_path,
            day_report(result, fsck=fsck_report, tenant=_tenant_id(args))
        )
        print(f"report: {path}")
        # retention for date-templated outputs (the daily CronJob path):
        # keep the newest TRACE_RETENTION days, so the shared store
        # volume never grows unboundedly under the dotted .traces dir
        if args.trace_out and "{date}" in args.trace_out:
            _prune_templated(args.trace_out)
            if not args.report_out:
                _prune_templated(_derived_report_path(args.trace_out))
        if args.report_out and "{date}" in args.report_out:
            _prune_templated(args.report_out)
    return 0


def cmd_run_sim(args) -> int:
    from bodywork_tpu.chaos.kill import arm_from_env, wrap_store
    from bodywork_tpu.pipeline import LocalRunner
    from bodywork_tpu.pipeline.journal import LEASE_LOST_EXIT, LeaseLost
    from bodywork_tpu.utils.shutdown import (
        SIGTERM_EXIT,
        ShutdownRequested,
        graceful_sigterm,
    )

    arm_from_env()  # the crash soak's child-runner kill schedule
    drift = None
    if getattr(args, "samples_per_day", None) is not None:
        from bodywork_tpu.data.drift_config import DriftConfig

        drift = DriftConfig(n_samples=args.samples_per_day)
    runner = LocalRunner(
        _pipeline_spec(args), wrap_store(_store(args)), drift=drift
    )
    try:
        with graceful_sigterm():
            results = runner.run_simulation(
                _date(args), args.days, profile_dir=args.profile_dir
            )
    except LeaseLost as exc:
        log.error(f"{exc}; exiting {LEASE_LOST_EXIT} (lease lost)")
        return LEASE_LOST_EXIT
    except ShutdownRequested:
        log.warning("run-sim interrupted by SIGTERM; journals mark the "
                    "in-flight day 'interrupted' — a re-run resumes")
        # see cmd_run_day: a live XLA compile on a daemon stage thread
        # segfaults interpreter finalization; the journal is durable
        os._exit(SIGTERM_EXIT)
    total = sum(r.wall_clock_s for r in results)
    for r in results:
        print(f"day {r.day}: {r.wall_clock_s:.3f}s")
    print(f"total {total:.3f}s over {args.days} day(s), "
          f"mean {total / max(args.days, 1):.3f}s/day")
    if args.trace_out:
        from bodywork_tpu.obs.spans import write_chrome_trace

        path = write_chrome_trace(
            args.trace_out, runner.recorder.spans(),
            process_name=f"run-sim {args.days}d",
        )
        print(f"trace: {path}")
    return 0


def cmd_run_ab(args) -> int:
    """Run N model variants as concurrent isolated pipelines sharing the
    device pool (BASELINE.json config 5) and print the comparison."""
    from bodywork_tpu.pipeline import (
        compare_report,
        run_ab_simulation,
        variants_from_model_types,
    )

    variants = variants_from_model_types(args.models.split(","))
    results = run_ab_simulation(variants, args.store, _date(args), args.days)
    failed = [v for v in results.values() if v.error is not None]
    report = compare_report(results)
    if not report.empty:
        print(report.to_string(index=False))
    for v in failed:
        print(f"variant {v.name} FAILED: {v.error!r}")
    return 1 if failed else 0


def cmd_run_stage(args) -> int:
    """Run one named stage from the default pipeline — the per-pod entrypoint
    the k8s manifests use."""
    from bodywork_tpu.pipeline.runner import resolve_executable
    from bodywork_tpu.pipeline.stages import StageContext

    from bodywork_tpu.utils.errors import tag_stage

    spec = _pipeline_spec(args)
    if args.stage not in spec.stages:
        log.error(f"unknown stage {args.stage!r}; have {sorted(spec.stages)}")
        return 1
    # every stage pod reports under its own stage name, not the shared
    # 'cli-run-stage' tag main() set before the stage was known
    tag_stage(args.stage)
    stage = spec.stages[args.stage]
    ctx = StageContext(
        store=_store(args), today=_date(args), scoring_url=args.scoring_url
    )
    fn = resolve_executable(stage.executable)
    if stage.kind == "service":
        # run the stage's declared executable and block for the pod's
        # lifetime, exposed on the declared port
        handle = fn(ctx, host="0.0.0.0", port=stage.port or 5000, **stage.args)
        handle.wait()
    else:
        fn(ctx, **stage.args)
    return 0


def cmd_wait_for(args) -> int:
    """Block until pipeline preconditions hold — the DAG-ordering gate for
    the k8s materialisation (used as Job initContainers, replacing the
    Bodywork controller's step sequencing)."""
    import time as _time

    from bodywork_tpu.store.schema import DATASETS_PREFIX, MODELS_PREFIX

    store = _store(args)
    deadline = _time.monotonic() + args.timeout

    def _conditions_met() -> bool:
        if args.dataset and not store.history(DATASETS_PREFIX):
            return False
        if args.model and not store.history(MODELS_PREFIX):
            return False
        if args.dataset_newer_than_model:
            datasets = store.history(DATASETS_PREFIX)
            models = store.history(MODELS_PREFIX)
            if not datasets or not models:
                return False
            if datasets[-1][1] <= models[-1][1]:
                return False
        if args.service_url:
            import requests

            try:
                if not requests.get(args.service_url, timeout=2).ok:
                    return False
            except requests.RequestException:
                return False
        return True

    while not _conditions_met():
        if _time.monotonic() > deadline:
            log.error(f"wait-for conditions not met within {args.timeout}s")
            return 1
        _time.sleep(args.poll_interval)
    print("conditions met")
    return 0


#: ``report --fail-on-drift`` exit code. Deliberately NOT 1 (generic CLI
#: failure), 2 (argparse usage error — a gate keyed on 2 would fire on a
#: mistyped flag), or 3 (backend-unreachable, utils.watchdog).
DRIFT_EXIT = 4

#: ``fsck`` exit when ACTIONABLE integrity findings remain after the
#: scan (and, with ``--repair``, after the repairs that could run) —
#: the scrub CronJob's k8s-native alarm, distinct from every other
#: documented code (docs/RESILIENCE.md §11).
FSCK_FINDINGS_EXIT = 7

#: ``registry rollback`` exit when the restore target fails
#: pre-verification (missing ``previous`` checkpoint, or bytes that no
#: longer match the record's lineage digest): the alias did NOT move.
#: Distinct from 1 so an automated rollback wrapper can tell "refused
#: for your protection — run fsck" from a generic failure.
ROLLBACK_REFUSED_EXIT = 8


def cmd_report(args) -> int:
    from bodywork_tpu.monitor import detect_drift, drift_report

    store = _store(args)
    report = drift_report(store)
    if report.empty:
        print("no metric history yet")
        return 0
    print(report.to_string(index=False))
    if args.plot:
        from bodywork_tpu.monitor import render_drift_dashboard

        # a failure here (e.g. matplotlib missing) propagates to main()'s
        # catch-all: logged error + exit 1, never an uncaught traceback
        print(render_drift_dashboard(store, args.plot, report=report))
    verdict = detect_drift(
        report, mape_ratio=args.mape_ratio, corr_floor=args.corr_floor,
        window=args.window, bias_z=args.bias_z,
        bias_window=args.bias_window, bias_baseline=args.bias_baseline,
    )
    if verdict["drifted"]:
        # stderr, not stdout: the report command's stdout contract is the
        # report table (parseable); the verdict is operator/gate signal
        scope = (f"last {args.window} day(s)" if args.window is not None
                 else "all history")
        rules = (
            f"bias |z| > {args.bias_z} over {args.bias_window}d or "
            f"corr < {args.corr_floor}"
        )
        if args.mape_ratio is not None:
            rules += f" or MAPE_live > {args.mape_ratio} x MAPE_train"
        print(
            f"DRIFT: {len(verdict['flagged_dates'])}/{verdict['n_days']} "
            f"day(s) flagged over {scope}, first "
            f"{verdict['first_flagged_date']} ({rules})",
            file=sys.stderr,
        )
        if args.fail_on_drift:
            return DRIFT_EXIT
    return 0


def cmd_compact(args) -> int:
    """Consolidate dataset history into one ``snapshots/`` artefact, so
    cold processes (per-day k8s Jobs, the daily-loop CronJob, plain
    ``cli train``) load all history in O(1 + tail) store reads instead
    of O(days). ``--dry-run`` prints what WOULD be consolidated — days
    covered, rows, estimated bytes — without writing, so an operator can
    size the compaction CronJob before enabling it."""
    from bodywork_tpu.data.snapshot import plan_compaction, write_snapshot

    store = _store(args)
    plan = plan_compaction(store)
    if plan["days"] == 0:
        print("no datasets to consolidate")
        return 0
    if plan["days_without_tokens"]:
        print(
            f"warning: {plan['days_without_tokens']} day(s) have no "
            "version token (backend cannot verify them) and will be "
            "skipped",
            file=sys.stderr,
        )
    if plan["would_write"] is None:
        # nothing consolidatable: every day is token-less on this
        # backend — exiting 0 would let a CronJob claim success forever
        log.error("nothing consolidatable: backend reports no version "
                  "tokens for any dataset day")
        return 1
    latest = plan["latest_snapshot"] or "none"
    print(
        f"{len(plan['covered_days'])} day(s) "
        f"({plan['covered_days'][0]} .. {plan['covered_days'][-1]}), "
        f"{plan['rows']} rows, ~{plan['estimated_bytes']} bytes; "
        f"latest snapshot: {latest}"
    )
    if args.dry_run:
        print(f"dry-run: would write {plan['would_write']}")
        return 0
    kwargs = {"keep": args.keep} if args.keep is not None else {}
    key = write_snapshot(store, **kwargs)
    if key is None:
        log.error("compaction wrote nothing (store changed mid-run?)")
        return 1
    print(key)
    return 0


def cmd_fsck(args) -> int:
    """Full-store integrity scrub (docs/RESILIENCE.md §11): walk every
    prefix in ``schema.ALL_PREFIXES``, verify each artefact against its
    write-time digest evidence and the cross-subsystem reference graph,
    and (with ``--repair``) execute the safe repair subset — corrupt
    bytes quarantined, derived artefacts rebuilt, digest-verified
    replicas restored, dangling references demoted. Exit 0 when no
    actionable findings remain, 7 otherwise, 1 on error."""
    import json as _json

    from bodywork_tpu.audit import run_fsck

    # stdout carries exactly ONE JSON document with --json (the
    # traffic/chaos CLI convention); logs go to stderr either way so
    # the per-finding warnings never interleave with the report
    configure_logger(stream=sys.stderr)
    report = run_fsck(_store(args), repair=args.repair)
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
    else:
        by_sev = report["by_severity"]
        print(
            f"scanned {report['keys_scanned']} artefact(s) across "
            f"{len(report['prefixes'])} prefix(es): "
            + (
                ", ".join(f"{s}={n}" for s, n in sorted(by_sev.items()))
                or "clean"
            )
        )
        for finding in report["findings"]:
            print(
                f"  [{finding['severity']}] {finding['problem']} "
                f"{finding['key']}: {finding['detail']}"
            )
        for entry in report["repairs"]:
            print(
                f"  repair {entry['action']} {entry['key']}: "
                f"{entry['outcome']} — {entry['detail']}"
            )
        if report["residual"]:
            print(f"{len(report['residual'])} actionable finding(s) remain")
    return 0 if report["ok"] else FSCK_FINDINGS_EXIT


def cmd_fleet_sim(args) -> int:
    """Multi-tenant fleet soak (``tenancy/fleet.py``): run N scenario-zoo
    tenants' daily pipelines interleaved in ONE shared store under
    ``tenants/<id>/``, optionally NaN-sabotage one tenant's final
    training day, then re-run every healthy tenant SOLO in a fresh store
    and require its artefacts byte-identical to its fleet namespace —
    zero cross-tenant blast radius, proven at the byte level. The
    sabotaged tenant's registry gate must reject the poisoned candidate
    with production held on the prior healthy model. Exit 0 on a
    verified pass, 1 otherwise."""
    import json as _json

    from bodywork_tpu.tenancy import zoo
    from bodywork_tpu.tenancy.fleet import run_fleet_sim

    # stdout carries exactly ONE JSON document with --json (the
    # fsck/traffic/chaos CLI convention); logs go to stderr either way
    # so the per-day pipeline chatter never interleaves with the report
    configure_logger(stream=sys.stderr)
    if args.store.startswith("gs://"):
        log.error(
            "fleet-sim needs fresh local stores for the byte-level "
            "twin comparison; point --store at a directory, not gs://"
        )
        return 1
    specs = zoo(args.tenants, base_seed=args.seed,
                n_samples=args.samples_per_day)
    summary = run_fleet_sim(
        args.store, _date(args), args.days, specs,
        sabotage_tenant=args.sabotage,
        model_type=args.model,
    )
    if args.json:
        print(_json.dumps(summary, indent=2, sort_keys=True))
    else:
        for tid, cmp_ in summary["comparisons"].items():
            state = "byte-identical" if cmp_["ok"] else (
                f"DIVERGED (mismatched={len(cmp_['mismatched'])} "
                f"missing={len(cmp_['missing'])} extra={len(cmp_['extra'])})"
            )
            print(f"  {tid}: solo twin {state}")
        if summary["sabotage_tenant"]:
            print(
                f"  {summary['sabotage_tenant']}: gate_rejected="
                f"{summary['gate_rejected']} "
                f"production_held={summary['production_held']}"
            )
        print("fleet soak " + ("PASS" if summary["ok"] else "FAIL"))
    return 0 if summary["ok"] else 1


def cmd_chaos_run_sim(args) -> int:
    """Seeded chaos soak (docs/RESILIENCE.md): run the N-day simulation
    fault-free AND under the fault plan, then require the faulted run's
    final artefacts to match the clean run's byte-for-byte (zero torn
    artefacts). Exit 0 on a verified-identical pass, 1 otherwise.

    Reproducibility: the seed (flag, or env ``BODYWORK_TPU_CHAOS_SEED``)
    and plan (flag, or env ``BODYWORK_TPU_CHAOS_PLAN`` naming a JSON
    file) fully determine each op stream's fault sequence — re-running
    with the same seed replays the same adversity."""
    from bodywork_tpu.chaos import FaultPlan, run_chaos_sim

    if args.store.startswith("gs://"):
        log.error(
            "chaos run-sim needs two fresh local stores for the "
            "byte-level comparison; point --store at a directory, "
            "not gs://"
        )
        return 1
    # seed precedence: explicit --seed flag > plan file's seed > env
    # knob > 0. The env knob must NOT override a plan file's own seed —
    # the plan documents the run it reproduces, and a stale exported
    # BODYWORK_TPU_CHAOS_SEED silently replaying different adversity
    # would break the reproduce-by-seed contract.
    env_seed = _env_number("BODYWORK_TPU_CHAOS_SEED", int, 0)
    if args.plan:
        plan = FaultPlan.from_file(args.plan)
        if args.seed is not None:
            plan.seed = args.seed
    else:
        seed = args.seed if args.seed is not None else env_seed
        plan = FaultPlan.default(seed if seed is not None else 0)
    if args.crash_schedule or plan.crash_schedule:
        if args.bit_rot or plan.bit_rot_p > 0:
            # the soaks are exclusive; a mixed plan must not silently
            # drop half its adversity (the bit-rot branch warns in the
            # other direction)
            log.warning(
                "crash soak selected; the plan's bit-rot knobs are "
                "ignored here — run a separate chaos run-sim --bit-rot"
            )
        return _chaos_crash_sim(args, plan)
    if args.bit_rot or plan.bit_rot_p > 0:
        return _chaos_bit_rot_sim(args, plan)
    drift = None
    if args.samples_per_day is not None:
        from bodywork_tpu.data.drift_config import DriftConfig

        drift = DriftConfig(n_samples=args.samples_per_day)
    summary = run_chaos_sim(
        args.store, _date(args), args.days, plan,
        model_type=args.model, scoring_mode=args.mode, drift=drift,
        train_mode=args.train_mode,
    )
    faults = summary["faults_injected"]
    print(
        "faults injected: "
        + (
            # keys arrive as "kind=<name>" label strings; print name=count
            " ".join(
                f"{k.removeprefix('kind=')}={int(v)}"
                for k, v in sorted(faults.items())
            )
            or "none"
        )
    )
    for name, deltas in summary["retries"].items():
        total = int(sum(deltas.values()))
        print(f"{name.removeprefix('bodywork_tpu_')}: {total}")
    print(f"breaker state: {summary['breaker_state']}")
    comparison = summary["comparison"]
    if summary["ok"]:
        print(
            f"PASS: {comparison['matched']} final artefact(s) "
            f"byte-identical to the fault-free run "
            f"(seed={plan.seed}, {args.days} day(s), 0 torn)"
        )
        return 0
    log.error(
        f"chaos soak FAILED: mismatched={comparison['mismatched']} "
        f"missing={comparison['missing']} extra={comparison['extra']} "
        f"torn={comparison['torn']} snapshot_ok={comparison['snapshot_ok']}"
    )
    return 1


def _chaos_bit_rot_sim(args, plan) -> int:
    """The at-rest bit-rot soak (``--bit-rot``): run the N-day sim into
    two audited twins, flip seeded bytes across every populated prefix
    of one (timestamps preserved — invisible to every read-time check),
    then require fsck to detect and classify 100% of the injected
    corruption and ``--repair`` to converge the store byte-identical to
    the healthy twin outside ``quarantine/``. Knob precedence mirrors
    the seed's: the ``--bit-rot`` flag arms a plan whose ``bit_rot_p``
    is 0 at the stock probability; a plan file's own bit_rot fields are
    never overridden by the flag."""
    from bodywork_tpu.chaos import run_bit_rot_sim

    if plan.bit_rot_p == 0:
        plan.bit_rot_p = 0.25  # flag > plan default > env, like --seed
    if any(
        getattr(plan, f) for f in (
            "store_transient_p", "store_latency_p", "torn_write_p",
            "corrupt_read_p", "http_error_p", "http_latency_p",
        )
    ):
        # mirror of the crash soak's warning: the soaks are exclusive,
        # a mixed plan must not silently drop half its adversity
        log.warning(
            "bit-rot soak twins run WITHOUT in-flight fault injection; "
            "the plan's in-flight probabilities are ignored here — run "
            "a separate chaos run-sim for them"
        )
    drift = None
    if args.samples_per_day is not None:
        from bodywork_tpu.data.drift_config import DriftConfig

        drift = DriftConfig(n_samples=args.samples_per_day)
    summary = run_bit_rot_sim(
        args.store, _date(args), args.days, plan,
        model_type=args.model, scoring_mode=args.mode, drift=drift,
        train_mode=args.train_mode,
    )
    print(
        f"bit rot injected: {summary['injected']} key(s) across "
        + ", ".join(
            f"{p}={n}" for p, n in sorted(
                summary["injected_by_prefix"].items()
            )
        )
    )
    print(
        f"detected: {summary['detected']}/{summary['injected']} "
        f"(severities: "
        + (
            ", ".join(
                f"{s}={n}"
                for s, n in sorted(summary["findings_by_severity"].items())
            )
            or "none"
        )
        + ")"
    )
    repaired = sum(
        1 for r in summary["repairs"] if r["outcome"] == "repaired"
    )
    print(f"repairs: {repaired}/{len(summary['repairs'])} applied")
    if summary["ok"]:
        print(
            f"PASS: {summary['injected']} injected corruption(s) all "
            f"detected, classified, and repaired; store byte-identical "
            f"to the healthy twin outside quarantine/ "
            f"(seed={summary['seed']}, {args.days} day(s))"
        )
        return 0
    log.error(
        f"bit-rot soak FAILED: undetected={summary['undetected']} "
        f"residual={[f['key'] for f in summary['post_repair_residual']]} "
        f"comparison_ok={summary['comparison']['ok']}"
    )
    return 1


def _chaos_crash_sim(args, plan) -> int:
    """The crash-resume soak (``--crash-schedule``): kill + restart a
    subprocess runner at the scheduled points and require convergence to
    artefacts byte-identical to an uninterrupted twin. The literal value
    ``sweep`` enumerates EVERY stage boundary of the N-day sim plus
    seeded mid-stage store-op points; otherwise the value is a JSON kill-
    point list (inline or a file path), or comes from the --plan file's
    ``crash_schedule`` key. Children run fault-free — this soak isolates
    process death; compose in-call faults via a separate run."""
    from bodywork_tpu.chaos import run_crash_sim
    from bodywork_tpu.chaos.kill import parse_schedule

    points = None  # None -> run_crash_sim builds the full sweep
    raw = args.crash_schedule
    if raw and raw.strip().lower() != "sweep":
        if os.path.exists(raw):
            raw = open(raw).read()
        try:
            points = parse_schedule(raw)
        except ValueError as exc:
            log.error(f"bad --crash-schedule: {exc}")
            return 1
    elif not raw and plan.crash_schedule:
        points = list(plan.crash_schedule)
    if args.plan and (plan.corrupt_read_p or any(
        getattr(plan, f) for f in (
            "store_transient_p", "store_latency_p", "torn_write_p",
            "http_error_p", "http_latency_p",
        )
    )):
        log.warning(
            "crash soak children run WITHOUT in-call fault injection; "
            "the plan's fault probabilities are ignored here"
        )
    summary = run_crash_sim(
        args.store, _date(args), args.days, seed=plan.seed, points=points,
        model_type=args.model, scoring_mode=args.mode,
        samples_per_day=args.samples_per_day,
    )
    failed = [r for r in summary["results"] if not r["ok"]]
    for r in failed:
        log.error(f"crash point {r['point']}: {r.get('error') or 'diverged'}")
    if summary["ok"]:
        print(
            f"PASS: {summary['points']} kill point(s) all converged "
            f"byte-identical to the uninterrupted twin "
            f"(seed={summary['seed']}, {args.days} day(s))"
        )
        return 0
    log.error(
        f"crash soak FAILED: {len(failed)}/{summary['points']} point(s) "
        "did not converge"
    )
    return 1


def cmd_chaos_canary(args) -> int:
    """Canary release-safety acceptance (docs/RESILIENCE.md §canary):
    run one seeded sabotage scenario — NaN-weight canary checkpoint,
    chaos latency addressed to the canary stream, or a healthy canary —
    against a fresh store and require the SLO watchdog to auto-abort
    (exactly one alias CAS, zero insane responses serialized, production
    byte-identical to a canary-free twin) or auto-promote. Exit 0 on a
    verified PASS, 1 otherwise."""
    from bodywork_tpu.chaos import run_canary_chaos
    from bodywork_tpu.store import open_store

    # stdout carries exactly ONE JSON document (the acceptance summary)
    # so the command composes with jq/scripts; logs go to stderr, as
    # `traffic run` and bench.py
    configure_logger(stream=sys.stderr)
    if args.store.startswith("gs://"):
        log.error(
            "chaos canary needs a fresh local store for the twin "
            "comparison; point --store at a directory, not gs://"
        )
        return 1
    summary = run_canary_chaos(
        open_store(args.store),
        scenario=args.scenario,
        seed=args.seed,
        n_requests=args.requests,
        fraction=args.fraction,
        samples_per_day=args.samples_per_day or 96,
    )
    import json as _json

    print(_json.dumps(summary, indent=2, sort_keys=True))
    if summary["ok"]:
        return 0
    log.error(f"canary chaos scenario {args.scenario!r} FAILED")
    return 1


def cmd_trace(args) -> int:
    """Inspect stored flight-recorder dumps (``obs/flightrec/``,
    ``obs/tracing.py``): ``tail`` lists recent dumps and their traces,
    ``show`` prints one trace as JSON by (a prefix of) its id, and
    ``export --chrome`` renders traces through the existing Chrome
    trace-event emitter for Perfetto. Exits 9 when the requested trace
    (or any dump, for tail/export) is absent — distinct from 1 (error)
    so scripts can tell 'not recorded' from 'broken'."""
    from bodywork_tpu.obs.tracing import (
        find_trace,
        flight_trace_spans,
        iter_flight_records,
    )

    configure_logger(stream=sys.stderr)
    store = _store(args)
    command = args.trace_command
    if command == "show":
        dump_key, trace_doc = find_trace(store, args.trace_id)
        if trace_doc is None:
            log.error(f"trace {args.trace_id!r} not found in any dump")
            return 9
        import json as _json

        print(_json.dumps(
            {"dump": dump_key, "trace": trace_doc}, indent=2, sort_keys=True
        ))
        return 0
    records = list(iter_flight_records(store))
    if not records:
        log.error("no flight-recorder dumps stored (obs/flightrec/ empty "
                  "— dumps are written at SLO-watchdog verdicts with "
                  "tracing enabled)")
        return 9
    if command == "tail":
        for key, doc in records[-args.n:]:
            print(
                f"{key}  verdict={doc['verdict']} reason={doc['reason']!r} "
                f"canary={doc.get('canary_key')} traces={doc['n_traces']}"
            )
            for t in doc["traces"][-args.traces:]:
                meta = t.get("meta") or {}
                print(
                    f"  {t['trace_id']}  {t.get('route')} "
                    f"status={t.get('status')} "
                    f"duration={t.get('duration_s')}s "
                    f"stream={meta.get('stream', '-')} "
                    f"spans={len(t['spans'])}"
                )
        return 0
    # export: one trace by id, or every trace of the newest dump
    from bodywork_tpu.obs.spans import write_chrome_trace

    if args.trace_id:
        dump_key, trace_doc = find_trace(store, args.trace_id)
        if trace_doc is None:
            log.error(f"trace {args.trace_id!r} not found in any dump")
            return 9
        spans = flight_trace_spans(trace_doc)
        source = dump_key
    else:
        source, doc = records[-1]
        spans = [
            span for t in doc["traces"] for span in flight_trace_spans(t)
        ]
    path = write_chrome_trace(args.chrome, spans, process_name=source)
    print(path)
    return 0


#: alias names `registry show` resolves (anything else must look like a
#: model key or a date, or the command exits 1 with a clear message)
_REGISTRY_ALIASES = ("production", "previous")


def _registry_model_key(raw: str) -> str:
    """Accept a full model key, a bare record basename, or a date."""
    from bodywork_tpu.store.schema import MODELS_PREFIX

    if raw.startswith(MODELS_PREFIX):
        return raw
    try:
        return f"{MODELS_PREFIX}regressor-{parse_date(raw)}.npz"
    except ValueError:
        return f"{MODELS_PREFIX}{raw}"


def cmd_registry_list(args) -> int:
    from bodywork_tpu.registry import ModelRegistry, read_aliases

    store = _store(args)
    registry = ModelRegistry(store)
    records = registry.records()
    if not records:
        print("no registry records")
        return 0
    aliases = read_aliases(store) or {}  # ONE validated read for both
    production = aliases.get("production")
    previous = aliases.get("previous")
    print(f"{'MODEL KEY':<42} {'STATUS':<10} {'DATE':<10} ALIAS")
    for record in records:
        alias = (
            "production" if record["model_key"] == production
            else "previous" if record["model_key"] == previous
            else ""
        )
        print(
            f"{record['model_key']:<42} {record['status']:<10} "
            f"{record.get('data_date') or '-':<10} {alias}"
        )
    return 0


def cmd_registry_show(args) -> int:
    import json as _json

    from bodywork_tpu.registry import resolve_alias
    from bodywork_tpu.registry.records import load_record

    store = _store(args)
    what = args.what
    if what in _REGISTRY_ALIASES:
        key = resolve_alias(store, what)
        if key is None:
            log.error(f"alias {what!r} is not set (no promotion yet?)")
            return 1
    elif what == "aliases":
        from bodywork_tpu.registry import read_aliases

        doc = read_aliases(store)
        if doc is None:
            log.error("no registry alias document")
            return 1
        print(_json.dumps(doc, indent=2, sort_keys=True))
        return 0
    elif "/" not in what and "." not in what and not any(
        c.isdigit() for c in what
    ):
        # looks like a (mistyped) alias name, not a key or date — say so
        # instead of fabricating a models/ key that can never exist
        log.error(
            f"unknown alias {what!r}; known aliases: "
            f"{', '.join(_REGISTRY_ALIASES)} (or pass a model key/date)"
        )
        return 1
    else:
        key = _registry_model_key(what)
    record = load_record(store, key)
    if record is None:
        log.error(f"no registry record for {key!r}")
        return 1
    print(_json.dumps(record, indent=2, sort_keys=True))
    return 0


def cmd_registry_promote(args) -> int:
    from bodywork_tpu.registry import ModelRegistry

    store = _store(args)
    key = _registry_model_key(args.model)
    doc = ModelRegistry(store).promote(
        key, day=_date(args), reason="cli: operator promote"
    )
    print(f"production -> {doc['production']} (previous: {doc['previous']})")
    return 0


def cmd_registry_rollback(args) -> int:
    from bodywork_tpu.registry import ModelRegistry, RollbackBlocked

    try:
        doc = ModelRegistry(_store(args)).rollback(
            day=_date(args), reason="cli: operator rollback"
        )
    except RollbackBlocked as exc:
        log.error(
            f"rollback refused: {exc} — the alias did not move; run "
            "`cli fsck --repair` (or promote a known-good checkpoint) "
            "and retry"
        )
        return ROLLBACK_REFUSED_EXIT
    print(f"production -> {doc['production']} (previous: {doc['previous']})")
    return 0


def cmd_registry_gate(args) -> int:
    from bodywork_tpu.registry import GatePolicy, ModelRegistry

    store = _store(args)
    policy = GatePolicy()
    if args.shadow_days is not None:
        policy.shadow_days = args.shadow_days
    registry = ModelRegistry(store, policy=policy)
    key = _registry_model_key(args.model) if args.model else None
    decision = registry.gate(
        day=_date(args), model_key=key, dry_run=args.dry_run
    )
    if decision is None:
        print("no candidate to gate")
        return 0
    verdict = "PROMOTE" if decision.promote else "REJECT"
    prefix = "dry-run: would " if args.dry_run else ""
    print(f"{prefix}{verdict} {decision.model_key}")
    for check in decision.checks:
        print(f"  [{'ok' if check['ok'] else 'FAIL'}] "
              f"{check['name']}: {check['detail']}")
    return 0


def _fraction(raw: str) -> float:
    """argparse type for --fraction: a probability in (0, 1] — 0 would
    start a canary no request ever routes to (the watchdog would wait
    forever) and >1 is a typo; reject both as usage errors (exit 2)."""
    try:
        value = float(raw)
    except ValueError:
        raise argparse.ArgumentTypeError(f"must be a number, got {raw!r}")
    if not 0.0 < value <= 1.0:
        raise argparse.ArgumentTypeError(f"must be in (0, 1], got {raw}")
    return value


def cmd_registry_canary(args) -> int:
    """The canary lifecycle (docs/REGISTRY.md §canary): every action is
    one alias-document CAS applied through the ModelRegistry — the
    action names are pinned against registry.CANARY_ACTIONS and the
    manager API by a guard test."""
    import json as _json

    from bodywork_tpu.registry import ModelRegistry

    registry = ModelRegistry(_store(args))
    action = args.canary_command
    if action == "start":
        key = _registry_model_key(args.model) if args.model else None
        if key is None:
            candidate = registry.newest_candidate()
            if candidate is None:
                log.error("no candidate record to canary; train or pass "
                          "--model")
                return 1
            key = candidate["model_key"]
        doc = registry.canary_start(
            key, fraction=args.fraction, seed=args.seed, day=_date(args)
        )
        print(
            f"canary -> {doc['canary']} at fraction "
            f"{doc['canary_fraction']} (seed {doc['canary_seed']}, "
            f"production {doc['production']})"
        )
        return 0
    if action == "stop":
        doc = registry.canary_abort(
            day=_date(args), reason="cli: operator stop"
        )
        if doc is None:
            log.error("no live canary to stop")
            return 1
        print(f"canary stopped (production stays {doc['production']})")
        return 0
    if action == "promote":
        doc = registry.canary_promote(
            day=_date(args), reason="cli: operator promote"
        )
        print(
            f"production -> {doc['production']} "
            f"(previous: {doc['previous']})"
        )
        return 0
    # status
    print(_json.dumps(registry.canary_status(), indent=2, sort_keys=True))
    return 0


def cmd_deploy(args) -> int:
    from bodywork_tpu.pipeline import write_manifests

    spec = _pipeline_spec(args)
    # Derived per-stage image tags (content-addressed from each stage's
    # requirements pins) exist only after `--emit-images` + build.sh.
    # Emitting manifests that reference them without emitting the build
    # contexts ships guaranteed ImagePullBackOff pods (ADVICE.md medium,
    # k8s.py:204) — refuse unless the operator forces it.
    from bodywork_tpu.pipeline.images import uses_derived_tag

    derived = [s.name for s in spec.stages.values() if uses_derived_tag(s)]
    if derived and not args.emit_images:
        if not args.force:
            log.error(
                f"stages {derived} reference derived per-stage image tags "
                "that nothing builds: pass --emit-images DIR to emit "
                "their build contexts (then run build.sh), or --force to "
                "write the manifests anyway"
            )
            return 1
        log.warning(
            f"--force: manifests reference derived per-stage image tags "
            f"for {derived} with no build contexts emitted; pods will "
            "ImagePullBackOff until the images are built and pushed"
        )
    written = write_manifests(
        spec,
        args.out,
        store_path=args.store_path,
        image=args.image,
        store_volume=args.store_volume,
        storage_class=args.storage_class or None,
        pvc_size=args.pvc_size,
    )
    for path in written:
        print(path)
    if args.emit_images:
        from bodywork_tpu.pipeline.images import write_stage_images

        for path in write_stage_images(
            spec, args.emit_images, image=args.image
        ):
            print(path)
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="bodywork_tpu", description="TPU-native ML pipeline framework"
    )
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persistent XLA compilation-cache dir, so cold processes "
             "(daily pods) reuse previous compiles; defaults to "
             "$BODYWORK_TPU_COMPILE_CACHE, else disabled",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    def add(name, fn, **kwargs):
        p = sub.add_parser(name, **kwargs)
        p.set_defaults(fn=fn)
        return p

    common_store = {"required": True, "help": "artefact store dir or gs:// URL"}

    p = add("generate", cmd_generate, help="generate one day's drift data")
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None)

    p = add("train", cmd_train, help="train on all history, persist model")
    p.add_argument("--store", **common_store)
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == train.TRAIN_MODES == the train_stage env parsing by
        # tests/test_incremental.py
        "--mode", default=_env_choice(
            "BODYWORK_TPU_TRAIN_MODE", ("full", "incremental"), "full"
        ),
        choices=["full", "incremental"],
        help="'full' refits on all history (default; env "
             "BODYWORK_TPU_TRAIN_MODE overrides); 'incremental' folds "
             "in only the new day — exact persisted sufficient "
             "statistics for the linear model, warm-start + replay "
             "fine-tune for the mlp, both falling back to a full refit "
             "(with the reason counted and printed) when the store "
             "lacks what they need",
    )
    p.add_argument(
        "--mesh-data", type=int, default=None,
        help="data-parallel mesh axis for sharded training (mlp only)",
    )
    p.add_argument(
        "--mesh-model", type=int, default=1,
        help="tensor-parallel mesh axis for sharded training (mlp only)",
    )
    p.add_argument(
        "--profile-dir", default=None, metavar="DIR",
        help="wrap the training run in a jax.profiler trace written "
             "here (device-side op view; open in TensorBoard/Perfetto)",
    )

    p = add("serve", cmd_serve, help="serve the latest model over HTTP")
    p.add_argument("--store", **common_store)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=5000)
    p.add_argument(
        "--mesh-data", type=int,
        default=_env_number("BODYWORK_TPU_MESH_DATA", int, 1),
        help="shard each padded request batch over this many devices "
             "(the mesh's data axis; env BODYWORK_TPU_MESH_DATA "
             "overrides — the knob the k8s serve Deployment "
             "materialises). Default: single-device serving",
    )
    p.add_argument(
        "--mesh-model", type=int,
        default=_env_number("BODYWORK_TPU_MESH_MODEL", int, 1) or 1,
        help="tensor-parallel mesh axis for sharded serving (MLP "
             "checkpoints only — weights Megatron-split across this "
             "many devices; env BODYWORK_TPU_MESH_MODEL overrides). "
             "Combines with --mesh-data into a data x model mesh",
    )
    p.add_argument(
        "--engine", default="auto",
        choices=["auto", "xla", "xla-bf16", "pallas", "pallas-bf16"],
        help="prediction engine: the XLA apply (f32), the bf16-matmul "
             "XLA apply (explicit precision/throughput trade, MLP only), "
             "the fused Pallas MLP kernel (f32 or bf16 weights), or auto "
             "(kernel only where it wins: wide MLPs on a real TPU; "
             "never bf16)",
    )
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == serve.predictor.SERVE_DTYPES by tests/test_compiled.py
        "--dtype", default=_env_choice(
            "BODYWORK_TPU_SERVE_DTYPE",
            ("float32", "bfloat16", "int8"), "float32",
        ),
        choices=["float32", "bfloat16", "int8"],
        help="serving precision (env BODYWORK_TPU_SERVE_DTYPE "
             "overrides): float32 (default — byte-identical to the "
             "frozen contract), or a quantized variant (bfloat16 "
             "matmuls / int8 weights, MLP only). A quantized dtype only "
             "serves after the shadow quality gate admits it against "
             "the f32 predictions of the same checkpoint; a regression "
             "past the policy ceiling keeps f32 serving (visible on "
             "/healthz serving_dtype)",
    )
    p.add_argument(
        "--reload-interval", type=float, default=30.0,
        help="poll the store every N seconds and hot-swap newer model "
             "checkpoints into the running service (0 disables; the "
             "service then serves its boot-time model until restart)",
    )
    p.add_argument(
        "--workers", type=int, default=1, metavar="N",
        help="serve through N OS-process replicas sharing this port via "
             "SO_REUSEPORT, supervised and respawned on death — the real "
             "local analogue of the reference's `replicas: 2` Deployment "
             "(default 1: single process, in-process serving)",
    )
    p.add_argument(
        "--frontends", type=_positive_int, metavar="N",
        default=_env_number("BODYWORK_TPU_FRONTENDS", int, 1),
        help="disaggregated serving: N model-free parse/admission "
             "front-end processes on this port (SO_REUSEPORT) feeding "
             "ONE device-owning dispatcher over a shared-memory "
             "row-queue, so batches coalesce from the UNION of every "
             "front-end's rows (--workers fragments them per replica). "
             "Mutually exclusive with --workers > 1. Env "
             "BODYWORK_TPU_FRONTENDS overrides — the knob the k8s "
             "serve Deployment materialises (docs/PERF.md §config 14)",
    )
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == serve.netqueue.SERVE_TRANSPORTS (and the stages
        # env-knob parser) by tests/test_netqueue.py
        "--transport", default=_env_choice(
            "BODYWORK_TPU_SERVE_TRANSPORT", ("shm", "tcp", "unix"), "shm"
        ),
        choices=["shm", "tcp", "unix"],
        help="row-queue transport between the front-ends and the "
             "dispatcher (--frontends mode only): 'shm' (default — "
             "shared memory, one host; env BODYWORK_TPU_SERVE_TRANSPORT "
             "overrides), 'unix' (domain socket, one host), or 'tcp' "
             "(cross-host: the split k8s Deployments' transport). "
             "Admission/shed semantics and response bytes are identical "
             "across all three (docs/PERF.md §config 16)",
    )
    p.add_argument(
        "--dispatcher-addr", default=(
            os.environ.get(
                "BODYWORK_TPU_DISPATCHER_ADDR", ""
            ).strip() or None
        ), metavar="ADDR",
        help="where the dispatcher's row-queue listener lives for the "
             "socket transports: host:port for tcp (the dispatcher "
             "k8s Service), a filesystem path for unix (env "
             "BODYWORK_TPU_DISPATCHER_ADDR overrides). Default: "
             "auto-picked on loopback / a temp path when both halves "
             "run under this process (--role auto); REQUIRED for the "
             "split roles",
    )
    p.add_argument(
        # choices hardcoded like --transport; pinned ==
        # serve.netqueue.SERVE_ROLES by tests/test_netqueue.py
        "--role", default=_env_choice(
            "BODYWORK_TPU_SERVE_ROLE",
            ("auto", "frontend", "dispatcher"), "auto",
        ),
        choices=["auto", "frontend", "dispatcher"],
        help="which half of the disaggregated split this process runs "
             "(env BODYWORK_TPU_SERVE_ROLE overrides): 'auto' (default) "
             "runs both halves locally; 'frontend' runs only the "
             "parse/admission fleet against a remote dispatcher at "
             "--dispatcher-addr; 'dispatcher' runs only the "
             "device-owning scorer, serving the socket row-queue "
             "instead of HTTP — the two halves the split k8s "
             "Deployments run (docs/RESILIENCE.md §14)",
    )
    p.add_argument(
        "--standby", action="store_true",
        default=_env_flag("BODYWORK_TPU_SERVE_STANDBY"),
        help="dispatcher high availability (socket transports only): "
             "run a WARM standby dispatcher next to the active one, "
             "arbitrated by a CAS lease on the artefact store — the "
             "standby takes over within the lease TTL of a leader "
             "death, and front-ends resubmit in-flight rows across the "
             "takeover instead of shedding (env "
             "BODYWORK_TPU_SERVE_STANDBY overrides; with --role "
             "dispatcher this supervises the active/standby PAIR; "
             "docs/RESILIENCE.md failover runbook)",
    )
    p.add_argument(
        "--buckets", default=None, metavar="N[,N...]", type=_bucket_list,
        help="comma-separated request-size buckets to compile and warm "
             "(positive integers; narrows startup cost when request "
             "sizes are known; default: each engine's own bucket set)",
    )
    p.add_argument(
        "--batch-window-ms", type=float, metavar="MS",
        default=_env_number("BODYWORK_TPU_BATCH_WINDOW_MS", float, 0.0),
        help="coalesce concurrent single-row /score/v1 requests into "
             "shared padded device calls, flushing each batch after at "
             "most this many milliseconds (serve.batcher; ~1-2 ms is a "
             "good start). Default off (env "
             "BODYWORK_TPU_BATCH_WINDOW_MS overrides); an EXPLICIT 0 "
             "forces coalescing off even against a --tuned-config "
             "window. Adds at most one window of latency per request; "
             "under concurrency, device dispatches scale with bucket "
             "size instead of request count",
    )
    p.add_argument(
        "--batch-max-rows", type=_positive_int, metavar="N",
        default=_env_number("BODYWORK_TPU_BATCH_MAX_ROWS", int, 1),
        help="flush a coalesced batch as soon as it reaches N rows, "
             "before the window elapses (default 64, or env "
             "BODYWORK_TPU_BATCH_MAX_ROWS; align with a predictor "
             "bucket so a full flush pads to one compiled shape)",
    )
    p.add_argument(
        "--metrics", action="store_true",
        help="with --workers N: aggregate every replica's metrics into "
             "one coherent GET /metrics view (Prometheus text format) "
             "on the serving port. Single-process serving exposes "
             "/metrics unconditionally; this flag is the multi-worker "
             "aggregation switch (docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == serve.server.SERVER_ENGINES by tests/test_aio.py
        "--server-engine", default=_env_choice(
            "BODYWORK_TPU_SERVER_ENGINE", ("thread", "aio"), "thread"
        ),
        choices=["thread", "aio"],
        help="HTTP front-end: 'thread' (werkzeug thread-per-request, "
             "default; env BODYWORK_TPU_SERVER_ENGINE overrides) or "
             "'aio' (asyncio event loop, serve.aio — built for "
             "open-loop arrival-rate load; arms admission control by "
             "default). Responses are byte-identical across engines",
    )
    p.add_argument(
        "--max-pending", type=_positive_int, metavar="N",
        default=_env_number("BODYWORK_TPU_MAX_PENDING", int, 1),
        help="admission budget (serve.admission): at most N scoring "
             "requests admitted-and-unfinished at once; beyond it "
             "requests answer 429 + Retry-After BEFORE any work. "
             "Default: off for --server-engine thread, 512 for aio "
             "(env BODYWORK_TPU_MAX_PENDING overrides). Per worker "
             "process with --workers N",
    )
    p.add_argument(
        "--retry-after-max-s", type=float, metavar="S",
        default=_env_number("BODYWORK_TPU_RETRY_AFTER_MAX_S", float, 1.0),
        help="cap on the EWMA-derived Retry-After hint that shed 429s "
             "and degraded 503s carry (default 30; env "
             "BODYWORK_TPU_RETRY_AFTER_MAX_S overrides)",
    )
    p.add_argument(
        "--tuned-config", default=(
            os.environ.get("BODYWORK_TPU_TUNED_CONFIG", "").strip() or None
        ), metavar="REF",
        help="serve with a fitted knob set from `cli tune`: a tuning/ "
             "store key or 'latest' (env BODYWORK_TPU_TUNED_CONFIG "
             "overrides — the knob the k8s serve Deployment "
             "materialises). Tuned values fill every knob not set "
             "explicitly (window/max-rows/buckets/max-pending); a "
             "missing or malformed document degrades to the built-in "
             "defaults with a warning, never a failed boot",
    )
    p.add_argument(
        "--online-tune", action="store_true",
        default=_env_flag("BODYWORK_TPU_TUNE_ONLINE"),
        help="arm the online re-tune controller (tune/online.py) on "
             "the reload-watcher loop: incremental drift detection "
             "over the --tune-*-log files, mid-flight knob refits "
             "under a config-canary guard that auto-reverts a "
             "regressing config in one CAS. Requires --reload-interval "
             "> 0 (env BODYWORK_TPU_TUNE_ONLINE=1 overrides); "
             "single-process serving only",
    )
    p.add_argument(
        "--tune-request-log", action="append", metavar="FILE",
        default=_env_paths("BODYWORK_TPU_TUNE_REQUEST_LOGS"),
        help="a growing `traffic run` request log the online "
             "controller watches incrementally (arrival process + row "
             "shapes); repeatable (env BODYWORK_TPU_TUNE_REQUEST_LOGS "
             "colon-separated)",
    )
    p.add_argument(
        "--tune-results-log", action="append", metavar="FILE",
        default=_env_paths("BODYWORK_TPU_TUNE_RESULTS_LOGS"),
        help="a growing `traffic run --results-out` outcome log the "
             "online controller watches incrementally; repeatable "
             "(env BODYWORK_TPU_TUNE_RESULTS_LOGS colon-separated)",
    )
    p.add_argument(
        "--cost-budget-s", type=float, metavar="S",
        default=_env_number("BODYWORK_TPU_COST_BUDGET_S", float, 0.0),
        help="arm the admission layer's cost-priced shed: bound the "
             "ESTIMATED dispatch-seconds of admitted-and-unfinished "
             "work, priced per request by the latest learned cost "
             "model under tuning/ (env BODYWORK_TPU_COST_BUDGET_S "
             "overrides; requires admission to be armed)",
    )

    p = add(
        "tune", cmd_tune,
        help="fit the serving knobs from observed traces (docs/PERF.md "
             "§config 13); `tune status` reports the active config + "
             "apply/revert history",
    )
    p.add_argument(
        "action", nargs="?", default="fit", choices=["fit", "status"],
        help="fit (default): ingest traces and write a tuned config; "
             "status: print the ACTIVE tuned config (digest, source "
             "window, per-knob source incl. env overrides) and the "
             "online controller's applied/reverted history from the "
             "config log — exit 1 on a corrupt document",
    )
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None,
                   help="date key for the tuned document (default: today)")
    p.add_argument(
        "--traffic-log", action="append", default=[], metavar="FILE",
        help="a `traffic run --log-out` request log to ingest (arrival "
             "process + offered row shapes); repeatable",
    )
    p.add_argument(
        "--results-log", action="append", default=[], metavar="FILE",
        help="a `traffic run --results-out` outcome log to ingest "
             "(latencies, goodput — the measured service rate when the "
             "drive was saturated); repeatable",
    )
    p.add_argument(
        "--obs-snapshot", action="append", default=[], metavar="FILE",
        help="an obs registry snapshot JSON to ingest (flush occupancy, "
             "phase histograms, per-op store costs); repeatable",
    )
    p.add_argument(
        "--day-report", action="append", default=[], metavar="FILE",
        help="a `run-day --report-out` document to ingest (span "
             "timings); repeatable",
    )
    p.add_argument(
        "--no-probe", dest="probe", action="store_false",
        help="skip the active dispatch-cost probe (by default the "
             "serving checkpoint's padded dispatch is timed at each "
             "candidate bucket — the measured cost curve the bucket and "
             "window models need)",
    )
    p.add_argument(
        "--probe-buckets", default=(1, 8, 64, 256, 512, 1024, 4096),
        type=_bucket_list, metavar="N[,N...]",
        help="candidate buckets the dispatch-cost probe measures",
    )
    p.add_argument("--probe-reps", type=_positive_int, default=5,
                   help="timed probe repetitions per bucket (median wins)")
    p.add_argument("--dry-run", action="store_true",
                   help="fit and print, write nothing")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the per-knob decision trace as a Chrome "
                        "trace-event file (one span per knob with "
                        "chosen-vs-default meta)")

    p = add("test", cmd_test, help="test a live scoring service")
    p.add_argument("--store", **common_store)
    p.add_argument("--scoring-url", required=True)
    p.add_argument("--mode", default="batch", choices=["single", "batch"])

    p = add("run-day", cmd_run_day, help="run one simulated day in-process")
    p.add_argument("--spec", default=None, help="pipeline spec YAML (overrides --model/--mode)")
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None)
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument("--mode", default="batch", choices=["single", "batch"])
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the day's stage spans as a Chrome "
                        "trace-event file (open in Perfetto or "
                        "chrome://tracing); also writes the structured "
                        "JSON run report next to it")
    p.add_argument("--report-out", default=None, metavar="PATH",
                   help="write the structured per-day run report (JSON: "
                        "stage seconds + spans) here; defaults to "
                        "<trace-out stem>.report.json when --trace-out "
                        "is given")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore the runs/ journal: no lease, no verified "
                        "skipping, full re-run (the pre-journal "
                        "behaviour). Default: resume — completed stages "
                        "whose recorded artefact digests verify against "
                        "the store are skipped; exit codes 5 (lease "
                        "lost) / 6 (resumed-noop) are documented in "
                        "docs/RESILIENCE.md")
    p.add_argument("--scrub", action="store_true",
                   help="run a detect-only integrity scrub (fsck) after "
                        "the day converges; findings are printed, "
                        "counted on bodywork_tpu_audit_* metrics, and "
                        "embedded as the day report's fsck block "
                        "(docs/RESILIENCE.md §11)")

    p = add("run-sim", cmd_run_sim, help="run an N-day drift simulation")
    p.add_argument("--spec", default=None, help="pipeline spec YAML (overrides --model/--mode)")
    p.add_argument("--store", **common_store)
    p.add_argument("--days", type=int, required=True)
    p.add_argument("--date", default=None, help="start date (YYYY-MM-DD)")
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument("--mode", default="batch", choices=["single", "batch"])
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of the whole loop here")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the whole simulation's stage spans "
                        "(stages, lookahead-train overlap, prefetch, "
                        "prewarm) as one Chrome trace-event file")
    p.add_argument("--samples-per-day", type=_positive_int, default=None,
                   metavar="N",
                   help="shrink the generator to N rows/day (default: "
                        "the full reference-parity 1440) — what the "
                        "crash soak's subprocess runners use for quick "
                        "kill-and-restart cycles")

    p = add("run-ab", cmd_run_ab,
            help="concurrent A/B model pipelines on one device pool")
    p.add_argument("--store", **common_store)
    p.add_argument("--days", type=int, required=True)
    p.add_argument("--date", default=None, help="start date (YYYY-MM-DD)")
    p.add_argument("--models", default="linear,mlp",
                   help="comma-separated model types, one pipeline each")

    p = add("run-stage", cmd_run_stage, help="run one pipeline stage (pod entrypoint)")
    p.add_argument("--spec", default=None, help="pipeline spec YAML (overrides --model/--mode)")
    p.add_argument("--store", **common_store)
    p.add_argument("--stage", required=True)
    p.add_argument("--date", default=None)
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument("--mode", default="batch", choices=["single", "batch"])
    p.add_argument("--scoring-url", default=None)

    p = add("wait-for", cmd_wait_for, help="block until pipeline preconditions hold")
    p.add_argument("--store", **common_store)
    p.add_argument("--dataset", action="store_true",
                   help="wait until any dataset exists")
    p.add_argument("--model", action="store_true",
                   help="wait until any model checkpoint exists")
    p.add_argument("--dataset-newer-than-model", action="store_true",
                   help="wait until the latest dataset postdates the latest model")
    p.add_argument("--service-url", default=None,
                   help="wait until this health URL returns 200")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--poll-interval", type=float, default=2.0)

    p = add("report", cmd_report, help="longitudinal train-vs-live drift report")
    p.add_argument("--store", **common_store)
    p.add_argument("--plot", default=None, metavar="OUT.png",
                   help="also render the drift dashboard PNG here "
                        "(requires matplotlib)")
    p.add_argument("--fail-on-drift", action="store_true",
                   help="exit 4 when the drift rule flags any day — lets "
                        "a CronJob/CI gate react to drift instead of an "
                        "analyst eyeballing the table (4 is unambiguous: "
                        "1=error, 2=usage, 3=backend unreachable)")
    p.add_argument("--mape-ratio", type=float, default=None,
                   help="OPT-IN: flag a day when MAPE_live exceeds this "
                        "multiple of MAPE_train. Disabled by default — "
                        "calibration against the generator showed the "
                        "day-level mean-APE ratio has an unbounded "
                        "false-positive rate when labels touch zero (one "
                        "tiny label made a no-drift day 156x its train "
                        "MAPE). Use only for label distributions bounded "
                        "away from zero; the calibrated drift detector "
                        "is the bias rule")
    p.add_argument("--corr-floor", type=float, default=0.5,
                   help="flag a day when the live score/label correlation "
                        "falls below this (default 0.5)")
    p.add_argument("--bias-z", type=float, default=4.0,
                   help="flag a day when the trailing-window live "
                        "residual-mean statistic exceeds this many "
                        "standard errors (default 4.0; the calibrated "
                        "drift detector — see monitor.detect_drift)")
    p.add_argument("--bias-window", type=_positive_int, default=7,
                   metavar="N",
                   help="trailing days accumulated by the bias rule "
                        "(default 7: one week clears z=4 at the "
                        "generator's own drift amplitude)")
    p.add_argument("--bias-baseline", type=_positive_int, default=14,
                   metavar="N",
                   help="first N days of the report used as the bias "
                        "rule's deployment-time yardstick (default 14; "
                        "a frozen model's constant estimation error "
                        "cancels against it, so only CHANGE flags)")
    p.add_argument("--window", type=_positive_int, default=None, metavar="N",
                   help="evaluate the drift rule over the last N days only "
                        "(default: all history). Use with --fail-on-drift "
                        "so the gate reflects CURRENT drift instead of "
                        "latching forever on one past flagged day")

    p = add("compact", cmd_compact,
            help="consolidate dataset history into a snapshots/ artefact")
    p.add_argument("--store", **common_store)
    p.add_argument("--dry-run", action="store_true",
                   help="print days covered, rows, and estimated bytes "
                        "without writing anything — size the compaction "
                        "CronJob before enabling it")
    p.add_argument("--keep", type=_positive_int, default=None, metavar="N",
                   help="snapshots to retain after writing (default: "
                        "data.snapshot.SNAPSHOT_KEEP)")

    p = sub.add_parser(
        "chaos",
        help="deterministic fault-injection harness (docs/RESILIENCE.md)",
    )
    chaos_sub = p.add_subparsers(dest="chaos_command", required=True)
    p = chaos_sub.add_parser(
        "run-sim",
        help="seeded chaos soak: faulted N-day sim vs fault-free twin, "
             "final artefacts must be byte-identical",
    )
    p.set_defaults(fn=cmd_chaos_run_sim)
    p.add_argument("--store", required=True,
                   help="fresh local directory for the two runs' stores "
                        "(baseline/ and chaos/ subdirs; gs:// refused — "
                        "the byte-level comparison needs local twins)")
    p.add_argument("--days", type=_positive_int, required=True)
    p.add_argument("--date", default=None, help="start date (YYYY-MM-DD)")
    p.add_argument("--seed", type=int, default=None,
                   help="fault-plan seed; same seed => same per-op-stream "
                        "fault sequence. Precedence: this flag > a --plan "
                        "file's own seed > env BODYWORK_TPU_CHAOS_SEED > 0")
    p.add_argument("--plan", default=os.environ.get("BODYWORK_TPU_CHAOS_PLAN"),
                   metavar="FILE",
                   help="JSON fault plan (FaultPlan fields; unknown keys "
                        "rejected). Default: the stock all-kinds plan "
                        "(env BODYWORK_TPU_CHAOS_PLAN overrides). Only an "
                        "explicit --seed overrides the file's seed")
    p.add_argument("--samples-per-day", type=_positive_int, default=None,
                   metavar="N",
                   help="shrink the generator to N rows/day for quick "
                        "soaks (default: the full reference-parity 1440)")
    p.add_argument("--bit-rot", action="store_true",
                   help="run the AT-REST bit-rot soak instead: flip "
                        "seeded bytes across every populated prefix of "
                        "a finished sim's store (timestamps preserved — "
                        "invisible to read-time checks), then require "
                        "fsck to detect/classify 100%% of the damage "
                        "and --repair to converge byte-identical to a "
                        "healthy twin outside quarantine/ "
                        "(docs/RESILIENCE.md §11). A --plan file's "
                        "bit_rot_* fields arm this implicitly; the flag "
                        "never overrides a plan's own bit_rot knobs")
    p.add_argument("--crash-schedule", default=None, metavar="SPEC",
                   help="run the crash-resume soak instead: kill+restart "
                        "a subprocess runner at these points and require "
                        "final artefacts byte-identical to an "
                        "uninterrupted twin. SPEC is 'sweep' (every "
                        "stage boundary + seeded mid-stage store-op "
                        "points) or a JSON kill-point list (inline or a "
                        "file path); a --plan file's crash_schedule key "
                        "works too (docs/RESILIENCE.md §crash-resume)")
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument("--mode", default="batch", choices=["single", "batch"])
    p.add_argument(
        "--train-mode", default="full", choices=["full", "incremental"],
        help="run BOTH twins through this training mode; 'incremental' "
             "puts the trainstate/ sufficient-statistics artefact in "
             "the byte-identity comparison's scope "
             "(train/incremental.py)",
    )

    p = chaos_sub.add_parser(
        "canary",
        help="canary release-safety acceptance: a sabotaged canary "
             "(NaN weights / injected latency) must auto-abort via one "
             "CAS with production byte-identical to a canary-free twin; "
             "a healthy one must auto-promote (docs/RESILIENCE.md)",
    )
    p.set_defaults(fn=cmd_chaos_canary)
    p.add_argument("--store", required=True,
                   help="fresh local directory (gs:// refused — the "
                        "twin comparison is byte-level)")
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == chaos.CANARY_SCENARIOS by tests/test_canary.py
        "--scenario", default="nan",
        choices=["nan", "latency", "healthy"],
        help="sabotage mode: 'nan' (NaN-weight canary checkpoint), "
             "'latency' (chaos latency addressed to the canary stream), "
             "or 'healthy' (no sabotage; must auto-promote)",
    )
    p.add_argument("--seed", type=int, default=0,
                   help="drives the request stream, the routing hash, "
                        "and the fault plan — same seed, same verdict "
                        "at the same request")
    p.add_argument("--requests", type=_positive_int, default=240,
                   metavar="N",
                   help="seeded single-row scoring requests to drive "
                        "(default 240)")
    p.add_argument("--fraction", type=_fraction, default=0.35,
                   metavar="F",
                   help="canary traffic fraction in (0, 1] (default 0.35)")
    p.add_argument("--samples-per-day", type=_positive_int, default=None,
                   metavar="N",
                   help="rows/day for the two seeded training days "
                        "(default 96 — small; the scenario tests the "
                        "release loop, not the fit)")

    p = add(
        "fsck", cmd_fsck,
        help="full-store integrity scrub: verify every prefix against "
             "write-time digests + the cross-subsystem reference graph; "
             "--repair executes the safe subset (quarantine, rebuild, "
             "digest-verified restore) — docs/RESILIENCE.md §11",
    )
    p.add_argument("--store", **common_store)
    p.add_argument("--repair", action="store_true",
                   help="execute the safe repair subset: corrupt bytes "
                        "move to quarantine/ (never deleted), derived "
                        "artefacts rebuild, replicas restore "
                        "digest-verified, dangling alias slots demote. "
                        "Data-loss findings are quarantined and "
                        "reported, never 'fixed'")
    p.add_argument("--json", action="store_true",
                   help="print the full report as exactly one JSON "
                        "document on stdout (logs go to stderr) — the "
                        "traffic/chaos CLI convention")

    p = add(
        "fleet-sim", cmd_fleet_sim,
        help="multi-tenant fleet soak: N scenario-zoo tenants "
             "interleaved in ONE store under tenants/<id>/, optional "
             "single-tenant NaN sabotage, every healthy tenant proven "
             "byte-identical to a solo twin (zero cross-tenant blast "
             "radius)",
    )
    p.add_argument("--store", required=True,
                   help="fresh local root dir (fleet/ + solo twins are "
                        "created under it)")
    p.add_argument("--date", default=None, help="simulation start date")
    p.add_argument("--days", type=_positive_int, default=3, metavar="N")
    p.add_argument("--tenants", type=_positive_int, default=4, metavar="N",
                   help="fleet size; specs cycle the scenario zoo "
                        "(tenant-00 is always baseline/steady)")
    p.add_argument("--sabotage", default=None, metavar="TENANT",
                   help="NaN-poison this tenant's final training day; "
                        "its gate must reject, everyone else must stay "
                        "byte-identical to their solo twins")
    p.add_argument("--seed", type=int, default=42,
                   help="base seed folded into every tenant's data seed")
    p.add_argument("--samples-per-day", type=_positive_int, default=96,
                   metavar="N",
                   help="rows/day per tenant (default 96 — the soak "
                        "tests isolation, not the fit)")
    p.add_argument("--model", default="linear",
                   choices=["linear", "mlp"])
    p.add_argument("--json", action="store_true",
                   help="print the full summary as exactly one JSON "
                        "document on stdout")

    p = sub.add_parser(
        "registry",
        help="model registry: gated promotion, shadow eval, rollback "
             "(docs/REGISTRY.md)",
    )
    registry_sub = p.add_subparsers(dest="registry_command", required=True)

    p = registry_sub.add_parser("list", help="list registry records + aliases")
    p.set_defaults(fn=cmd_registry_list)
    p.add_argument("--store", **common_store)

    p = registry_sub.add_parser(
        "show",
        help="show one record (by model key or date) or resolve an alias "
             "(production/previous) or dump the alias doc (aliases)",
    )
    p.set_defaults(fn=cmd_registry_show)
    p.add_argument("--store", **common_store)
    p.add_argument("what",
                   help="model key, date, 'production', 'previous', or "
                        "'aliases'")

    p = registry_sub.add_parser(
        "promote",
        help="point the production alias at a registered model (one CAS; "
             "old production becomes 'previous')",
    )
    p.set_defaults(fn=cmd_registry_promote)
    p.add_argument("--store", **common_store)
    p.add_argument("--model", required=True,
                   help="model key or date to promote")
    p.add_argument("--date", default=None,
                   help="day to stamp the promotion events with "
                        "(YYYY-MM-DD; default today)")

    p = registry_sub.add_parser(
        "rollback",
        help="ONE operation back to the previous production (a single "
             "alias CAS flip; the checkpoint watcher swaps on next "
             "poll). The restore target is pre-verified first — a "
             "missing or digest-mismatched 'previous' refuses with "
             "exit 8 instead of rolling back into a degraded boot",
    )
    p.set_defaults(fn=cmd_registry_rollback)
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None,
                   help="day to stamp the rollback events with "
                        "(YYYY-MM-DD; default today)")

    p = registry_sub.add_parser(
        "gate",
        help="adjudicate the newest candidate (promote or reject) — the "
             "step run-day runs between train and serve",
    )
    p.set_defaults(fn=cmd_registry_gate)
    p.add_argument("--store", **common_store)
    p.add_argument("--model", default=None,
                   help="candidate to gate (default: newest record in "
                        "candidate status)")
    p.add_argument("--date", default=None,
                   help="day to stamp decision events with (YYYY-MM-DD)")
    p.add_argument("--dry-run", action="store_true",
                   help="evaluate and print the decision WITHOUT writing "
                        "anything (no events, no status move, no alias CAS)")
    p.add_argument("--shadow-days", type=_positive_int, default=None,
                   metavar="K",
                   help="also shadow-evaluate the candidate against "
                        "production over the last K dataset days "
                        "(in-process, no live traffic; default off)")

    p = registry_sub.add_parser(
        "canary",
        help="live canary lifecycle: route a seeded traffic fraction to "
             "a candidate, watched by the SLO watchdog — auto-abort on "
             "breach, auto-promote when healthy (docs/REGISTRY.md)",
    )
    canary_sub = p.add_subparsers(dest="canary_command", required=True)
    # action names pinned == registry.CANARY_ACTIONS == the manager API
    # == docs/REGISTRY.md by tests/test_canary.py (hardcoded here to keep
    # parser construction import-light)
    p = canary_sub.add_parser(
        "start",
        help="point the canary slot at a candidate (one CAS); serving "
             "routes --fraction of /score traffic to it on its next poll",
    )
    p.set_defaults(fn=cmd_registry_canary)
    p.add_argument("--store", **common_store)
    p.add_argument("--model", default=None,
                   help="model key or date to canary (default: newest "
                        "record in candidate status)")
    p.add_argument("--fraction", type=_fraction, default=0.1, metavar="F",
                   help="fraction of scoring traffic routed to the "
                        "canary, in (0, 1] (default 0.1)")
    p.add_argument("--seed", type=int, default=0,
                   help="routing-hash seed: (seed, request bytes) "
                        "deterministically pick the stream, so replays "
                        "and replicas route identically")
    p.add_argument("--date", default=None,
                   help="day to stamp the canary events with (YYYY-MM-DD)")
    p = canary_sub.add_parser(
        "stop",
        help="abort the live canary (one CAS; record -> rejected) — the "
             "manual form of the watchdog's breach action",
    )
    p.set_defaults(fn=cmd_registry_canary)
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None,
                   help="day to stamp the abort events with (YYYY-MM-DD)")
    p = canary_sub.add_parser(
        "promote",
        help="graduate the live canary to production (one CAS: "
             "production=canary, old production -> previous, slot "
             "cleared) — the manual form of the watchdog's healthy-"
             "window action",
    )
    p.set_defaults(fn=cmd_registry_canary)
    p.add_argument("--store", **common_store)
    p.add_argument("--date", default=None,
                   help="day to stamp the promotion events with "
                        "(YYYY-MM-DD)")
    p = canary_sub.add_parser(
        "status", help="the canary slot, serveability, and record status"
    )
    p.set_defaults(fn=cmd_registry_canary)
    p.add_argument("--store", **common_store)

    p = sub.add_parser(
        "traffic",
        help="open-loop load harness: seeded arrival-rate traffic "
             "against a live scoring service (docs/PERF.md §config 9)",
    )
    traffic_sub = p.add_subparsers(dest="traffic_command", required=True)
    p = traffic_sub.add_parser(
        "run",
        help="generate (or replay) a seeded request log and drive it "
             "open-loop — requests fire at their scheduled arrival "
             "times whether or not earlier responses returned",
    )
    p.set_defaults(fn=cmd_traffic_run)
    p.add_argument("--url", default=None,
                   help="base URL of the service under load (e.g. "
                        "http://127.0.0.1:5000 — per-request routes come "
                        "from the log). Omit with --log-out to only "
                        "generate the log")
    p.add_argument("--rate", type=float, default=100.0, metavar="RPS",
                   help="mean offered load, requests/second (default 100)")
    p.add_argument("--duration", type=float, default=5.0, metavar="S",
                   help="log span in seconds (default 5)")
    p.add_argument(
        # choices hardcoded to keep parser construction import-light;
        # pinned == traffic.ARRIVAL_PROCESSES by tests/test_traffic.py
        "--transport", default="json", choices=["json", "binary"],
        help="wire encoding for the SAME request log (choices pinned == "
             "traffic.generator.TRANSPORTS): 'json' sends the frozen "
             "contract body, 'binary' the f32 row framing "
             "(application/x-bodywork-rows) both serving engines "
             "accept — a json-vs-binary pair isolates JSON "
             "parse/format cost from everything else",
    )
    p.add_argument(
        "--shards", type=_positive_int, default=1, metavar="N",
        help="drive through N worker processes, splitting the request "
             "log round-robin and merging per-shard results into ONE "
             "report — one driver process tops out around ~1.6k rps "
             "(docs/PERF.md §config 14 note), so high offered rates "
             "need N > 1 (default 1)",
    )
    p.add_argument(
        "--arrival", default="poisson", choices=["poisson", "mmpp"],
        help="arrival process: memoryless 'poisson' or bursty 'mmpp' "
             "(2-state Markov-modulated: calm/burst squalls at the SAME "
             "mean rate — the shape that breaks queues)",
    )
    p.add_argument("--batch-fraction", type=float, default=0.0,
                   metavar="P",
                   help="probability an arrival is a /score/v1/batch "
                        "request (default 0: all single-row)")
    p.add_argument("--batch-rows", type=_positive_int, default=64,
                   metavar="N",
                   help="rows per batch request (default 64)")
    p.add_argument("--seed", type=int, default=0,
                   help="request-log seed: the same (seed, knobs) "
                        "generates the identical request sequence — "
                        "replayable adversity, as chaos run-sim")
    p.add_argument("--burst-multiplier", type=float, default=4.0,
                   metavar="M",
                   help="mmpp: burst-state rate as a multiple of calm "
                        "(default 4)")
    p.add_argument("--timeout", type=float, default=30.0, metavar="S",
                   help="per-request response timeout (default 30)")
    p.add_argument("--log-out", default=None, metavar="FILE",
                   help="write the generated request log (JSONL) here "
                        "for later replay")
    p.add_argument("--log-in", default=None, metavar="FILE",
                   help="replay THIS request log instead of generating "
                        "one (ignores the shape flags)")
    p.add_argument("--results-out", default=None, metavar="FILE",
                   help="write one JSONL record per request (status, "
                        "client latency, answering model key, and the "
                        "server's X-Bodywork-Trace-Id) — the join table "
                        "between client-observed latency and the "
                        "server-side spans `trace show` renders "
                        "(docs/OBSERVABILITY.md tracing section)")

    p = sub.add_parser(
        "trace",
        help="inspect request traces from stored flight-recorder dumps "
             "(obs/flightrec/ — written at SLO-watchdog verdicts; "
             "docs/OBSERVABILITY.md tracing section)",
    )
    trace_sub = p.add_subparsers(dest="trace_command", required=True)
    p = trace_sub.add_parser(
        "show", help="print one stored trace (JSON) by trace id or prefix"
    )
    p.set_defaults(fn=cmd_trace)
    p.add_argument("--store", **common_store)
    p.add_argument("trace_id",
                   help="full 32-hex trace id, or any unambiguous prefix "
                        "(first match wins) — e.g. from a /metrics "
                        "EXEMPLAR line, /healthz latency_exemplars, or "
                        "the traffic harness's --results-out log")
    p = trace_sub.add_parser(
        "tail", help="list recent dumps and the traces they carry"
    )
    p.set_defaults(fn=cmd_trace)
    p.add_argument("--store", **common_store)
    p.add_argument("-n", type=_positive_int, default=5, metavar="N",
                   help="dumps to show, newest last (default 5)")
    p.add_argument("--traces", type=_positive_int, default=10, metavar="N",
                   help="traces to list per dump (default 10)")
    p = trace_sub.add_parser(
        "export",
        help="render stored traces as a Chrome trace-event file "
             "(open in Perfetto / chrome://tracing) — one track per trace",
    )
    p.set_defaults(fn=cmd_trace)
    p.add_argument("--store", **common_store)
    p.add_argument("--chrome", required=True, metavar="OUT.json",
                   help="output path for the Chrome trace-event JSON")
    p.add_argument("--trace-id", default=None,
                   help="export only this trace (id or prefix); default: "
                        "every trace of the newest dump")

    p = add("deploy", cmd_deploy, help="write GKE TPU manifests")
    p.add_argument("--spec", default=None, help="pipeline spec YAML (overrides --model/--mode)")
    p.add_argument("--out", required=True)
    p.add_argument("--store-path", default="/mnt/artefact-store")
    p.add_argument("--image", default="bodywork-tpu/runtime:latest")
    p.add_argument(
        "--store-volume", default="auto",
        choices=["auto", "pvc", "hostpath", "gcs"],
        help="shared-store medium: ReadWriteMany PVC (multi-node safe), "
             "hostPath (single-node clusters ONLY), or direct GCS; auto "
             "picks gcs for gs:// store paths and pvc otherwise",
    )
    p.add_argument("--storage-class", default="standard-rwx",
                   help="storageClassName for the store PVC (default: GKE "
                        "Filestore's RWX class; pass '' for the cluster "
                        "default, which must support ReadWriteMany)")
    p.add_argument("--pvc-size", default="10Gi")
    p.add_argument(
        "--emit-images", default=None, metavar="DIR",
        help="also write per-stage image build contexts (Dockerfile + "
             "pinned requirements.txt + build.sh) to DIR — the buildable "
             "source of the per-stage image tags the manifests reference "
             "(reference parity: per-stage dependency isolation)",
    )
    p.add_argument(
        "--force", action="store_true",
        help="write manifests referencing derived per-stage image tags "
             "even when --emit-images is not given (the tags will not "
             "exist until their build contexts are generated and built "
             "— pods ImagePullBackOff until then)",
    )
    p.add_argument("--model", default="linear", choices=["linear", "mlp"])
    p.add_argument("--mode", default="batch", choices=["single", "batch"])

    _inject_tenant_arg(parser)
    return parser


def _inject_tenant_arg(parser: argparse.ArgumentParser) -> None:
    """Give every (sub)command that opens a store a ``--tenant`` flag.

    One walk over the finished parser tree instead of 30 per-command
    declarations, so a new store-opening command can never forget the
    flag. ``_store()`` scopes all keys under ``tenants/<ID>/``;
    ``default`` (or unset) is the root namespace, byte-identical to
    pre-tenancy layouts. Env ``BODYWORK_TPU_TENANT`` is the soft
    default when the flag is absent."""
    for action in parser._actions:
        if isinstance(action, argparse._SubParsersAction):
            seen = set()
            for child in action.choices.values():
                if id(child) in seen:  # aliases share one parser
                    continue
                seen.add(id(child))
                _inject_tenant_arg(child)
    options = {s for a in parser._actions for s in a.option_strings}
    if "--store" in options and "--tenant" not in options:
        parser.add_argument(
            "--tenant", default=None, metavar="ID",
            help="tenant namespace to operate in (strictly validated "
                 "against the schema tenant-id charset; env "
                 "BODYWORK_TPU_TENANT is the soft default; 'default' = "
                 "the root namespace)",
        )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    configure_logger(args.log_level)
    init_error_monitoring(f"cli-{args.command}")
    try:
        from bodywork_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache(args.compile_cache)
        return args.fn(args)
    except Exception as exc:
        log.error(exc)
        return 1


if __name__ == "__main__":
    sys.exit(main())
