"""Drift-data generation and dataset IO.

Exports resolve LAZILY (PEP 562): ``data.generator`` imports jax at
module level (the fused sampler is a jitted program), but ``data.io`` is
plain numpy/pandas — and the live-service test stage (reference stage 4)
needs only the IO half. Eager re-exports here would hand every stage pod
the full accelerator runtime; lazy ones let per-stage dependency pin
sets (``pipeline.spec.STAGE_REQUIREMENTS``) genuinely differ, like the
reference's per-stage requirements blocks (``bodywork.yaml:67-72``: its
stage 4 installs no sklearn either).
"""
from __future__ import annotations

import importlib

_EXPORTS = {
    "DriftConfig": "bodywork_tpu.data.drift_config",
    "alpha": "bodywork_tpu.data.generator",
    "generate_day": "bodywork_tpu.data.generator",
    "generate_dataframe": "bodywork_tpu.data.generator",
    "Dataset": "bodywork_tpu.data.io",
    "load_all_datasets": "bodywork_tpu.data.io",
    "load_dataset": "bodywork_tpu.data.io",
    "load_latest_dataset": "bodywork_tpu.data.io",
    "persist_dataset": "bodywork_tpu.data.io",
    "load_latest_snapshot": "bodywork_tpu.data.snapshot",
    "plan_compaction": "bodywork_tpu.data.snapshot",
    "refresh_due": "bodywork_tpu.data.snapshot",
    "write_snapshot": "bodywork_tpu.data.snapshot",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
