from bodywork_tpu.data.generator import (
    DriftConfig,
    alpha,
    generate_day,
    generate_dataframe,
)
from bodywork_tpu.data.io import (
    Dataset,
    load_all_datasets,
    load_dataset,
    load_latest_dataset,
    persist_dataset,
)

__all__ = [
    "DriftConfig",
    "alpha",
    "generate_day",
    "generate_dataframe",
    "Dataset",
    "load_all_datasets",
    "load_dataset",
    "load_latest_dataset",
    "persist_dataset",
]
