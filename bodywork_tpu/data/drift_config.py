"""The generative-model parameter set, dependency-free.

``DriftConfig`` is a plain frozen dataclass of floats (defaults =
reference ``stage_3:19,36-38``). It lives apart from
``data.generator`` — which imports jax for the fused sampler — so that
processes that only CARRY the config (the runner constructing a
``StageContext``, the jax-free test stage's pod) never pull the
accelerator runtime. ``generator`` re-exports it; importing it from
either module is equivalent.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Generative-model parameters (defaults = reference ``stage_3:19,36-38``)."""

    n_samples: int = 24 * 60          # rows sampled per simulated day
    beta: float = 0.5                 # slope
    sigma: float = 10.0               # noise scale
    freq: float = 6.0                 # intercept cycles per year
    kappa: float = 1.0                # intercept mean
    amplitude: float = 0.5            # intercept oscillation amplitude
    x_low: float = 0.0
    x_high: float = 100.0
    seed: int = 42                    # global seed folded with the date
    #: heteroscedasticity: noise scale grows linearly with x, from
    #: ``sigma`` at ``x_low`` to ``sigma * (1 + hetero)`` at ``x_high``.
    #: 0.0 (the default) traces the EXACT pre-tenancy sampler graph —
    #: the generator branches in Python on this static field — so every
    #: existing dataset stays byte-identical. Used by the scenario zoo
    #: (``tenancy/scenarios.py``).
    hetero: float = 0.0
