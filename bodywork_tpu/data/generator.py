"""Synthetic drift-data generator (reference C4, the "drift engine").

Behavioral spec reproduced exactly from
``stage_3_synthetic_data_generation.py:28-43`` (see SURVEY.md §2):

    y = alpha(d) + beta * X + sigma * eps
    X ~ U(0, 100), eps ~ N(0, 1), n = 24*60 = 1440 rows/day, keep y >= 0
    alpha(d) = kappa + A * sin(2*pi*f*(d-1)/364)      # d = day of year
    beta = 0.5, sigma = 10, f = 6, kappa = 1, A = 0.5

Concept drift: the intercept oscillates 6 cycles/year in [0.5, 1.5],
deliberately degrading any model trained on earlier days — drift as a
controlled failure mode.

TPU-native design differences from the reference (not bugs — upgrades):

- ``jax.random`` with an explicit per-day PRNG key derived from the simulated
  date, so every day's dataset is *reproducible* (the reference's seedless
  ``np.random`` is not).
- Sampling is a single fused jitted program; the ``y >= 0`` filter runs on
  device via a mask and the (data-dependent) compaction happens on host,
  keeping shapes static inside ``jit``.
- The generator is parameterised by simulated date rather than wall-clock
  ``date.today()`` (``stage_3:35``), so multi-day simulations can run faster
  than real time.
"""
from __future__ import annotations

from datetime import date
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodywork_tpu.data.drift_config import DriftConfig
from bodywork_tpu.utils.dates import day_of_year

__all__ = [
    "DriftConfig",  # re-export: defined dependency-free in drift_config
    "alpha",
    "key_for_date",
    "generate_day",
    "generate_dataframe",
]


def alpha(day: jax.Array | int, cfg: DriftConfig = DriftConfig()) -> jax.Array:
    """Drifting intercept for a given day-of-year (``stage_3:31-33``)."""
    day = jnp.asarray(day, dtype=jnp.float32)
    return cfg.kappa + cfg.amplitude * jnp.sin(
        2.0 * jnp.pi * cfg.freq * (day - 1.0) / 364.0
    )


def key_for_date(d: date, cfg: DriftConfig = DriftConfig()) -> jax.Array:
    """Deterministic PRNG key for a simulated date."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.seed), d.toordinal())


@partial(jax.jit, static_argnums=(2,))
def _sample_day(key: jax.Array, day: jax.Array, cfg: DriftConfig):
    """Fused sampler: one (3, n_samples) array stacking (X, y, valid_mask) —
    a single device->host transfer instead of three."""
    kx, ke = jax.random.split(key)
    x = jax.random.uniform(
        kx, (cfg.n_samples,), minval=cfg.x_low, maxval=cfg.x_high
    )
    eps = jax.random.normal(ke, (cfg.n_samples,))
    if cfg.hetero:
        # heteroscedastic scenario (tenancy/scenarios.py): noise scale
        # ramps with x. Python-branched on the static cfg so hetero=0.0
        # traces the exact pre-tenancy graph — byte-identical datasets.
        span = max(cfg.x_high - cfg.x_low, 1e-9)
        scale = cfg.sigma * (1.0 + cfg.hetero * (x - cfg.x_low) / span)
        y = alpha(day, cfg) + cfg.beta * x + scale * eps
    else:
        y = alpha(day, cfg) + cfg.beta * x + cfg.sigma * eps
    return jnp.stack([x, y, (y >= 0.0).astype(x.dtype)])


def generate_day(
    d: date, cfg: DriftConfig = DriftConfig()
) -> tuple[np.ndarray, np.ndarray]:
    """Generate one simulated day's data: returns host arrays (X, y).

    Rows with ``y < 0`` are dropped, as in the reference's
    ``dataset.query('y >= 0')`` (``stage_3:43``).
    """
    stacked = np.asarray(_sample_day(key_for_date(d, cfg), day_of_year(d), cfg))
    x, y, mask = stacked[0], stacked[1], stacked[2] > 0.0
    return x[mask], y[mask]


def generate_dataframe(d: date, cfg: DriftConfig = DriftConfig()):
    """One day's data as a DataFrame with the reference's exact column schema
    ``['date', 'y', 'X']`` (``stage_3:42``)."""
    import pandas as pd

    x, y = generate_day(d, cfg)
    return pd.DataFrame({"date": np.full(len(x), str(d)), "y": y, "X": x})
