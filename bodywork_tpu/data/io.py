"""Dataset I/O against the artefact store.

Replaces the reference's per-stage S3 dataset plumbing:

- persist: ``stage_3_synthetic_data_generation.py:46-61`` (CSV with columns
  ``date,y,X``, ``header=True, index=False``, key
  ``datasets/regression-dataset-<date>.csv``).
- load-all-history (training): ``stage_1_train_model.py:39-76`` — the
  reference re-downloads *every* day's CSV from S3 on each training run
  (O(days) round-trips); here history lives on the local/TPU-VM filesystem
  and is concatenated once.
- load-latest (live testing): ``stage_4_test_model_scoring_service.py:39-63``.
"""
from __future__ import annotations

import io
from datetime import date

import numpy as np
import pandas as pd

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import DATASETS_PREFIX, dataset_key
from bodywork_tpu.utils.logging import get_logger

log = get_logger("data.io")


class Dataset:
    """A (X, y) regression dataset with its artefact date."""

    def __init__(self, X: np.ndarray, y: np.ndarray, data_date: date | None = None):
        self.X = np.asarray(X, dtype=np.float32)
        self.y = np.asarray(y, dtype=np.float32)
        if self.X.ndim == 1:
            self.X = self.X[:, None]
        self.date = data_date

    def __len__(self) -> int:
        return self.X.shape[0]

    def to_dataframe(self) -> pd.DataFrame:
        d = str(self.date) if self.date else ""
        cols = {"date": np.full(len(self), d), "y": self.y, "X": self.X[:, 0]}
        # extra feature columns beyond the reference's single 'X' are
        # serialised as X2, X3, ... so multi-feature datasets round-trip
        for i in range(1, self.X.shape[1]):
            cols[f"X{i + 1}"] = self.X[:, i]
        return pd.DataFrame(cols)

    @classmethod
    def from_dataframe(cls, df: pd.DataFrame, data_date: date | None = None) -> "Dataset":
        x_cols = ["X"] + sorted(
            (c for c in df.columns if c.startswith("X") and c[1:].isdigit()),
            key=lambda c: int(c[1:]),
        )
        return cls(df[x_cols].values, df["y"].values, data_date)


def persist_dataset(store: ArtefactStore, ds: Dataset) -> str:
    """Write a day's dataset as CSV under ``datasets/`` (``stage_3:46-61``)."""
    assert ds.date is not None, "dataset must carry its simulated date"
    key = dataset_key(ds.date)
    buf = io.StringIO()
    ds.to_dataframe().to_csv(buf, header=True, index=False)
    store.put_text(key, buf.getvalue())
    log.info(f"persisted {len(ds)} rows to {key}")
    return key


def _parse_dataset_csv(data: bytes, key: str) -> Dataset:
    from bodywork_tpu.utils.dates import date_from_key

    df = pd.read_csv(io.BytesIO(data))
    return Dataset.from_dataframe(df, date_from_key(key))


def load_dataset(store: ArtefactStore, key: str) -> Dataset:
    return _parse_dataset_csv(store.get_bytes(key), key)


def load_latest_dataset(store: ArtefactStore) -> Dataset:
    """Latest day's dataset (``stage_4:39-63``)."""
    key, _ = store.latest(DATASETS_PREFIX)
    return load_dataset(store, key)


def load_history_parts(
    store: ArtefactStore,
    hist: list,
    tokens: dict,
    record_outcome: bool = True,
) -> dict[str, Dataset]:
    """Per-day parsed datasets for every ``hist`` entry, resolved through
    three tiers, cheapest first:

    1. the per-day parsed cache (keyed by the backend's ``version_token``);
    2. the latest valid consolidated snapshot (``data.snapshot``) — a
       covered day is trusted only while its recorded token equals the
       store's current one, so an overwritten day degrades to tier 3 for
       that day alone;
    3. a batched ``store.get_many`` fetch + CSV parse of the remainder
       (parallel round-trips on backends that support it).

    Snapshot slices feed the per-day cache, so a cold process's first
    load warms the same cache a long-lived one builds incrementally.
    ``record_outcome=False`` keeps maintenance reads (the compactor's
    own, via ``write_snapshot``/``plan_compaction``) out of the
    hit/stale/miss loader counters operators alert on.
    """
    cache: dict = store.mutable_cache("_parsed_dataset_cache")
    dates = dict(hist)
    parts: dict[str, Dataset] = {}
    missing: list[str] = []
    for key, _ in hist:
        token = tokens.get(key)
        hit = cache.get(key) if token is not None else None
        if hit is not None and hit[0] == token:
            parts[key] = hit[1]
        else:
            missing.append(key)
    n_from_snapshot = 0
    if missing:
        from bodywork_tpu.data import snapshot as snapshot_mod
        from bodywork_tpu.store.schema import SNAPSHOTS_PREFIX

        snaps = store.history(SNAPSHOTS_PREFIX)
        snap = None
        if not snaps:
            if record_outcome:
                snapshot_mod.record_load_outcome("miss")
        elif any(dates[key] <= snaps[-1][1] for key in missing):
            # the listing alone bounds what the snapshot can cover (its
            # embedded date): only read the payload when some missing day
            # could actually be in it. Without this cut the WARM daily
            # loop — whose only missing day is the freshly generated one
            # — would re-download the ever-growing snapshot artefact
            # every day for nothing, and record a phantom "stale" in the
            # healthy steady state.
            snap = snapshot_mod.load_latest_snapshot(
                store, hist=snaps, record_outcome=record_outcome
            )
        if snap is not None:
            hist_keys = {key for key, _ in hist}
            slices = snap.slices()
            usable = {}
            covered_mismatch = False
            for entry in snap.entries:
                key = entry["key"]
                token = tokens.get(key)
                if key in hist_keys and token is not None:
                    if snapshot_mod.canon_token(token) == entry["token"]:
                        usable[key] = token
                    else:
                        covered_mismatch = True
            if covered_mismatch:
                # a covered day was OVERWRITTEN since the snapshot (same
                # date, new token): the date-only refresh_due check can't
                # see this, so flag it for the in-process compactor —
                # otherwise every cold reader pays that day's GET forever
                store.mutable_cache("_snapshot_state")["repair_needed"] = True
            still_missing = []
            for key in missing:
                token = usable.get(key)
                if token is None:
                    still_missing.append(key)
                    continue
                Xs, ys = slices[key]
                ds = Dataset(Xs, ys, dates[key])
                cache[key] = (token, ds)
                parts[key] = ds
                n_from_snapshot += 1
            if record_outcome:
                snapshot_mod.record_load_outcome(
                    "hit" if not still_missing else "stale"
                )
            missing = still_missing
    if missing:
        blobs = store.get_many(missing)
        for key in missing:
            ds = _parse_dataset_csv(blobs[key], key)
            token = tokens.get(key)
            if token is not None:
                cache[key] = (token, ds)
            parts[key] = ds
    log.info(
        f"history parts: {len(hist)} day(s) — "
        f"{len(hist) - n_from_snapshot - len(missing)} cached, "
        f"{n_from_snapshot} from snapshot, {len(missing)} fetched+parsed"
    )
    return parts


def load_all_datasets(store: ArtefactStore) -> Dataset:
    """All available history, oldest first, concatenated (``stage_1:39-76``).

    The reference re-downloads and re-parses every day's CSV on each
    training run — O(days) round-trips on a monotonically growing history
    (``stage_1:68-71``; SURVEY.md hard part 2). Here three layers
    eliminate that, coldest to warmest:

    - a cold process loads the latest consolidated snapshot plus only the
      tail days written after it — O(1 + tail) store reads
      (:mod:`bodywork_tpu.data.snapshot`);
    - a warm process re-parses only days whose ``version_token`` changed
      (the per-day parsed cache);
    - a fully-warm reload whose exact ``(key, token)`` list is unchanged
      skips even the O(total-rows) concatenation (the concat cache).

    The returned ``Dataset`` is byte-identical across all paths —
    snapshot present, stale, corrupt, or absent.
    """
    hist = store.history(DATASETS_PREFIX)
    if not hist:
        from bodywork_tpu.store.base import ArtefactNotFound

        raise ArtefactNotFound(f"no datasets under '{DATASETS_PREFIX}'")
    keys = [key for key, _ in hist]
    tokens = store.version_tokens(keys)
    most_recent = hist[-1][1]
    concat_cache: dict = store.mutable_cache("_concat_history_cache")
    concat_key = None
    if len(tokens) == len(keys):  # every key verifiable
        concat_key = tuple((k, repr(tokens[k])) for k in keys)
        cached = concat_cache.get(concat_key)
        if cached is not None:
            X, y = cached
            log.info(
                f"loaded {len(keys)} day(s) (concatenation cache hit), "
                f"{len(y)} rows, most recent {most_recent}"
            )
            return Dataset(X, y, most_recent)
    parts = load_history_parts(store, hist, tokens)
    X = np.concatenate([parts[k].X for k in keys])
    y = np.concatenate([parts[k].y for k in keys])
    if concat_key is not None:
        # one entry only: histories are cumulative, so yesterday's concat
        # can never hit again — keeping it would double peak memory
        concat_cache.clear()
        concat_cache[concat_key] = (X, y)
    log.info(
        f"loaded {len(parts)} day(s), {len(y)} rows, "
        f"most recent {most_recent}"
    )
    return Dataset(X, y, most_recent)
