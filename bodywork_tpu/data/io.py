"""Dataset I/O against the artefact store.

Replaces the reference's per-stage S3 dataset plumbing:

- persist: ``stage_3_synthetic_data_generation.py:46-61`` (CSV with columns
  ``date,y,X``, ``header=True, index=False``, key
  ``datasets/regression-dataset-<date>.csv``).
- load-all-history (training): ``stage_1_train_model.py:39-76`` — the
  reference re-downloads *every* day's CSV from S3 on each training run
  (O(days) round-trips); here history lives on the local/TPU-VM filesystem
  and is concatenated once.
- load-latest (live testing): ``stage_4_test_model_scoring_service.py:39-63``.
"""
from __future__ import annotations

import io
from datetime import date

import numpy as np
import pandas as pd

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import DATASETS_PREFIX, dataset_key
from bodywork_tpu.utils.logging import get_logger

log = get_logger("data.io")


class Dataset:
    """A (X, y) regression dataset with its artefact date."""

    def __init__(self, X: np.ndarray, y: np.ndarray, data_date: date | None = None):
        self.X = np.asarray(X, dtype=np.float32)
        self.y = np.asarray(y, dtype=np.float32)
        if self.X.ndim == 1:
            self.X = self.X[:, None]
        self.date = data_date

    def __len__(self) -> int:
        return self.X.shape[0]

    def to_dataframe(self) -> pd.DataFrame:
        d = str(self.date) if self.date else ""
        cols = {"date": np.full(len(self), d), "y": self.y, "X": self.X[:, 0]}
        # extra feature columns beyond the reference's single 'X' are
        # serialised as X2, X3, ... so multi-feature datasets round-trip
        for i in range(1, self.X.shape[1]):
            cols[f"X{i + 1}"] = self.X[:, i]
        return pd.DataFrame(cols)

    @classmethod
    def from_dataframe(cls, df: pd.DataFrame, data_date: date | None = None) -> "Dataset":
        x_cols = ["X"] + sorted(
            (c for c in df.columns if c.startswith("X") and c[1:].isdigit()),
            key=lambda c: int(c[1:]),
        )
        return cls(df[x_cols].values, df["y"].values, data_date)


def persist_dataset(store: ArtefactStore, ds: Dataset) -> str:
    """Write a day's dataset as CSV under ``datasets/`` (``stage_3:46-61``)."""
    assert ds.date is not None, "dataset must carry its simulated date"
    key = dataset_key(ds.date)
    buf = io.StringIO()
    ds.to_dataframe().to_csv(buf, header=True, index=False)
    store.put_text(key, buf.getvalue())
    log.info(f"persisted {len(ds)} rows to {key}")
    return key


def load_dataset(store: ArtefactStore, key: str) -> Dataset:
    from bodywork_tpu.utils.dates import date_from_key

    df = pd.read_csv(io.BytesIO(store.get_bytes(key)))
    return Dataset.from_dataframe(df, date_from_key(key))


def load_latest_dataset(store: ArtefactStore) -> Dataset:
    """Latest day's dataset (``stage_4:39-63``)."""
    key, _ = store.latest(DATASETS_PREFIX)
    return load_dataset(store, key)


def load_all_datasets(store: ArtefactStore) -> Dataset:
    """All available history, oldest first, concatenated (``stage_1:39-76``).

    The reference re-downloads and re-parses every day's CSV on each
    training run — O(days) round-trips on a monotonically growing history
    (``stage_1:68-71``; SURVEY.md hard part 2). Here each day's parsed
    arrays are cached on the store instance keyed by the backend's
    ``version_token``, so a daily retrain only parses the one new day.
    """
    hist = store.history(DATASETS_PREFIX)
    if not hist:
        from bodywork_tpu.store.base import ArtefactNotFound

        raise ArtefactNotFound(f"no datasets under '{DATASETS_PREFIX}'")
    cache: dict = store.mutable_cache("_parsed_dataset_cache")
    tokens = store.version_tokens([key for key, _ in hist])
    parts, n_parsed = [], 0
    for key, _ in hist:
        token = tokens.get(key)
        hit = cache.get(key) if token is not None else None
        if hit is not None and hit[0] == token:
            parts.append(hit[1])
            continue
        ds = load_dataset(store, key)
        n_parsed += 1
        if token is not None:
            cache[key] = (token, ds)
        parts.append(ds)
    X = np.concatenate([p.X for p in parts])
    y = np.concatenate([p.y for p in parts])
    most_recent = hist[-1][1]
    log.info(
        f"loaded {len(parts)} day(s) ({n_parsed} parsed, rest cached), "
        f"{len(y)} rows, most recent {most_recent}"
    )
    return Dataset(X, y, most_recent)
