"""Consolidated-history snapshots: the cold-path data plane.

The per-day parsed-dataset cache (``data.io``) only helps processes that
LIVE across days; every cold process — a k8s per-day Job, the daily-loop
CronJob, plain ``cli train`` — still reconstructed training history with
O(days) store round-trips and O(days) CSV parses, the reference's
re-download-everything pattern (``stage_1_train_model.py:68-71``, SURVEY
hard part 2) paid again on every pod. On the measured transport
(~67-200 ms per round-trip, PERF.md §1) that O(days) dominates cold
train-stage wall time long before the fit does.

A snapshot is one binary columnar artefact under ``snapshots/``
(``schema.snapshot_key``) holding the float32 ``X``/``y`` arrays of
every dataset day up to its embedded date, concatenated in history
order, plus a JSON manifest of covered day-keys, per-key row counts, and
per-key ``version_token``\\ s. The manifest makes staleness *detectable*:
a reader trusts a covered day only when its recorded token still equals
the store's current token, so an overwritten or deleted day degrades
that one day to a per-day fetch — never a silently wrong training set.
``load_all_datasets`` is byte-identical with the snapshot present,
stale, corrupt, or absent.

Format: ``numpy.savez`` (no new dependencies) with arrays ``X``, ``y``
and a 0-d unicode ``manifest`` array carrying the JSON. Snapshots are
derived artefacts — deleting the whole prefix is always safe.

Refresh runs OFF the critical path: the persistent runner compacts on a
background thread after each day persists, and the k8s materialisation
runs ``cli compact`` as a CronJob after the daily loop (one-shot pods
never pay the write; they only enjoy the read).
"""
from __future__ import annotations

import dataclasses
import io
import json
import time

import numpy as np

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore
from bodywork_tpu.store.schema import (
    DATASETS_PREFIX,
    SNAPSHOTS_PREFIX,
    snapshot_key,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("data.snapshot")

SNAPSHOT_SCHEMA = "bodywork_tpu.history_snapshot/1"

#: snapshots retained per store; older ones are pruned on each write
#: (each snapshot is a full consolidation, so one valid file suffices —
#: the second is race headroom for a reader mid-``latest`` during a write)
SNAPSHOT_KEEP = 2


def canon_token(token) -> object:
    """A ``version_token`` in the form it round-trips through the JSON
    manifest (tuples become lists), so recorded and current tokens
    compare equal exactly when the backend would call them equal.
    Non-JSON-able tokens canonicalise via ``repr`` — stable for the
    value types real backends use, and at worst a false MISMATCH (a
    per-day re-fetch), never a false match."""
    try:
        return json.loads(json.dumps(token))
    except (TypeError, ValueError):
        return repr(token)


@dataclasses.dataclass
class Snapshot:
    """A parsed snapshot artefact: the concatenated arrays plus the
    manifest entries (``{"key", "rows", "token"}`` in history order)."""

    key: str
    X: np.ndarray
    y: np.ndarray
    entries: list[dict]

    def slices(self) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        """Per-day ``(X, y)`` views into the columnar arrays, keyed by
        the covered dataset key (no copies — readers concatenate)."""
        out = {}
        offset = 0
        for entry in self.entries:
            rows = entry["rows"]
            out[entry["key"]] = (
                self.X[offset:offset + rows],
                self.y[offset:offset + rows],
            )
            offset += rows
        return out


def record_load_outcome(outcome: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_snapshot_loads_total",
        "Snapshot consultations by the history loader, by outcome "
        "(hit: covered everything; stale: used, but some days needed "
        "per-day fetch; miss: no snapshot; corrupt: unreadable)",
    ).inc(outcome=outcome)


def load_latest_snapshot(
    store: ArtefactStore,
    hist: list | None = None,
    record_outcome: bool = True,
) -> Snapshot | None:
    """The newest *parseable* snapshot, or None (none kept, or all
    unreadable — the caller falls back to per-day loads either way).

    Cost: one listing + one ``get_bytes`` — the O(1) read the whole
    layer exists for. A corrupt newest snapshot falls back to the older
    kept one (``SNAPSHOT_KEEP`` exists exactly for this) at one extra
    GET, and flags ``repair_needed`` so the in-process compactor
    rewrites it instead of every cold reader paying the degradation
    until the next dataset day. Pass ``hist`` (a prior
    ``history(SNAPSHOTS_PREFIX)`` result) to skip re-listing;
    ``record_outcome=False`` keeps maintenance reads (the compactor's
    own) out of the loader-outcome counters.
    """
    if hist is None:
        hist = store.history(SNAPSHOTS_PREFIX)
    if not hist:
        if record_outcome:
            record_load_outcome("miss")
        return None
    corrupt_seen = False
    found = None
    for key, _ in reversed(hist):
        try:
            raw = store.get_bytes(key)
            with np.load(io.BytesIO(raw), allow_pickle=False) as npz:
                manifest = json.loads(str(npz["manifest"][()]))
                X = npz["X"]
                y = npz["y"]
            if manifest.get("schema") != SNAPSHOT_SCHEMA:
                raise ValueError(
                    f"unknown snapshot schema {manifest.get('schema')!r}"
                )
            entries = manifest["covered"]
            n_rows = sum(e["rows"] for e in entries)
            if X.shape[0] != n_rows or y.shape[0] != n_rows:
                raise ValueError(
                    f"manifest covers {n_rows} rows but arrays hold "
                    f"{X.shape[0]}/{y.shape[0]}"
                )
        except ArtefactNotFound:
            continue  # pruned between listing and read: try the older one
        except Exception as exc:
            # a torn/garbled artefact must degrade — to the older kept
            # snapshot first, then to the per-day path — never crash
            # training or serve a wrong dataset
            log.warning(f"snapshot {key} unreadable ({exc!r}); ignoring it")
            if record_outcome:
                record_load_outcome("corrupt")
            corrupt_seen = True
            continue
        found = Snapshot(key=key, X=X, y=y, entries=entries)
        break
    if corrupt_seen:
        store.mutable_cache("_snapshot_state")["repair_needed"] = True
    if found is None and not corrupt_seen and record_outcome:
        record_load_outcome("miss")  # every kept snapshot was pruned away
    return found


def write_snapshot(store: ArtefactStore, keep: int = SNAPSHOT_KEEP) -> str | None:
    """Consolidate every dataset day currently in the store into one
    snapshot artefact; returns its key (None on an empty store).

    Reads ride the same parsed-dataset cache as ``load_all_datasets``
    (and the latest snapshot itself), so compacting from a warm process
    parses nothing. Older snapshots beyond ``keep`` are pruned.
    """
    from bodywork_tpu.data.io import load_history_parts

    t0 = time.perf_counter()
    hist = store.history(DATASETS_PREFIX)
    if not hist:
        return None
    tokens = store.version_tokens([k for k, _ in hist])
    # filter BEFORE fetching: an unverifiable (token-less) day would be
    # dead weight — readers only trust entries whose token still matches
    # — so downloading it just to discard it wastes the whole read, and
    # a fully token-less backend must bail here, not after O(days) GETs
    consolidatable = []
    for key, d in hist:
        if tokens.get(key) is None:
            log.warning(f"snapshot skips {key}: backend reports no version token")
        else:
            consolidatable.append((key, d))
    if not consolidatable:
        return None
    # record_outcome=False: this is a MAINTENANCE read — a healthy daily
    # compaction finding yesterday's snapshot "stale" is expected, and
    # counting it would fire the operator alert the counter feeds
    parts = load_history_parts(
        store, consolidatable, tokens, record_outcome=False
    )
    covered = [
        {"key": key, "rows": len(parts[key]), "token": canon_token(tokens[key])}
        for key, _ in consolidatable
    ]
    X = np.concatenate([parts[e["key"]].X for e in covered])
    y = np.concatenate([parts[e["key"]].y for e in covered])
    most_recent = consolidatable[-1][1]  # hist (and this) sort oldest-first
    manifest = {
        "schema": SNAPSHOT_SCHEMA,
        "covered": covered,
        "n_rows": int(X.shape[0]),
        "most_recent": str(most_recent),
    }
    buf = io.BytesIO()
    np.savez(buf, X=X, y=y, manifest=np.array(json.dumps(manifest)))
    key = snapshot_key(most_recent)
    store.put_bytes(key, buf.getvalue())
    _prune_snapshots(store, keep)
    # the freshly written snapshot matches current tokens by construction
    store.mutable_cache("_snapshot_state")["repair_needed"] = False
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_snapshot_writes_total", "Snapshot compactions written"
    ).inc()
    reg.gauge(
        "bodywork_tpu_snapshot_rows",
        "Rows covered by the most recently written snapshot",
    ).set(X.shape[0])
    log.info(
        f"wrote snapshot {key}: {len(covered)} day(s), {X.shape[0]} rows "
        f"in {time.perf_counter() - t0:.3f}s"
    )
    return key


def _prune_snapshots(store: ArtefactStore, keep: int) -> None:
    hist = store.history(SNAPSHOTS_PREFIX)
    for key, _ in hist[:-keep] if keep > 0 else hist:
        try:
            store.delete(key)
        except ArtefactNotFound:
            pass  # concurrent compactor got there first


def refresh_due(store: ArtefactStore) -> bool:
    """True when the latest snapshot no longer covers the latest dataset
    day (or none exists) — the cheap, listing-only trigger the runner's
    background compactor polls after each day persists.

    An overwritten day (same date, new token) and a corrupt snapshot
    artefact are both invisible to the date comparison; the history
    loader flags either case on the store's ``_snapshot_state`` cache
    when it hits it, and the flag triggers a refresh here (cleared by
    the next ``write_snapshot``).
    """
    try:
        _, latest_day = store.latest(DATASETS_PREFIX)
    except ArtefactNotFound:
        return False
    if store.mutable_cache("_snapshot_state").get("repair_needed"):
        return True
    snaps = store.history(SNAPSHOTS_PREFIX)
    return not snaps or snaps[-1][1] < latest_day


def plan_compaction(store: ArtefactStore) -> dict:
    """What ``write_snapshot`` would consolidate, without writing — the
    ``cli compact --dry-run`` payload operators size the CronJob with.

    Parses the uncovered days (through the shared caches) to count rows;
    the estimate is the uncompressed npz payload (4 bytes per float32
    cell) plus the manifest.
    """
    hist = store.history(DATASETS_PREFIX)
    snaps = store.history(SNAPSHOTS_PREFIX)
    plan: dict = {
        "days": len(hist),
        "latest_snapshot": snaps[-1][0] if snaps else None,
        "snapshots_kept": len(snaps),
    }
    if not hist:
        plan.update(rows=0, estimated_bytes=0, covered_days=[],
                    days_without_tokens=0, would_write=None)
        return plan
    tokens = store.version_tokens([k for k, _ in hist])
    # apply write_snapshot's exact filter: token-less days are skipped by
    # the writer, so the plan must not promise to consolidate them
    consolidatable = [(k, d) for k, d in hist if tokens.get(k) is not None]
    plan["days_without_tokens"] = len(hist) - len(consolidatable)
    if not consolidatable:
        plan.update(rows=0, estimated_bytes=0, covered_days=[],
                    would_write=None)
        return plan
    from bodywork_tpu.data.io import load_history_parts

    # fetch only the consolidatable days: token-less days are skipped by
    # the writer, so downloading their payloads here would be pure waste
    # (the filter-before-fetch rule write_snapshot itself follows)
    parts = load_history_parts(
        store, consolidatable, tokens, record_outcome=False
    )
    rows = sum(len(parts[k]) for k, _ in consolidatable)
    n_features = next(iter(parts.values())).X.shape[1]
    plan.update(
        rows=rows,
        estimated_bytes=rows * 4 * (n_features + 1),
        covered_days=[str(d) for _, d in consolidatable],
        would_write=str(snapshot_key(consolidatable[-1][1])),
    )
    return plan


