from bodywork_tpu.models.base import Regressor, TrainSplit, train_test_split
from bodywork_tpu.models.linear import LinearRegressor, LinearConfig
from bodywork_tpu.models.mlp import MLPRegressor, MLPConfig
from bodywork_tpu.models.metrics import regression_metrics
from bodywork_tpu.models.checkpoint import (
    MODEL_REGISTRY,
    load_model,
    load_model_bytes,
    save_model,
    save_model_bytes,
)

__all__ = [
    "Regressor",
    "TrainSplit",
    "train_test_split",
    "LinearRegressor",
    "LinearConfig",
    "MLPRegressor",
    "MLPConfig",
    "regression_metrics",
    "MODEL_REGISTRY",
    "load_model",
    "load_model_bytes",
    "save_model",
    "save_model_bytes",
]
