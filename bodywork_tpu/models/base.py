"""Regressor protocol and train/test split.

The reference's model layer is the sklearn estimator protocol
(``fit(X, y)`` / ``predict(X)`` — ``stage_1_train_model.py:105-107``,
``stage_2_serve_model.py:78``). Here the protocol is functional-style:
models are thin wrappers around a JAX pytree of parameters plus a static
config; ``fit`` returns a *new* fitted model, ``predict`` routes through a
jitted apply function that is cached per model class (so repeated instances
never recompile).
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any

import jax
import numpy as np


def _bucket_rows(n: int, minimum: int = 1024) -> int:
    """Next power-of-two row count >= n (>= minimum).

    Padding datasets to bucketed static shapes keeps the number of distinct
    XLA compilations logarithmic in dataset size as the simulated-day history
    grows — the TPU answer to SURVEY.md's "hard part (2)".
    """
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_rows(X: np.ndarray, y: np.ndarray, minimum: int = 1024):
    """Zero-pad (X, y) to a bucketed row count; returns (Xp, yp, weights)."""
    n = X.shape[0]
    b = _bucket_rows(n, minimum)
    Xp = np.zeros((b,) + X.shape[1:], dtype=X.dtype)
    yp = np.zeros((b,), dtype=y.dtype)
    w = np.zeros((b,), dtype=np.float32)
    Xp[:n] = X
    yp[:n] = y
    w[:n] = 1.0
    return Xp, yp, w


@dataclasses.dataclass
class TrainSplit:
    X_train: np.ndarray
    y_train: np.ndarray
    X_test: np.ndarray
    y_test: np.ndarray


def train_test_split(
    X: np.ndarray, y: np.ndarray, test_size: float = 0.2, seed: int = 42
) -> TrainSplit:
    """Random 80/20 split with a fixed seed (reference ``stage_1:98-103``,
    ``test_size=0.2, random_state=42``)."""
    n = X.shape[0]
    perm = np.random.default_rng(seed).permutation(n)
    n_test = int(round(n * test_size))
    test_idx, train_idx = perm[:n_test], perm[n_test:]
    return TrainSplit(X[train_idx], y[train_idx], X[test_idx], y[test_idx])


#: per-class cache of fused evaluate programs (see Regressor.evaluate)
_EVAL_FNS: dict[type, Any] = {}

#: per-class cache of jitted apply functions (see Regressor.predict_device)
_APPLY_FNS: dict[type, Any] = {}


class Regressor(abc.ABC):
    """Fitted-or-unfitted regression model over a JAX pytree of params."""

    #: short registry name, e.g. "linear" / "mlp" (used in checkpoints)
    model_type: str = "base"

    #: the pure apply function ``(params, X(n,d)) -> y(n,)`` backing
    #: ``predict`` — set per subclass; used to build fused programs
    apply = None

    def __init__(self, config: Any = None, params: Any = None):
        self.config = config
        self.params = params
        #: host (numpy) copy of params, populated by the fused fit+eval path
        #: so checkpointing never re-fetches from the device (each fetch is a
        #: full tunnel round-trip on a remote-attached TPU)
        self._host_params: Any = None

    # -- estimator protocol ------------------------------------------------
    @abc.abstractmethod
    def fit(
        self, X: np.ndarray, y: np.ndarray, seed: int | None = None
    ) -> "Regressor":
        """Return a fitted copy of this model.

        ``seed`` overrides the model config's own seed when given; None
        defers to the config (deterministic models ignore it entirely).
        """

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Predict targets; accepts (n, d) or (n,) arrays. Routes through
        the per-class jitted apply cache (:meth:`predict_device`), so there
        is exactly ONE compiled apply program per class per shape."""
        return np.asarray(self.predict_device(X))

    def fit_and_evaluate(
        self,
        X_train: np.ndarray,
        y_train: np.ndarray,
        X_test: np.ndarray,
        y_test: np.ndarray,
        seed: int | None = None,
        materialize: bool = True,
    ) -> tuple["Regressor", dict[str, float]] | tuple[None, None]:
        """Fit on the train split and score the held-out split.

        Default implementation is fit-then-evaluate (several device
        round-trips); Linear/MLP override it with a single fused XLA
        program whose result comes back in ONE device->host transfer
        (see :mod:`bodywork_tpu.models.fused`). ``materialize=False`` is
        the prewarming mode: compile both programs and return
        ``(None, None)`` — the fused overrides additionally skip the host
        fetch entirely; this generic fallback still blocks on the fit.
        """
        fitted = self.fit(X_train, y_train, seed=seed)
        if not materialize:
            fitted.evaluate(X_test, y_test)  # compile the eval program too
            return None, None
        return fitted, fitted.evaluate(X_test, y_test)

    @staticmethod
    def _pad_splits(
        X_train: np.ndarray,
        y_train: np.ndarray,
        X_test: np.ndarray,
        y_test: np.ndarray,
    ):
        """Shared input coercion + bucket padding for the fused fit+eval
        paths: float32, (n, d) features, ravelled targets, train padded to
        the fit bucket and test to the eval bucket (min 256)."""

        def _coerce(X, y):
            X = np.asarray(X, dtype=np.float32)
            if X.ndim == 1:
                X = X[:, None]
            return X, np.asarray(y, dtype=np.float32).ravel()

        X_train, y_train = _coerce(X_train, y_train)
        X_test, y_test = _coerce(X_test, y_test)
        return pad_rows(X_train, y_train) + pad_rows(
            X_test, y_test, minimum=256
        )

    def host_params(self):
        """Params as host numpy arrays, fetching from device only if the
        fused fit path didn't already deliver a host copy."""
        assert self.params is not None, "model is not fitted"
        if self._host_params is not None:
            return self._host_params
        self._host_params = jax.tree_util.tree_map(
            np.asarray, jax.device_get(self.params)
        )
        return self._host_params

    def predict_device(self, X: np.ndarray):
        """Dispatch the jitted apply WITHOUT materialising the result on the
        host (no device->host transfer; returns the device array). Used by
        serving warmup, where only the compile + dispatch matter."""
        assert self.params is not None, "model is not fitted"
        assert type(self).apply is not None, (
            f"{type(self).__name__} does not define an apply function"
        )
        fn = _APPLY_FNS.get(type(self))
        if fn is None:
            fn = _APPLY_FNS[type(self)] = jax.jit(type(self).apply)
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        return fn(self.params, X)

    def evaluate(self, X: np.ndarray, y: np.ndarray) -> dict[str, float]:
        """MAPE / R^2 / max-residual of this model on (X, y), computed as a
        single fused device program over padded shapes (predict + metrics in
        one dispatch; see :func:`~bodywork_tpu.models.metrics.make_eval_fn`)."""
        from bodywork_tpu.models.metrics import make_eval_fn

        assert self.params is not None, "model is not fitted"
        assert type(self).apply is not None, (
            f"{type(self).__name__} does not define an apply function"
        )
        fn = _EVAL_FNS.get(type(self))
        if fn is None:
            fn = _EVAL_FNS[type(self)] = make_eval_fn(type(self).apply)
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(y, dtype=np.float32).ravel()
        Xp, yp, w = pad_rows(X, y, minimum=256)
        mape, r2, max_resid = fn(self.params, Xp, yp, w)
        return {
            "MAPE": float(mape),
            "r_squared": float(r2),
            "max_residual": float(max_resid),
        }

    def predict_padded(self, X: np.ndarray, minimum: int = 256) -> np.ndarray:
        """Predict through a power-of-two row bucket.

        Evaluation on arbitrary-sized arrays (e.g. a growing held-out split)
        would trigger one XLA recompile per distinct shape; padding keeps the
        compile count logarithmic. Serving uses the richer
        :class:`~bodywork_tpu.serve.predictor.PaddedPredictor`; this is the
        lightweight equivalent for in-process evaluation."""
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        n = X.shape[0]
        b = _bucket_rows(n, minimum)
        if b == n:
            return np.asarray(self.predict(X))
        Xp = np.zeros((b, X.shape[1]), dtype=X.dtype)
        Xp[:n] = X
        return np.asarray(self.predict(Xp))[:n]

    # -- serving metadata --------------------------------------------------
    @property
    def n_features(self) -> int | None:
        """Input feature dimension of a fitted model (None if unfitted).

        Serving warmup uses this to pre-compile the right bucket shapes.
        """
        return None

    @property
    def info(self) -> str:
        """The ``model_info`` string in the scoring response — the analogue
        of the reference's ``str(model)`` == "LinearRegression()"
        (``stage_2_serve_model.py:79``)."""
        return f"{type(self).__name__}()"

    def __repr__(self) -> str:
        return self.info

    # -- checkpoint hooks (see checkpoint.py) ------------------------------
    def config_dict(self) -> dict:
        return dataclasses.asdict(self.config) if self.config else {}

    @classmethod
    @abc.abstractmethod
    def from_config_dict(cls, cfg: dict, params: Any) -> "Regressor": ...
