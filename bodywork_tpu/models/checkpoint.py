"""Model checkpointing: JAX pytrees as date-versioned artefacts.

Replaces the reference's ``joblib.dump``/``joblib.load`` model serialization
(C6 — ``stage_1_train_model.py:114``, ``stage_2_serve_model.py:65``). Format:
a single ``.npz`` holding the flattened params pytree (one entry per leaf,
keyed by its tree path) plus a JSON metadata blob (model type, config,
framework version, artefact date). Self-describing, dependency-free, and
loadable without executing pickled code (unlike joblib).
"""
from __future__ import annotations

import io
import json
from datetime import date

import jax
import numpy as np

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import MODELS_PREFIX, model_key
from bodywork_tpu.utils.logging import get_logger
from bodywork_tpu.version import __version__

log = get_logger("models.checkpoint")

_META_KEY = "__meta__"


def _leaf_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_model_bytes(model) -> bytes:
    """Serialise a fitted Regressor to npz bytes."""
    assert model.params is not None, "cannot checkpoint an unfitted model"
    # host_params() is free when the fused fit path already delivered a host
    # copy; otherwise it fetches from device once
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(model.host_params())[0]
    arrays = {_leaf_path(p): np.asarray(v) for p, v in leaves_with_paths}
    meta = {
        "model_type": model.model_type,
        "config": model.config_dict(),
        "framework_version": __version__,
    }
    buf = io.BytesIO()
    np.savez(buf, **arrays, **{_META_KEY: np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)})
    return buf.getvalue()


def _listify(node):
    """Convert dict nodes whose keys are 0..n-1 back into lists."""
    if isinstance(node, dict):
        if node and all(k.isdigit() for k in node) and sorted(
            int(k) for k in node
        ) == list(range(len(node))):
            return [_listify(node[str(i)]) for i in range(len(node))]
        return {k: _listify(v) for k, v in node.items()}
    return node


def _unflatten_paths(arrays: dict[str, np.ndarray]):
    root: dict = {}
    for path, arr in arrays.items():
        parts = path.split("/")
        node = root
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return _listify(root)


def load_model_bytes(data: bytes, device: bool = True):
    """Reconstruct a fitted Regressor from npz bytes.

    ``device=False`` keeps params as host numpy arrays — for callers that
    may substitute an already-device-resident copy (see
    ``pipeline.stages.serve_stage``) before paying the host->device
    transfer.
    """
    with np.load(io.BytesIO(data)) as npz:
        meta = json.loads(bytes(npz[_META_KEY]).decode())
        arrays = {k: npz[k] for k in npz.files if k != _META_KEY}
    cls = MODEL_REGISTRY[meta["model_type"]]
    params = _unflatten_paths(arrays)
    model = cls.from_config_dict(
        meta["config"], jax.device_put(params) if device else params
    )
    if not device:
        model._host_params = params
    return model


def save_model(
    store: ArtefactStore, model, artefact_date: date,
    data: bytes | None = None,
) -> str:
    """Persist a fitted model under ``models/regressor-<date>.npz``
    (reference ``stage_1:111-125``). ``data`` lets a caller that also
    needs the serialised bytes (e.g. the registry's lineage digest)
    serialise once instead of paying the params host-transfer + npz
    encode twice."""
    key = model_key(artefact_date)
    store.put_bytes(key, data if data is not None else save_model_bytes(model))
    log.info(f"persisted {model.info} to {key}")
    return key


def resolve_serving_key(store: ArtefactStore) -> tuple[str, str]:
    """The (key, source) serving should load with no explicit key:

    - a store with an ACTIVE registry (an alias document exists —
      ``bodywork_tpu.registry``) resolves the ``production`` alias, so
      only gate-promoted checkpoints ever take traffic; ``source`` is
      ``"production"``;
    - otherwise the newest date-keyed checkpoint under ``models/`` that
      the gate has not REJECTED — a bootstrapping store whose very
      first candidate failed the gate (records exist, no promotion yet)
      must not serve it through the fallback; a checkpoint with no
      record, or one still in ``candidate`` status, serves exactly as
      today (registry-less stores are byte-identical: with no records
      the record probe is one empty listing); ``source`` is
      ``"latest"``. No serviceable checkpoint raises
      :class:`~bodywork_tpu.store.base.ArtefactNotFound` (the degraded
      -boot path).

    A corrupt alias document raises
    (:class:`bodywork_tpu.registry.records.RegistryCorrupt`) rather
    than silently degrading to the ungated fallback.
    """
    from bodywork_tpu.registry.records import load_record, resolve_alias
    from bodywork_tpu.store.base import ArtefactNotFound
    from bodywork_tpu.store.schema import REGISTRY_RECORDS_PREFIX

    key = resolve_alias(store, "production")
    if key is not None:
        return key, "production"
    hist = store.history(MODELS_PREFIX)
    if not hist:
        raise ArtefactNotFound(f"no date-keyed artefacts under '{MODELS_PREFIX}'")
    if not store.list_keys(REGISTRY_RECORDS_PREFIX):
        return hist[-1][0], "latest"  # registry-less: today's behavior
    for candidate_key, _d in reversed(hist):
        record = load_record(store, candidate_key)
        if record is not None and record.get("status") == "rejected":
            log.info(
                f"skipping gate-rejected checkpoint {candidate_key} in "
                "latest-fallback resolution"
            )
            continue
        return candidate_key, "latest"
    raise ArtefactNotFound(
        f"every checkpoint under '{MODELS_PREFIX}' was gate-rejected "
        "and none was ever promoted"
    )


def resolve_serving_state(store: ArtefactStore):
    """:func:`resolve_serving_key` plus the canary slot, with ONE alias
    read: ``(production_key, source, canary_state, canary_dangling)``.

    ``canary_state`` (``{"key", "fraction", "seed", "day", "bounds"}``)
    is set when the alias document names a live, serveable canary;
    ``canary_dangling`` carries the reason when the slot is set but
    must be ignored — a stale canary pointing at a deleted checkpoint
    or a rejected record (a crashed watchdog's debris) falls back to
    production-only serving instead of wedging boot. The reload
    watcher repairs such a slot; this resolver only reports it."""
    from bodywork_tpu.registry.records import read_aliases, resolve_canary

    doc = read_aliases(store)  # RegistryCorrupt propagates, as resolve_alias
    if doc is None or not doc.get("production"):
        key, source = resolve_serving_key(store)
        return key, source, None, None
    canary_state, dangling = resolve_canary(store, doc)
    return doc["production"], "production", canary_state, dangling


def load_model(store: ArtefactStore, key: str | None = None, device: bool = True):
    """Load a model by key; with ``key=None``, resolve the registry's
    ``production`` alias when one exists and fall back to the latest
    under ``models/`` on a registry-less store (reference
    ``stage_2:46-70``). Returns (model, artefact_date)."""
    from bodywork_tpu.utils.dates import date_from_key

    if key is None:
        key, _source = resolve_serving_key(store)
    d = date_from_key(key)
    model = load_model_bytes(store.get_bytes(key), device=device)
    log.info(f"loaded {model.info} from {key} (trained {d})")
    return model, d


from bodywork_tpu.models.linear import LinearRegressor as _Linear
from bodywork_tpu.models.mlp import MLPRegressor as _MLP

MODEL_REGISTRY = {
    _Linear.model_type: _Linear,
    _MLP.model_type: _MLP,
}
