"""Pack/unpack helpers for single-transfer fused train programs.

On a remote-attached TPU every device->host fetch pays the tunnel
round-trip (~70-200 ms measured), so a train stage that fetches three
metric scalars and each param leaf separately spends ~0.4 s/day on
transfers alone. The fused fit+eval programs (``linear._ols_fit_eval``,
``mlp._mlp_fit_eval``) instead return the params pytree (kept on device
for serving) *plus* one flat ``float32`` vector holding every param leaf
ravelled followed by the metrics — so the whole train stage costs exactly
ONE device->host transfer.

The reference has no analogue (sklearn is host-resident, transfers are
free — ``stage_1_train_model.py:105-107``); this is remote-accelerator
design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_tree_with_tail(params, tail_scalars) -> jax.Array:
    """Concatenate every leaf of ``params`` (ravelled, f32) and the given
    scalars into one flat device vector. Runs inside jit."""
    leaves = jax.tree_util.tree_leaves(params)
    flat = [jnp.ravel(leaf).astype(jnp.float32) for leaf in leaves]
    tail = jnp.stack([jnp.asarray(s, jnp.float32) for s in tail_scalars])
    return jnp.concatenate(flat + [tail])


def unpack_tree_with_tail(packed_host: np.ndarray, params_like, n_tail: int):
    """Split a fetched flat vector back into (host params pytree, tail).

    ``params_like`` supplies the tree structure and leaf shapes (its device
    leaves are never transferred — only ``.shape`` is read).
    """
    leaves, treedef = jax.tree_util.tree_flatten(params_like)
    out, offset = [], 0
    for leaf in leaves:
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        arr = np.asarray(
            packed_host[offset : offset + size], dtype=np.float32
        ).reshape(leaf.shape)
        out.append(arr)
        offset += size
    tail = np.asarray(packed_host[offset : offset + n_tail], dtype=np.float32)
    return jax.tree_util.tree_unflatten(treedef, out), tail


def metrics_dict(tail: np.ndarray) -> dict[str, float]:
    """First three tail entries are always (MAPE, r_squared, max_residual)."""
    return {
        "MAPE": float(tail[0]),
        "r_squared": float(tail[1]),
        "max_residual": float(tail[2]),
    }
