"""Pack/unpack helpers for single-transfer fused train programs.

On a remote-attached TPU every device->host fetch pays the tunnel
round-trip (~70-200 ms measured), so a train stage that fetches three
metric scalars and each param leaf separately spends ~0.4 s/day on
transfers alone. The fused fit+eval programs (``linear._ols_fit_eval``,
``mlp._mlp_fit_eval``) instead return the params pytree (kept on device
for serving) *plus* one flat ``float32`` vector holding every param leaf
ravelled followed by the metrics — so the whole train stage costs exactly
ONE device->host transfer.

The reference has no analogue (sklearn is host-resident, transfers are
free — ``stage_1_train_model.py:105-107``); this is remote-accelerator
design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_tree_with_tail(params, tail_scalars) -> jax.Array:
    """Concatenate every leaf of ``params`` (ravelled, f32) and the given
    scalars into one flat device vector. Runs inside jit."""
    leaves = jax.tree_util.tree_leaves(params)
    flat = [jnp.ravel(leaf).astype(jnp.float32) for leaf in leaves]
    tail = jnp.stack([jnp.asarray(s, jnp.float32) for s in tail_scalars])
    return jnp.concatenate(flat + [tail])


def unpack_tree_with_tail(packed_host: np.ndarray, params_like, n_tail: int):
    """Split a fetched flat vector back into (host params pytree, tail).

    ``params_like`` supplies the tree structure and leaf shapes (its device
    leaves are never transferred — only ``.shape`` is read).
    """
    leaves, treedef = jax.tree_util.tree_flatten(params_like)
    out, offset = [], 0
    for leaf in leaves:
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        arr = np.asarray(
            packed_host[offset : offset + size], dtype=np.float32
        ).reshape(leaf.shape)
        out.append(arr)
        offset += size
    tail = np.asarray(packed_host[offset : offset + n_tail], dtype=np.float32)
    return jax.tree_util.tree_unflatten(treedef, out), tail


def metrics_dict(tail: np.ndarray) -> dict[str, float]:
    """First three tail entries are always (MAPE, r_squared, max_residual)."""
    return {
        "MAPE": float(tail[0]),
        "r_squared": float(tail[1]),
        "max_residual": float(tail[2]),
    }


# -- quantized inference (serve --dtype {bfloat16,int8}) ---------------------
#
# Serving the small-to-mid MLP regime is weight-HBM-bound: every forward
# re-reads the whole dense stack. bf16 halves those bytes (models.mlp
# compute_dtype); int8 quarters them, at a per-matmul relative error of
# order 1/127 on the weight operand. Quantization here is symmetric
# per-OUTPUT-CHANNEL (one f32 scale per weight column): within a column
# the quantization grid adapts to that column's own dynamic range, which
# for He-initialised dense stacks keeps the realised prediction error one
# to two orders below a per-tensor scale. Biases and the folded scaler
# stay f32 (they are O(width) bytes — nothing to win), and accumulation
# is always f32. Whether the realised quality delta is acceptable is NOT
# decided here: serve.server routes it through the shadow gate
# (registry.gates quantization check) before a quantized predictor may
# take traffic.


def quantize_int8(w) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 quantization of a 2-D weight
    matrix: returns ``(q, scale)`` with ``w ≈ q * scale[None, :]``.
    An all-zero column gets scale 1.0 (q is zero anyway)."""
    w = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w), axis=0)
    scale = np.where(absmax > 0.0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale[None, :]), -127, 127).astype(np.int8)
    return q, scale


def quantize_mlp_params_int8(params: dict) -> dict:
    """Quantize an MLP params pytree's dense weights to int8 + per-column
    f32 scales; the scaler and biases ride through untouched. The result
    is the pytree :func:`int8_mlp_apply` serves from."""
    layers = []
    for layer in params["net"]["layers"]:
        q, scale = quantize_int8(layer["w"])
        layers.append({
            "wq": q,
            "w_scale": np.asarray(scale, dtype=np.float32),
            "b": np.asarray(layer["b"], dtype=np.float32),
        })
    scaler = {
        k: np.asarray(v, dtype=np.float32)
        for k, v in params["scaler"].items()
    }
    return {"net": {"layers": layers}, "scaler": scaler}


def dequantize_mlp_params(qparams: dict) -> dict:
    """The f32 params pytree an int8 pytree represents (tests compare
    this against the original to bound the quantization error)."""
    layers = []
    for layer in qparams["net"]["layers"]:
        w = (
            np.asarray(layer["wq"], dtype=np.float32)
            * np.asarray(layer["w_scale"], dtype=np.float32)[None, :]
        )
        layers.append({"w": w, "b": np.asarray(layer["b"], dtype=np.float32)})
    return {"net": {"layers": layers}, "scaler": dict(qparams["scaler"])}


def int8_mlp_apply(qparams: dict, x: jax.Array) -> jax.Array:
    """Full MLP apply from int8 weights: raw X -> raw prediction, the
    pure ``(params, X) -> y`` shape the AOT executable cache lowers
    (serve.predictor.Int8MLPPredictor). Weights dequantize inside the
    program — XLA fuses the ``int8 -> f32 scale`` into the matmul's
    operand read, so HBM traffic is the int8 bytes."""
    s = qparams["scaler"]
    h = (x - s["x_mean"]) / s["x_std"]
    layers = qparams["net"]["layers"]
    for i, layer in enumerate(layers):
        w = layer["wq"].astype(jnp.float32) * layer["w_scale"][None, :]
        h = jnp.dot(h, w, preferred_element_type=jnp.float32) + layer["b"]
        if i < len(layers) - 1:
            h = jax.nn.relu(h)
    out = h[:, 0]
    return out * s["y_std"] + s["y_mean"]
