"""Closed-form linear regressor, jitted for TPU.

TPU-native replacement for the reference's
``sklearn.linear_model.LinearRegression(fit_intercept=True)``
(``stage_1_train_model.py:105-106``) — the only model compute in the
reference. Instead of an iterative solver, the fit is the weighted normal
equations computed as one fused XLA program:

    G = A^T diag(w) A,  c = A^T diag(w) y,  theta = solve(G, c)

with A = [X | 1]. Inputs are zero-padded to bucketed static row counts with
weight-0 padding rows, so re-training on a growing multi-day history reuses
the same compiled executable (see ``base.pad_rows``). The O(n d^2) Gram
matmul is MXU work; the O(d^3) solve is negligible (d = 2 here).
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodywork_tpu.models.base import Regressor, pad_rows
from bodywork_tpu.models.fused import (
    metrics_dict,
    pack_tree_with_tail,
    unpack_tree_with_tail,
)
from bodywork_tpu.models.metrics import _metrics


@dataclasses.dataclass
class LinearConfig:
    fit_intercept: bool = True
    #: L2 ridge term added to the Gram diagonal for numerical safety. 0 keeps
    #: exact OLS parity with the reference.
    l2: float = 0.0


def _ols_core(X: jax.Array, y: jax.Array, w: jax.Array, l2: jax.Array):
    ones = jnp.ones((X.shape[0], 1), X.dtype)
    A = jnp.concatenate([X, ones], axis=1)
    Aw = A * w[:, None]
    G = Aw.T @ A + l2 * jnp.eye(A.shape[1], dtype=A.dtype)
    c = Aw.T @ y
    theta = jnp.linalg.solve(G, c)
    return {"w": theta[:-1], "b": theta[-1]}


_ols_fit = jax.jit(_ols_core)


def _ols_no_intercept_core(X: jax.Array, y: jax.Array, w: jax.Array, l2: jax.Array):
    Xw = X * w[:, None]
    G = Xw.T @ X + l2 * jnp.eye(X.shape[1], dtype=X.dtype)
    c = Xw.T @ y
    theta = jnp.linalg.solve(G, c)
    return {"w": theta, "b": jnp.zeros((), X.dtype)}


@partial(jax.jit, static_argnames=("fit_intercept",))
def _ols_fit_eval(Xtr, ytr, wtr, Xte, yte, wte, l2, fit_intercept: bool = True):
    """Fused fit + held-out metrics; returns (device params, packed vector).

    The packed vector is [w..., b, MAPE, r2, max_residual] — the train
    stage's entire device->host traffic in one transfer (see
    :mod:`bodywork_tpu.models.fused`).
    """
    core = _ols_core if fit_intercept else _ols_no_intercept_core
    params = core(Xtr, ytr, wtr, l2)
    m = _metrics(yte, linear_apply(params, Xte), wte)
    return params, pack_tree_with_tail(params, m)


def gram_stats(X: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Float64 sufficient statistics ``(G, c)`` of one row block for the
    normal equations, over the intercept-augmented design ``A = [X | 1]``:
    ``G = AᵀA`` (d+1, d+1) and ``c = Aᵀy`` (d+1,).

    These are the additive state behind incremental training
    (:mod:`bodywork_tpu.train.incremental`): the statistics of a multi-day
    history are the SUM of each day's, so folding in one new day and
    solving :func:`solve_normal_eq` reproduces the full refit's
    coefficients exactly — O(new rows) work instead of O(history).
    Host float64 on purpose: the blocks are tiny (d = 2 here), the sum
    must be exact enough to survive hundreds of days of accumulation,
    and the serialized statistics must be bit-deterministic so chaos
    twins' ``trainstate/`` documents stay byte-identical."""
    X = np.asarray(X, dtype=np.float64)
    if X.ndim == 1:
        X = X[:, None]
    y = np.asarray(y, dtype=np.float64).ravel()
    A = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
    return A.T @ A, A.T @ y


def solve_normal_eq(
    G: np.ndarray, c: np.ndarray, config: LinearConfig | None = None
) -> dict:
    """Solve summed :func:`gram_stats` statistics into host float32
    params ``{"w", "b"}`` — the same math as ``_ols_core`` (l2 ridge on
    the full augmented diagonal, intercept as the last column), computed
    in float64 on the host. The no-intercept variant drops the augmented
    row/column, mirroring ``_ols_no_intercept_core``."""
    config = config or LinearConfig()
    G = np.asarray(G, dtype=np.float64)
    c = np.asarray(c, dtype=np.float64)
    if config.fit_intercept:
        theta = np.linalg.solve(G + config.l2 * np.eye(G.shape[0]), c)
        w, b = theta[:-1], theta[-1]
    else:
        Gs = G[:-1, :-1] + config.l2 * np.eye(G.shape[0] - 1)
        theta = np.linalg.solve(Gs, c[:-1])
        w, b = theta, 0.0
    return {
        "w": np.asarray(w, dtype=np.float32),
        "b": np.float32(b),
    }


def linear_apply(params, X: jax.Array) -> jax.Array:
    # plain (unjitted) pure function: the per-class jitted version lives in
    # base._APPLY_FNS (one compiled apply per class), and fused programs
    # inline it
    return X @ params["w"] + params["b"]


class LinearRegressor(Regressor):
    model_type = "linear"
    apply = staticmethod(linear_apply)

    def __init__(self, config: LinearConfig | None = None, params=None):
        super().__init__(config or LinearConfig(), params)

    def fit(
        self, X: np.ndarray, y: np.ndarray, seed: int | None = None
    ) -> "LinearRegressor":
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(y, dtype=np.float32).ravel()
        Xp, yp, w = pad_rows(X, y)
        if not self.config.fit_intercept:
            # Weight-0 on the intercept column via a huge ridge on it would be
            # hacky; instead solve without the ones column.
            params = _ols_fit_no_intercept(Xp, yp, w, jnp.float32(self.config.l2))
        else:
            params = _ols_fit(Xp, yp, w, jnp.float32(self.config.l2))
        params = jax.device_put(params)
        return LinearRegressor(self.config, params)

    def fit_and_evaluate(
        self,
        X_train: np.ndarray,
        y_train: np.ndarray,
        X_test: np.ndarray,
        y_test: np.ndarray,
        seed: int | None = None,
        materialize: bool = True,
    ) -> tuple["LinearRegressor", dict[str, float]] | tuple[None, None]:
        """Fused fit + held-out metrics: one XLA program, ONE device->host
        transfer for params and metrics together (vs fit/eval/fetch costing
        ~5 tunnel round-trips — see models/fused.py).

        ``materialize=False`` only compiles + dispatches (for bucket
        prewarming): no host fetch, no blocking, returns ``(None, None)``."""
        Xtr, ytr, wtr, Xte, yte, wte = self._pad_splits(
            X_train, y_train, X_test, y_test
        )
        params, packed = _ols_fit_eval(
            Xtr, ytr, wtr, Xte, yte, wte,
            jnp.float32(self.config.l2),
            fit_intercept=self.config.fit_intercept,
        )
        if not materialize:
            return None, None
        host_params, tail = unpack_tree_with_tail(np.asarray(packed), params, 3)
        fitted = LinearRegressor(self.config, params)
        fitted._host_params = host_params
        return fitted, metrics_dict(tail)

    @property
    def n_features(self) -> int | None:
        if self.params is None:
            return None
        # .shape only — np.asarray here would be a device->host fetch
        return int(self.params["w"].shape[0])

    @property
    def info(self) -> str:
        return "LinearRegressor(closed_form_ols)"

    @classmethod
    def from_config_dict(cls, cfg: dict, params) -> "LinearRegressor":
        return cls(LinearConfig(**cfg), params)


_ols_fit_no_intercept = jax.jit(_ols_no_intercept_core)
