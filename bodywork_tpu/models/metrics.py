"""Regression metrics in jnp (reference ``stage_1_train_model.py:79-90``).

The reference computes sklearn ``mean_absolute_percentage_error``,
``r2_score`` and ``max_error`` on the held-out split. Same definitions here,
as a single jitted fused reduction. Inputs are zero-padded to power-of-two
row buckets with a 0/1 weight mask, so computing metrics on a growing
held-out split (the daily retrain loop) reuses a logarithmic number of
compiled executables instead of recompiling every day.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bodywork_tpu.models.base import pad_rows

# sklearn's MAPE guards the denominator with float64 machine epsilon.
_MAPE_EPS = 2.220446049250313e-16


@jax.jit
def _metrics(y_true: jax.Array, y_pred: jax.Array, w: jax.Array):
    """Masked MAPE / R^2 / max-abs-residual; padding rows carry weight 0."""
    n = jnp.maximum(jnp.sum(w), 1.0)
    # Mask with where, not multiplication: a non-finite prediction on a
    # padding row would turn 0 * inf into NaN and poison every reduction.
    resid = jnp.where(w > 0, y_true - y_pred, 0.0)
    mape = (
        jnp.sum(jnp.abs(resid) / jnp.maximum(jnp.abs(y_true), _MAPE_EPS)) / n
    )
    mean_y = jnp.sum(w * y_true) / n
    ss_res = jnp.sum(resid**2)
    ss_tot = jnp.sum(w * (y_true - mean_y) ** 2)
    r_squared = 1.0 - ss_res / ss_tot
    max_residual = jnp.max(jnp.abs(resid))
    return mape, r_squared, max_residual


def make_eval_fn(apply_fn):
    """Fuse model apply + metrics into ONE jitted program.

    Evaluating a fitted model as predict-then-metrics costs two device
    dispatches; on a remote-attached TPU each dispatch pays the host
    round-trip. The fused program runs both on device and returns three
    scalars."""

    @jax.jit
    def eval_fn(params, Xp: jax.Array, yp: jax.Array, w: jax.Array):
        return _metrics(yp, apply_fn(params, Xp), w)

    return eval_fn


def regression_metrics(y_true, y_pred) -> dict[str, float]:
    """MAPE / R^2 / max-abs-residual, matching the reference's metric record
    columns (``stage_1:85-89``)."""
    y_true = np.asarray(y_true, dtype=np.float32).ravel()
    y_pred = np.asarray(y_pred, dtype=np.float32).ravel()
    yt, yp, w = pad_rows(y_true, y_pred, minimum=256)
    mape, r2, max_resid = _metrics(jnp.asarray(yt), jnp.asarray(yp), jnp.asarray(w))
    return {
        "MAPE": float(mape),
        "r_squared": float(r2),
        "max_residual": float(max_resid),
    }
