"""Regression metrics in jnp (reference ``stage_1_train_model.py:79-90``).

The reference computes sklearn ``mean_absolute_percentage_error``,
``r2_score`` and ``max_error`` on the held-out split. Same definitions here,
as a single jitted fused reduction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# sklearn's MAPE guards the denominator with float64 machine epsilon.
_MAPE_EPS = 2.220446049250313e-16


@jax.jit
def _metrics(y_true: jax.Array, y_pred: jax.Array):
    resid = y_true - y_pred
    mape = jnp.mean(jnp.abs(resid) / jnp.maximum(jnp.abs(y_true), _MAPE_EPS))
    ss_res = jnp.sum(resid**2)
    ss_tot = jnp.sum((y_true - jnp.mean(y_true)) ** 2)
    r_squared = 1.0 - ss_res / ss_tot
    max_residual = jnp.max(jnp.abs(resid))
    return mape, r_squared, max_residual


def regression_metrics(y_true, y_pred) -> dict[str, float]:
    """MAPE / R^2 / max-abs-residual, matching the reference's metric record
    columns (``stage_1:85-89``)."""
    y_true = jnp.asarray(y_true, dtype=jnp.float32).ravel()
    y_pred = jnp.asarray(y_pred, dtype=jnp.float32).ravel()
    mape, r2, max_resid = _metrics(y_true, y_pred)
    return {
        "MAPE": float(mape),
        "r_squared": float(r2),
        "max_residual": float(max_resid),
    }
