"""3-layer MLP regressor trained with Adam, fully jitted for TPU.

This is the "grown-up" model from BASELINE.json config 3 ("JAX 3-layer MLP on
v5e-1, 30-day drift loop"); the reference has no equivalent (its only model
is OLS), so the design is TPU-first with no parity constraints:

- The whole training run is ONE compiled XLA program: a ``lax.scan`` over
  optimisation steps with minibatches gathered by random index
  (with-replacement sampling). Steps and batch size are static, and the data
  array is bucket-padded (``base.pad_rows``), so day-over-day retraining on a
  growing history re-uses the same executable per bucket.
- Padding rows carry weight 0 in the loss, keeping shapes static without
  biasing the fit.
- Inputs/targets are standardised inside the params pytree (fold-in scaler),
  so serving needs no side-channel state.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bodywork_tpu.models.base import Regressor, pad_rows


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    # frozen => hashable, so the config can be a static jit argument
    hidden: tuple[int, ...] = (64, 64)
    learning_rate: float = 1e-2
    batch_size: int = 256
    n_steps: int = 2000
    seed: int = 0
    #: training matmul precision policy (a string so the config stays
    #: hashable/serialisable): None = float32 operands under XLA's default
    #: TPU precision; "bfloat16" = cast matmul operands to bf16 (params,
    #: optimizer state, and the loss stay f32 — standard mixed precision,
    #: single-pass MXU). Serving (``mlp_apply``) always runs f32.
    compute_dtype: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "hidden", tuple(self.hidden))


def init_mlp_params(key: jax.Array, sizes: tuple[int, ...]) -> dict:
    """He-initialised dense stack; sizes = (in, *hidden, out)."""
    layers = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (fan_in, fan_out)) * jnp.sqrt(2.0 / fan_in)
        layers.append({"w": w, "b": jnp.zeros((fan_out,))})
    return {"layers": layers}


def mlp_forward(
    net_params: dict, x: jax.Array, compute_dtype: str | None = None
) -> jax.Array:
    """Dense->relu stack; returns (n,) predictions in standardised space.

    ``compute_dtype="bfloat16"`` casts every matmul operand (activations,
    weights, biases) to bf16 so the MXU runs single-pass; autodiff then
    computes the backward matmuls in bf16 too, with gradients cast back to
    the params' f32 on the way out. The (n,) output is always f32."""
    layers = net_params["layers"]
    cast = (lambda a: a.astype(compute_dtype)) if compute_dtype else (lambda a: a)
    h = cast(x)
    for layer in layers[:-1]:
        h = jax.nn.relu(h @ cast(layer["w"]) + cast(layer["b"]))
    out = h @ cast(layers[-1]["w"]) + cast(layers[-1]["b"])
    return out[:, 0].astype(jnp.float32)


def mlp_apply(
    params: dict, x: jax.Array, compute_dtype: str | None = None
) -> jax.Array:
    """Full apply incl. the folded-in scaler: raw X -> raw prediction.

    ``compute_dtype="bfloat16"`` runs the dense stack's matmuls in bf16
    (single-pass MXU — the opt-in ``xla-bf16`` serving engine); the scaler
    arithmetic and the output stay f32 either way."""
    s = params["scaler"]
    h = (x - s["x_mean"]) / s["x_std"]
    out = mlp_forward(params["net"], h, compute_dtype)
    return out * s["y_std"] + s["y_mean"]


def _loss(net_params, xb, yb, wb, compute_dtype: str | None = None):
    pred = mlp_forward(net_params, xb, compute_dtype)
    return jnp.sum(wb * (pred - yb) ** 2) / jnp.maximum(jnp.sum(wb), 1.0)




def _scaled_splits(Xp, yp, w):
    """Masked standardisation stats + standardised train arrays."""
    x_mean, x_std = jax.vmap(_masked_stats, in_axes=(1, None), out_axes=0)(Xp, w)
    y_mean, y_std = _masked_stats(yp, w)
    Xs = (Xp - x_mean) / x_std
    ys = (yp - y_mean) / y_std
    scaler = {"x_mean": x_mean, "x_std": x_std, "y_mean": y_mean, "y_std": y_std}
    return Xs, ys, scaler


def _train_core(net_params, X, y, w, key, cfg: MLPConfig):
    opt = optax.adam(cfg.learning_rate)
    opt_state = opt.init(net_params)

    def step(carry, _):
        params, opt_state, key = carry
        key, k_idx = jax.random.split(key)
        idx = jax.random.randint(k_idx, (cfg.batch_size,), 0, X.shape[0])
        xb, yb, wb = X[idx], y[idx], w[idx]
        loss, grads = jax.value_and_grad(_loss)(
            params, xb, yb, wb, cfg.compute_dtype
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, key), loss

    (net_params, _, _), losses = jax.lax.scan(
        step, (net_params, opt_state, key), None, length=cfg.n_steps
    )
    return net_params, losses


#: standalone jitted train loop (used by ``fit``; the fused path inlines
#: ``_train_core`` into one program instead)
_train = partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))(_train_core)


@partial(jax.jit, static_argnames=("cfg",))
def _mlp_fit_eval(Xp, yp, w, Xe, ye, we, key, cfg: MLPConfig):
    """Whole MLP train stage as ONE XLA program: scaler stats, init, the
    ``lax.scan`` optimisation loop, and held-out metrics. Returns the device
    params plus a packed [leaves..., MAPE, r2, max_resid, final_loss] vector
    so the host fetches everything in a single transfer."""
    from bodywork_tpu.models.fused import pack_tree_with_tail
    from bodywork_tpu.models.metrics import _metrics

    k_init, k_train = jax.random.split(key)
    Xs, ys, scaler = _scaled_splits(Xp, yp, w)
    sizes = (Xp.shape[1],) + cfg.hidden + (1,)
    net = init_mlp_params(k_init, sizes)
    net, losses = _train_core(net, Xs, ys, w, k_train, cfg)
    params = {"net": net, "scaler": scaler}
    m = _metrics(ye, mlp_apply(params, Xe), we)
    packed = pack_tree_with_tail(params, tuple(m) + (losses[-1],))
    return params, packed


@jax.jit
def _masked_stats(v: jax.Array, w: jax.Array):
    n = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(v * w) / n
    var = jnp.sum(w * (v - mean) ** 2) / n
    return mean, jnp.maximum(jnp.sqrt(var), 1e-6)


class MLPRegressor(Regressor):
    model_type = "mlp"
    apply = staticmethod(mlp_apply)

    def __init__(self, config: MLPConfig | None = None, params=None):
        super().__init__(config or MLPConfig(), params)

    def fit(self, X: np.ndarray, y: np.ndarray, seed: int | None = None) -> "MLPRegressor":
        cfg = self.config
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(y, dtype=np.float32).ravel()
        Xp, yp, w = pad_rows(X, y)

        key = jax.random.PRNGKey(cfg.seed if seed is None else seed)
        k_init, k_train = jax.random.split(key)

        Xp, yp, w = jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(w)
        Xs, ys, scaler = _scaled_splits(Xp, yp, w)

        sizes = (X.shape[1],) + cfg.hidden + (1,)
        net = init_mlp_params(k_init, sizes)
        net, losses = _train(net, Xs, ys, w, k_train, cfg)
        params = {"net": net, "scaler": scaler}
        fitted = MLPRegressor(cfg, jax.device_put(params))
        fitted.final_loss = float(losses[-1])
        return fitted

    def fit_and_evaluate(
        self,
        X_train: np.ndarray,
        y_train: np.ndarray,
        X_test: np.ndarray,
        y_test: np.ndarray,
        seed: int | None = None,
        materialize: bool = True,
    ) -> tuple["MLPRegressor", dict[str, float]] | tuple[None, None]:
        """Fused scaler+init+scan-train+metrics in one XLA program; host
        receives params, metrics, and the final loss in ONE transfer.

        ``materialize=False`` only compiles + dispatches (for bucket
        prewarming): no host fetch, no blocking, returns ``(None, None)``."""
        from bodywork_tpu.models.fused import metrics_dict, unpack_tree_with_tail

        cfg = self.config
        Xp, yp, w, Xe, ye, we = self._pad_splits(
            X_train, y_train, X_test, y_test
        )
        key = jax.random.PRNGKey(cfg.seed if seed is None else seed)
        params, packed = _mlp_fit_eval(Xp, yp, w, Xe, ye, we, key, cfg)
        if not materialize:
            return None, None
        host_params, tail = unpack_tree_with_tail(np.asarray(packed), params, 4)
        fitted = MLPRegressor(cfg, params)
        fitted._host_params = host_params
        fitted.final_loss = float(tail[3])
        return fitted, metrics_dict(tail)

    def fine_tune(
        self, X: np.ndarray, y: np.ndarray, n_steps: int,
        seed: int | None = None,
    ) -> "MLPRegressor":
        """Warm-started continuation: resume Adam from THIS model's
        fitted params for ``n_steps`` on (X, y) — the incremental-retrain
        path (:mod:`bodywork_tpu.train.incremental`), where the donor is
        the production checkpoint and (X, y) is a replay window instead
        of all history. The donor's folded-in scaler is KEPT (the net's
        input distribution must not shift under it mid-descent; replay
        windows are too small to re-estimate it anyway), so predictions
        stay continuous with the donor's. The optimizer state restarts
        fresh — checkpoints deliberately hold params only."""
        assert self.params is not None, "cannot fine-tune an unfitted model"
        cfg = dataclasses.replace(self.config, n_steps=n_steps)
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(y, dtype=np.float32).ravel()
        Xp, yp, w = pad_rows(X, y)
        host = self.host_params()
        s = host["scaler"]
        # standardise with the DONOR's scaler, on the host (O(rows), and
        # the zero-padding rows stay harmless: weight 0 in the loss)
        Xs = jnp.asarray((Xp - s["x_mean"]) / s["x_std"])
        ys = jnp.asarray((yp - s["y_mean"]) / s["y_std"])
        key = jax.random.PRNGKey(self.config.seed if seed is None else seed)
        # a FRESH device copy of the net: _train donates its params
        # argument, and the donor may still be serving traffic
        net = jax.device_put(jax.tree_util.tree_map(np.asarray, host["net"]))
        net, losses = _train(net, Xs, ys, jnp.asarray(w), key, cfg)
        params = {"net": net, "scaler": jax.device_put(host["scaler"])}
        # the ORIGINAL config rides the checkpoint: n_steps was a detail
        # of this continuation, not of the architecture being served
        tuned = MLPRegressor(self.config, params)
        tuned.final_loss = float(losses[-1])
        return tuned

    @property
    def n_features(self) -> int | None:
        if self.params is None:
            return None
        # .shape only — np.asarray here would be a device->host fetch
        return int(self.params["net"]["layers"][0]["w"].shape[0])

    @property
    def info(self) -> str:
        return f"MLPRegressor(hidden={list(self.config.hidden)})"

    @classmethod
    def from_config_dict(cls, cfg: dict, params) -> "MLPRegressor":
        cfg = dict(cfg)
        cfg["hidden"] = tuple(cfg.get("hidden", (64, 64)))
        return cls(MLPConfig(**cfg), params)
