from bodywork_tpu.monitor.tester import (
    HttpScoringClient,
    InProcessScoringClient,
    compute_test_metrics,
    persist_test_metrics,
    run_service_test,
    score_dataset,
    scoring_endpoint,
)
from bodywork_tpu.monitor.analytics import (
    detect_drift,
    drift_report,
    load_metric_history,
    render_drift_dashboard,
)

__all__ = [
    "HttpScoringClient",
    "InProcessScoringClient",
    "compute_test_metrics",
    "persist_test_metrics",
    "run_service_test",
    "score_dataset",
    "scoring_endpoint",
    "detect_drift",
    "drift_report",
    "load_metric_history",
    "render_drift_dashboard",
]
