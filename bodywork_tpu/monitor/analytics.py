"""Longitudinal model-performance analytics (reference C12,
``model-performance-analytics.ipynb``).

The reference notebook concatenates every CSV under ``model-metrics/`` and
``test-metrics/`` into two DataFrames (cell-4) and eyeballs per-day tables
for drift. Here that is a library function plus a joined drift report, so
dashboards and alerting can be built on it (and the CLI can print it).
"""
from __future__ import annotations

import io

import numpy as np
import pandas as pd

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import MODEL_METRICS_PREFIX, TEST_METRICS_PREFIX
from bodywork_tpu.utils.logging import get_logger

log = get_logger("monitor.analytics")


def _load_history_frame(store: ArtefactStore, prefix: str) -> pd.DataFrame:
    frames = []
    for key, _d in store.history(prefix):
        frames.append(pd.read_csv(io.BytesIO(store.get_bytes(key))))
    if not frames:
        return pd.DataFrame()
    df = pd.concat(frames, ignore_index=True)
    df["date"] = pd.to_datetime(df["date"]).dt.date
    return df.sort_values("date").reset_index(drop=True)


def load_metric_history(store: ArtefactStore) -> tuple[pd.DataFrame, pd.DataFrame]:
    """(train_metrics, test_metrics) histories, oldest first."""
    return (
        _load_history_frame(store, MODEL_METRICS_PREFIX),
        _load_history_frame(store, TEST_METRICS_PREFIX),
    )


def drift_report(store: ArtefactStore) -> pd.DataFrame:
    """Join train-time vs live-test metrics by date.

    Columns are suffixed ``_train`` / ``_live``; the gap between
    ``MAPE_train`` and ``MAPE_live`` over days is the concept-drift signal
    the whole simulation exists to surface.
    """
    train_df, test_df = load_metric_history(store)
    if train_df.empty and test_df.empty:
        return pd.DataFrame()
    if train_df.empty:
        return test_df.add_suffix("_live").rename(columns={"date_live": "date"})
    if test_df.empty:
        return train_df.add_suffix("_train").rename(columns={"date_train": "date"})
    report = pd.merge(
        train_df.add_suffix("_train").rename(columns={"date_train": "date"}),
        test_df.add_suffix("_live").rename(columns={"date_live": "date"}),
        on="date",
        how="outer",
    ).sort_values("date")
    return report.reset_index(drop=True)


def detect_drift(
    report: pd.DataFrame,
    mape_ratio: float | None = None,
    corr_floor: float = 0.5,
    window: int | None = None,
    bias_z: float = 4.0,
    bias_window: int = 7,
    bias_baseline: int = 14,
) -> dict:
    """Turn the longitudinal report into an actionable drift verdict.

    The reference stops at *surfacing* drift (an analyst eyeballs the
    joined tables — ``model-performance-analytics.ipynb`` cells 7-8);
    this adds the decision rule so the pipeline itself can react (the
    CLI's ``report --fail-on-drift`` exit code feeds a k8s CronJob or CI
    gate). Three rules, each calibrated against the generator's own
    alpha-sinusoid (``tests/test_monitor.py::test_detect_drift_calibrated
    _against_generator_sinusoid``):

    - **Bias rule (the calibrated drift detector).** A CHANGE detector
      on the live residual mean: the trailing ``bias_window``-day pooled
      ``mean_error_live`` is compared against the report's FIRST
      ``bias_baseline`` days (the deployment-time yardstick), in
      combined standard errors (per-day SE = ``error_std_live /
      sqrt(n_scored_live)``); a day is flagged when |z| exceeds
      ``bias_z``. Baseline-relative is the load-bearing choice: a
      frozen model carries a persistent estimation-error bias (~N(0,
      intercept-SE) of its own fit) that an absolute rule eventually
      flags on any threshold — calibration showed exactly that (one
      no-drift seed in five crossed even |z|>5.5 absolute). Against the
      baseline that constant cancels, leaving only what CHANGED since
      deployment. Calibration on the generator (sigma=10, ~1300
      rows/day, the reference's own +/-0.5 intercept swing = a ~1.8
      SE/day signal at its extremes): baseline 14 days, trailing week,
      z=4 gives ZERO false positives on flat-alpha controls over 5x60
      seed-days while every drift seed fires within ~10 days of the
      swing's extreme
      (``test_detect_drift_calibrated_against_generator_sinusoid``).
      The baseline days themselves cannot flag by construction. Needs
      the bias-channel columns
      (``monitor.tester.compute_test_metrics``); reports without them
      simply skip this rule.
    - ``MAPE_live > mape_ratio * MAPE_train`` — OPT-IN only
      (``mape_ratio=None`` default disables it). Calibration against
      the reference's own generator showed this statistic has an
      UNBOUNDED false-positive rate there: APE divides by the label
      (``stage_4:90``) and the ``y >= 0`` filter (``stage_3:43``)
      admits labels arbitrarily close to zero, so a single tiny label
      can make one no-drift day's mean APE 156x the train MAPE while a
      genuinely drifted day sits at 0.6x. No fixed ratio separates
      those. Set a ratio explicitly only for label distributions
      bounded away from zero. When enabled, a perfect train fit
      (``MAPE_train == 0``) with any positive live MAPE flags
      (infinite ratio).
    - ``r_squared_live < corr_floor`` — score/label correlation (the
      reference's "r_squared", ``stage_4:103``) collapsed outright.
      Needs only the live side: a collapsed service is evidence by
      itself, train history or not.

    ``window`` restricts the VERDICT to the last ``window`` days.
    Without it a gate keyed on the verdict (CronJob/CI running
    ``report --fail-on-drift``) latches permanently once any historical
    day was ever flagged, even after retraining recovers; with
    ``window=1`` the verdict is "is the service drifted *now*". The
    bias rule's trailing windows are computed over the FULL report
    before the verdict window is applied, so gating on recent days
    never weakens the accumulated evidence behind them.

    Returns ``{drifted, first_flagged_date, flagged_dates, n_days,
    thresholds}``. A day missing the inputs a rule needs is not flagged
    by that rule (no evidence is not drift).
    """
    if window is not None and int(window) < 1:
        # tail(0) would silently disable the gate (empty frame -> never
        # drifted); negative windows mean "all but the first N" in pandas.
        # Either way the caller asked for a range no reading of "last N
        # days" covers — fail loud.
        raise ValueError(f"window must be >= 1, got {window}")
    out = {
        "drifted": False,
        "first_flagged_date": None,
        "flagged_dates": [],
        "n_days": 0,
        "thresholds": {
            "mape_ratio": mape_ratio,
            "corr_floor": corr_floor,
            "window": window,
            "bias_z": bias_z,
            "bias_window": bias_window,
            "bias_baseline": bias_baseline,
        },
    }
    if report is None or report.empty:
        return out
    full = report.sort_values("date")

    # bias rule, over the full history (see docstring): trailing-window
    # pooled residual mean vs the deployment-time baseline (the first
    # bias_window days), in combined standard errors. Persistent model
    # miscalibration cancels; only change since deployment flags.
    bias_hit = pd.Series(False, index=full.index)
    needed = {"mean_error_live", "error_std_live", "n_scored_live"}
    if needed <= set(full.columns):
        se2 = (
            full["error_std_live"]
            / np.sqrt(full["n_scored_live"].clip(lower=1))
        ) ** 2
        me = full["mean_error_live"].where(
            np.isfinite(full["mean_error_live"]) & np.isfinite(se2)
        )
        se2 = se2.where(me.notna())
        valid = me.notna()
        base_idx = full.index[valid][: int(bias_baseline)]
        if len(base_idx) > 0:
            base_mean = float(me.loc[base_idx].mean())
            # SE of the baseline mean-of-day-means
            base_var = float(se2.loc[base_idx].mean()) / len(base_idx)
            cnt = valid.astype(float).rolling(
                bias_window, min_periods=1
            ).sum()
            trail_mean = me.fillna(0.0).rolling(
                bias_window, min_periods=1
            ).sum() / cnt.clip(lower=1.0)
            trail_var = (
                se2.fillna(0.0).rolling(bias_window, min_periods=1).sum()
                / cnt.clip(lower=1.0) ** 2
            )
            z = (trail_mean - base_mean) / np.sqrt(trail_var + base_var)
            bias_hit = (z.abs() > bias_z) & (cnt > 0) & valid
            # the baseline days are the yardstick, not evidence
            bias_hit.loc[base_idx] = False

    evaluated = full.tail(int(window)) if window is not None else full
    out["n_days"] = len(evaluated)
    flagged = []
    for idx, row in evaluated.iterrows():
        mape_t = row.get("MAPE_train")
        mape_l = row.get("MAPE_live")
        corr_l = row.get("r_squared_live")
        hit = bool(bias_hit.loc[idx])
        if (not hit and mape_ratio is not None
                and pd.notna(mape_t) and pd.notna(mape_l)):
            # mape_t == 0 (perfect train fit): any positive live error is
            # an infinite ratio — textbook drift, not a skipped rule
            hit = (mape_l > mape_ratio * mape_t) if mape_t > 0 else mape_l > 0
        if not hit and pd.notna(corr_l):
            hit = corr_l < corr_floor
        if hit:
            flagged.append(str(row["date"]))
    if flagged:
        out.update(
            drifted=True, first_flagged_date=flagged[0], flagged_dates=flagged
        )
    return out


# categorical slots 1-2 of the validated reference palette (adjacent-pair
# CVD dE 9.1, normal-vision dE 19.6 on the light surface — passes all gates)
_TRAIN_COLOR = "#2a78d6"  # blue: train-time metrics
_LIVE_COLOR = "#eb6834"   # orange: live-test metrics
_SURFACE = "#fcfcfb"
_INK = "#0b0b0b"
_INK_2 = "#52514e"
_GRID = "#e4e3df"


def render_drift_dashboard(store: ArtefactStore, out_path, report=None) -> "Path":
    """Render the longitudinal drift dashboard to a PNG (reference C12's
    visual half: ``model-performance-analytics.ipynb`` cells 7-8 eyeball
    per-day train-vs-live tables; here they are drawn).

    Three stacked panels over simulated days — same x-axis, one y-scale
    each (two measures never share an axis):

    1. MAPE, train vs live — the gap is the concept-drift signal.
    2. train R^2 vs live score/label correlation (the reference labels the
       live one ``r_squared`` — ``stage_4:103``).
    3. mean scoring-service response time (ms) — the latency channel
       (``stage_4:105``).

    ``report`` short-circuits the store read for callers that just computed
    :func:`drift_report` themselves (the CLI prints it before plotting —
    re-deriving it would double every per-day metric fetch against a
    remote store).

    Requires matplotlib (optional dependency); raises RuntimeError with a
    clear message when unavailable.
    """
    from pathlib import Path

    try:
        import matplotlib

        matplotlib.use("Agg")  # headless: never require a display
        import matplotlib.pyplot as plt
    except ImportError as exc:  # pragma: no cover - env without matplotlib
        raise RuntimeError(
            "rendering the drift dashboard requires matplotlib "
            "(pip install matplotlib)"
        ) from exc

    if report is None:
        report = drift_report(store)
    if report.empty:
        raise ValueError("no metric history to plot (run some days first)")

    days = pd.to_datetime(report["date"])
    fig, axes = plt.subplots(
        3, 1, figsize=(9, 9), sharex=True, facecolor=_SURFACE
    )

    def _style(ax, title, ylabel):
        ax.set_facecolor(_SURFACE)
        ax.set_title(title, color=_INK, fontsize=11, loc="left", pad=8)
        ax.set_ylabel(ylabel, color=_INK_2, fontsize=9)
        ax.grid(True, color=_GRID, linewidth=0.8)
        ax.tick_params(colors=_INK_2, labelsize=8)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        for side in ("left", "bottom"):
            ax.spines[side].set_color(_GRID)

    line_kw = dict(linewidth=2, marker="o", markersize=5, clip_on=False)

    def _series(ax, col, color, label):
        if col in report and report[col].notna().any():
            ax.plot(days, report[col], color=color, label=label, **line_kw)

    _series(axes[0], "MAPE_train", _TRAIN_COLOR, "train (held-out)")
    _series(axes[0], "MAPE_live", _LIVE_COLOR, "live service")
    _style(axes[0], "MAPE per simulated day — the drift gap", "MAPE")

    _series(axes[1], "r_squared_train", _TRAIN_COLOR, "train R²")
    _series(axes[1], "r_squared_live", _LIVE_COLOR, "live score/label corr")
    _style(axes[1], "Fit quality per day", "R² / corr")

    if (
        "mean_response_time_live" in report
        and report["mean_response_time_live"].notna().any()
    ):
        axes[2].plot(
            days,
            report["mean_response_time_live"] * 1000.0,
            color=_TRAIN_COLOR,
            **line_kw,
        )
    _style(axes[2], "Mean scoring-service response time", "ms")

    for ax in axes[:2]:
        if ax.has_data():
            legend = ax.legend(
                loc="best", fontsize=8, frameon=False, labelcolor=_INK
            )
            for line in legend.get_lines():
                line.set_linewidth(2)
    axes[2].tick_params(axis="x", rotation=30)
    fig.align_ylabels(axes)
    fig.tight_layout()

    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(out, dpi=144, facecolor=_SURFACE, bbox_inches="tight")
    plt.close(fig)
    log.info(f"drift dashboard rendered to {out}")
    return out
