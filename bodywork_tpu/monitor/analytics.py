"""Longitudinal model-performance analytics (reference C12,
``model-performance-analytics.ipynb``).

The reference notebook concatenates every CSV under ``model-metrics/`` and
``test-metrics/`` into two DataFrames (cell-4) and eyeballs per-day tables
for drift. Here that is a library function plus a joined drift report, so
dashboards and alerting can be built on it (and the CLI can print it).
"""
from __future__ import annotations

import io

import pandas as pd

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import MODEL_METRICS_PREFIX, TEST_METRICS_PREFIX
from bodywork_tpu.utils.logging import get_logger

log = get_logger("monitor.analytics")


def _load_history_frame(store: ArtefactStore, prefix: str) -> pd.DataFrame:
    frames = []
    for key, _d in store.history(prefix):
        frames.append(pd.read_csv(io.BytesIO(store.get_bytes(key))))
    if not frames:
        return pd.DataFrame()
    df = pd.concat(frames, ignore_index=True)
    df["date"] = pd.to_datetime(df["date"]).dt.date
    return df.sort_values("date").reset_index(drop=True)


def load_metric_history(store: ArtefactStore) -> tuple[pd.DataFrame, pd.DataFrame]:
    """(train_metrics, test_metrics) histories, oldest first."""
    return (
        _load_history_frame(store, MODEL_METRICS_PREFIX),
        _load_history_frame(store, TEST_METRICS_PREFIX),
    )


def drift_report(store: ArtefactStore) -> pd.DataFrame:
    """Join train-time vs live-test metrics by date.

    Columns are suffixed ``_train`` / ``_live``; the gap between
    ``MAPE_train`` and ``MAPE_live`` over days is the concept-drift signal
    the whole simulation exists to surface.
    """
    train_df, test_df = load_metric_history(store)
    if train_df.empty and test_df.empty:
        return pd.DataFrame()
    if train_df.empty:
        return test_df.add_suffix("_live").rename(columns={"date_live": "date"})
    if test_df.empty:
        return train_df.add_suffix("_train").rename(columns={"date_train": "date"})
    report = pd.merge(
        train_df.add_suffix("_train").rename(columns={"date_train": "date"}),
        test_df.add_suffix("_live").rename(columns={"date_live": "date"}),
        on="date",
        how="outer",
    ).sort_values("date")
    return report.reset_index(drop=True)
