"""Live-service tester / drift monitor (reference C5,
``stage_4_test_model_scoring_service.py``).

Black-box tests the deployed scoring service over its HTTP contract with the
latest day's labeled data, computes drift metrics, and persists them as
date-keyed artefacts — "testing in production" as a pipeline stage.

Reference parity, with its known bugs fixed idiomatically (SURVEY.md §2):

- Failed scores are NOT recorded as ``-1`` and averaged into metrics
  (``stage_4:82,85``); failures are counted separately and excluded.
- The APE denominator is guarded against label ~ 0 (the reference divides by
  raw label — ``stage_4:90``).
- The connection-error handler actually logs the exception (the reference
  references an unbound name and would NameError — ``stage_4:84``).

Both scoring clients retry 5xx/429 RESPONSE statuses — not just
connection-level failures, which is all the reference's
``HTTPAdapter(max_retries=3)`` ever covered — through the shared retry
policy (:mod:`bodywork_tpu.utils.retry`: full-jitter backoff, deadline
budget), honouring a numeric ``Retry-After`` header as a floor under the
backoff sleep. Retries are reported as
``bodywork_tpu_scoring_client_retries_total{reason=status|connection}``.

Metric definitions preserved exactly (``stage_4:101-113``): MAPE = mean APE,
``r_squared`` = Pearson correlation of score vs label (the reference's —
arguably mislabeled — definition), ``max_residual`` = max APE, plus
``mean_response_time`` of the HTTP round-trip.

TPU-native addition: ``mode="batch"`` drives ``/score/v1/batch`` so the whole
day's test set is scored in a handful of padded device calls instead of ~1.4k
serial single-row HTTP requests (the reference's hot loop, ``stage_4:97``).
"""
from __future__ import annotations

import io
from datetime import date
from time import perf_counter

import numpy as np
import pandas as pd

from bodywork_tpu.data.io import Dataset, load_latest_dataset
from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import test_metrics_key
from bodywork_tpu.utils.logging import get_logger

log = get_logger("monitor.tester")

_APE_EPS = 2.220446049250313e-16

#: response statuses worth retrying: rate limiting and transient server
#: failures (a 4xx other than 429 is a deterministic client error)
RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


class _RetryableStatus(Exception):
    """Internal: a retryable HTTP response status, raised inside the
    retry thunk so ``call_with_retry``'s transient machinery (and its
    ``retry_after_s`` floor) applies to response statuses exactly as it
    does to connection errors. Named ``TransientError``-compatible via
    the taxonomy below rather than subclassing requests' classes."""

    def __init__(self, status_code: int, retry_after_s: float | None):
        super().__init__(f"retryable scoring response: HTTP {status_code}")
        self.status_code = status_code
        self.retry_after_s = retry_after_s


def _retry_after_seconds(headers) -> float | None:
    """A numeric ``Retry-After`` header value, if present (HTTP-date
    forms are ignored — the backoff still applies without the floor)."""
    raw = headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return None


def _record_client_retry(exc, attempt, sleep_s) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_scoring_client_retries_total",
        "Scoring-client request retries by reason",
    ).inc(reason="status" if isinstance(exc, _RetryableStatus) else "connection")


def _is_retryable_scoring_failure(exc: BaseException) -> bool:
    from bodywork_tpu.utils.retry import is_transient

    return isinstance(exc, _RetryableStatus) or is_transient(exc)


def _post_with_retries(post, policy):
    """The ONE retry path both scoring clients share: run ``post()`` (a
    thunk returning an HTTP-shaped response with ``status_code`` and
    ``headers``) under ``policy``, retrying retryable response statuses
    (converted to :class:`_RetryableStatus` so the ``Retry-After`` floor
    applies) and transient transport errors, reporting each retry to the
    registry. Returns the final non-retryable response; raises
    :class:`_RetryableStatus` when the budget is spent on statuses (a
    transport error past the budget propagates as itself)."""
    from bodywork_tpu.utils.retry import call_with_retry

    def attempt():
        response = post()
        if response.status_code in RETRYABLE_STATUSES:
            raise _RetryableStatus(
                response.status_code, _retry_after_seconds(response.headers)
            )
        return response

    return call_with_retry(
        attempt,
        policy,
        is_retryable=_is_retryable_scoring_failure,
        on_retry=_record_client_retry,
    )


def scoring_endpoint(base_url: str, mode: str = "single") -> str:
    """Normalise a scoring-service URL to the endpoint for ``mode``.

    Accepts a bare base (``http://svc:5000``) or a URL already carrying the
    scoring path (``http://svc:5000/score/v1[/batch]``) — the k8s manifests
    pass the latter — and returns the correct endpoint either way.
    """
    url = base_url.rstrip("/")
    for suffix in ("/score/v1/batch", "/score/v1"):
        if url.endswith(suffix):
            url = url[: -len(suffix)]
            break
    return url + ("/score/v1/batch" if mode == "batch" else "/score/v1")


class HttpScoringClient:
    """Scores over real HTTP with per-request retries covering BOTH
    connection-level failures and retryable response statuses
    (the reference's ``HTTPAdapter(max_retries=3)`` — ``stage_4:68-85`` —
    only ever saw the former; a 503 *response* sailed straight through).
    Retries follow the shared policy: full-jitter backoff floored by a
    numeric ``Retry-After``, bounded by attempts and a deadline budget."""

    def __init__(
        self,
        url: str,
        max_retries: int = 3,
        timeout_s: float = 10.0,
        backoff_s: float = 0.05,
    ):
        import requests

        from bodywork_tpu.utils.retry import RetryPolicy

        self.url = url
        self.timeout_s = timeout_s
        self._policy = RetryPolicy(
            attempts=1 + max_retries,
            base_delay_s=backoff_s,
            max_delay_s=1.0,
            deadline_s=30.0,
        )
        self._session = requests.Session()
        # adapter retries OFF: the shared policy owns ALL retrying, so
        # connection and status retries share one budget instead of
        # multiplying (adapter x client loop)
        self._session.mount(url, requests.adapters.HTTPAdapter(max_retries=0))

    def score(self, payload: dict) -> tuple[bool, list[float], float]:
        """POST a payload; returns (ok, predictions, seconds). The
        elapsed time covers retries — a retried request really did take
        that long to answer."""
        import requests

        start = perf_counter()
        try:
            response = _post_with_retries(
                lambda: self._session.post(
                    self.url, json=payload, timeout=self.timeout_s
                ),
                self._policy,
            )
        except _RetryableStatus as exc:
            log.error(
                f"scoring request failed after retries: "
                f"HTTP {exc.status_code}"
            )
            return False, [], perf_counter() - start
        except (requests.ConnectionError, requests.Timeout) as exc:
            log.error(f"scoring request failed: {exc!r}")
            return False, [], perf_counter() - start
        elapsed = perf_counter() - start
        if response.ok:
            body = response.json()
            preds = (
                body["predictions"] if "predictions" in body else [body["prediction"]]
            )
            return True, [float(p) for p in preds], elapsed
        log.error(f"scoring request failed: HTTP {response.status_code}")
        return False, [], elapsed


class InProcessScoringClient:
    """Scores through a Flask test client — lets integration tests and the
    local runner exercise the exact HTTP contract without sockets. Same
    status-retry semantics as :class:`HttpScoringClient` (a tighter
    backoff: there is no network to be polite to), so the in-process
    daily loop survives a flaky or momentarily model-less service too."""

    def __init__(self, app, path: str = "/score/v1"):
        from bodywork_tpu.utils.retry import RetryPolicy

        self._client = app.test_client()
        self.path = path
        self._policy = RetryPolicy(
            attempts=4, base_delay_s=0.005, max_delay_s=0.05, deadline_s=5.0
        )

    def score(self, payload: dict) -> tuple[bool, list[float], float]:
        start = perf_counter()
        try:
            response = _post_with_retries(
                lambda: self._client.post(self.path, json=payload),
                self._policy,
            )
        except _RetryableStatus as exc:
            log.error(
                f"scoring request failed after retries: "
                f"HTTP {exc.status_code}"
            )
            return False, [], perf_counter() - start
        elapsed = perf_counter() - start
        if response.status_code == 200:
            body = response.get_json()
            preds = (
                body["predictions"] if "predictions" in body else [body["prediction"]]
            )
            return True, [float(p) for p in preds], elapsed
        log.error(f"scoring request failed: HTTP {response.status_code}")
        return False, [], elapsed

    def batch_sibling(self) -> "InProcessScoringClient":
        clone = InProcessScoringClient.__new__(InProcessScoringClient)
        clone._client = self._client
        clone.path = "/score/v1/batch"
        clone._policy = self._policy
        return clone


def _ape(score: float, label: float) -> float:
    return abs(score - label) / max(abs(label), _APE_EPS)


def score_dataset(
    client, ds: Dataset, mode: str = "single", batch_size: int = 512
) -> pd.DataFrame:
    """Score every labeled row via the live service.

    Returns a results frame with the reference's columns
    ``score,label,APE,response_time`` (``stage_4:98``) plus ``ok``.
    """
    rows = []
    multi = ds.X.shape[1] > 1

    def _payload_row(i: int):
        # scalar for 1-feature parity with the reference payloads
        # (``stage_4:91``); a full row list for multi-feature models (the
        # endpoint's np.array(ndmin=2) coerces it to one (1, d) instance)
        if multi:
            return [float(v) for v in ds.X[i]]
        return float(ds.X[i, 0])

    if mode == "single":
        for i, label in enumerate(ds.y):
            ok, preds, elapsed = client.score({"X": _payload_row(i)})
            score = preds[0] if ok else np.nan
            ape = _ape(score, float(label)) if ok else np.nan
            rows.append((score, float(label), ape, elapsed, ok))
    elif mode == "batch":
        for i in range(0, len(ds.y), batch_size):
            yb = ds.y[i : i + batch_size]
            if multi:
                xb_payload = [
                    [float(v) for v in row] for row in ds.X[i : i + batch_size]
                ]
            else:
                xb_payload = [float(v) for v in ds.X[i : i + batch_size, 0]]
            xb = ds.X[i : i + batch_size]
            ok, preds, elapsed = client.score({"X": xb_payload})
            per_row_time = elapsed / max(len(xb), 1)
            if ok and len(preds) == len(xb):
                for p, label in zip(preds, yb):
                    rows.append((p, float(label), _ape(p, float(label)), per_row_time, True))
            else:
                rows.extend(
                    (np.nan, float(label), np.nan, per_row_time, False) for label in yb
                )
    else:
        raise ValueError(f"unknown scoring mode: {mode!r}")
    return pd.DataFrame(rows, columns=["score", "label", "APE", "response_time", "ok"])


def compute_test_metrics(results: pd.DataFrame, results_date: date) -> pd.DataFrame:
    """One-row metrics record; columns extend the reference schema
    (``stage_4:101-113``) with an explicit ``n_failures`` count and a
    BIAS CHANNEL (``mean_error``, ``error_std``, ``n_scored``).

    Why the bias channel: calibrating the drift verdict against the
    generator's own sinusoid (``tests/test_monitor.py``) showed the
    reference's MAPE cannot see the reference's drift — mean APE divides
    by the label (``stage_4:90``), so a handful of near-zero labels
    dominate the day's mean and the statistic is day-to-day tail noise
    (flat-alpha control days exceed 8x their train-time MAPE with no
    drift at all), while the +/-0.5 intercept swing moves it by well
    under its own noise floor. The signed residual mean has none of
    that: per-day SE = error_std/sqrt(n_scored) ~ 0.28 at the
    generator's sigma=10, n~1300, so the 0.5-amplitude swing is a ~1.8
    SE/day signal a windowed rule accumulates reliably
    (``analytics.detect_drift``'s bias rule)."""
    ok = results[results["ok"]]
    n_failures = int((~results["ok"]).sum())
    if len(ok) == 0:
        mape = r_squared = max_residual = float("nan")
        mean_error = error_std = float("nan")
    else:
        mape = float(ok["APE"].mean())
        r_squared = float(ok["score"].corr(ok["label"]))
        max_residual = float(ok["APE"].max())
        err = ok["score"] - ok["label"]
        mean_error = float(err.mean())
        error_std = float(err.std(ddof=1)) if len(ok) > 1 else float("nan")
    mean_response_time = float(results["response_time"].mean())
    return pd.DataFrame(
        {
            "date": [results_date],
            "MAPE": [mape],
            "r_squared": [r_squared],
            "max_residual": [max_residual],
            "mean_response_time": [mean_response_time],
            "n_failures": [n_failures],
            "mean_error": [mean_error],
            "error_std": [error_std],
            "n_scored": [len(ok)],
        }
    )


def persist_test_metrics(
    store: ArtefactStore, metrics: pd.DataFrame, results_date: date
) -> str:
    """Write ``test-metrics/regressor-test-results-<date>.csv``
    (``stage_4:116-134``)."""
    key = test_metrics_key(results_date)
    buf = io.StringIO()
    metrics.to_csv(buf, header=True, index=False)
    store.put_text(key, buf.getvalue())
    log.info(f"persisted test metrics to {key}")
    return key


def _record_live_metrics(rec) -> None:
    """Export the day's live-test drift channel through the shared obs
    registry (:mod:`bodywork_tpu.obs`): the same numbers persisted to the
    date-keyed CSV become scrapeable gauges/counters, so an alerting
    stack can watch drift without polling the artefact store."""
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_live_test_runs_total", "Completed live-service tests"
    ).inc()
    reg.counter(
        "bodywork_tpu_live_test_rows_total",
        "Rows successfully scored by live-service tests",
    ).inc(float(rec.n_scored))
    reg.counter(
        "bodywork_tpu_live_test_failures_total",
        "Rows whose live scoring request failed",
    ).inc(float(rec.n_failures))
    gauges = (
        ("bodywork_tpu_live_mape_ratio",
         "Live MAPE of the latest service test", rec.MAPE),
        ("bodywork_tpu_live_score_label_corr_ratio",
         "Live score/label correlation of the latest service test",
         rec.r_squared),
        ("bodywork_tpu_live_response_mean_seconds",
         "Mean scoring-request round-trip of the latest service test",
         rec.mean_response_time),
    )
    for name, help_, value in gauges:
        if pd.notna(value):  # an all-failures day has no quality signal
            reg.gauge(name, help_).set(float(value))


def run_service_test(
    store: ArtefactStore,
    client,
    mode: str = "single",
    max_rows: int | None = None,
    batch_size: int = 512,
) -> pd.DataFrame:
    """Full stage-4 flow: latest dataset -> score via live service ->
    metrics -> persist. Returns the metrics record.

    ``max_rows`` caps the number of scored rows (head of the day's data) for
    cheap smoke tests; None (default) scores the full day as the reference
    does.
    """
    ds = load_latest_dataset(store)
    if max_rows is not None and len(ds) > max_rows:
        ds = Dataset(ds.X[:max_rows], ds.y[:max_rows], ds.date)
    if mode == "batch" and isinstance(client, InProcessScoringClient):
        client = client.batch_sibling()
    results = score_dataset(client, ds, mode=mode, batch_size=batch_size)
    metrics = compute_test_metrics(results, ds.date)
    persist_test_metrics(store, metrics, ds.date)
    rec = metrics.iloc[0]
    _record_live_metrics(rec)
    log.info(
        f"live test on {len(results)} rows ({ds.date}): MAPE={rec.MAPE:.4f} "
        f"corr={rec.r_squared:.4f} maxAPE={rec.max_residual:.2f} "
        f"mean_rt={rec.mean_response_time * 1000:.2f}ms failures={rec.n_failures}"
    )
    return metrics
