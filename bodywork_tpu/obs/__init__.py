"""Unified observability subsystem: metrics registry + stage spans.

The reference pipeline's only signals are print statements and an
end-of-day drift test; this package gives the running system itself a
telemetry surface (ISSUE 2):

- :mod:`~bodywork_tpu.obs.registry` — a dependency-free metrics registry
  (counters, gauges, fixed-bucket histograms) with Prometheus
  text-exposition rendering and a metric-name lint.
- :mod:`~bodywork_tpu.obs.multiproc` — snapshot files + merge so
  ``serve --workers N`` exposes ONE coherent ``/metrics`` view across
  OS-process replicas.
- :mod:`~bodywork_tpu.obs.spans` — stage spans for the pipeline runner:
  per-day structured run reports (JSON) and Chrome trace-event files
  loadable in Perfetto.
- :mod:`~bodywork_tpu.obs.tracing` — request-scoped tracing through the
  serving hot path: W3C-compatible trace ids with deterministic head
  sampling, a flight-recorder ring buffer the SLO watchdog dumps at
  every verdict, and histogram exemplars tying fat latency buckets to
  replayable traces.

Everything here is stdlib-only on purpose: the hot serving path and the
per-stage pods must be able to import it without pulling the accelerator
runtime (or anything else) into their dependency closure.
"""
from bodywork_tpu.obs.registry import (
    DEFAULT_LATENCY_BUCKETS,
    METRIC_NAME_RE,
    UNIT_SUFFIXES,
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    merge_snapshots,
    render_snapshot,
    validate_metric_name,
)
from bodywork_tpu.obs.spans import (
    Span,
    SpanRecorder,
    chrome_trace,
    day_report,
    write_chrome_trace,
    write_day_report,
)
from bodywork_tpu.obs.tracing import (
    TRACE_ID_HEADER,
    FlightRecorder,
    RequestTrace,
    Tracer,
    configure_tracing,
    configured_tracing,
    get_tracer,
)

__all__ = [
    "TRACE_ID_HEADER",
    "FlightRecorder",
    "RequestTrace",
    "Tracer",
    "configure_tracing",
    "configured_tracing",
    "get_tracer",
    "DEFAULT_LATENCY_BUCKETS",
    "METRIC_NAME_RE",
    "UNIT_SUFFIXES",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "SpanRecorder",
    "chrome_trace",
    "day_report",
    "get_registry",
    "merge_snapshots",
    "render_snapshot",
    "validate_metric_name",
    "write_chrome_trace",
    "write_day_report",
]
