"""Multiprocess metrics aggregation for ``serve --workers N``.

The multi-process service (:mod:`bodywork_tpu.serve.multiproc`) runs N
OS-process replicas behind one ``SO_REUSEPORT`` port; a ``GET /metrics``
scrape lands on ONE of them, chosen by the kernel. For the scrape to be
a coherent service-wide view, every worker periodically flushes its
registry snapshot to a shared directory (atomic tmp+rename, one file per
pid), and whichever worker answers the scrape merges its own LIVE
registry with its siblings' latest flushed snapshots.

Properties of this scheme (the same trade prometheus_client's
multiprocess mode makes, minus the mmap machinery):

- the answering worker's own numbers are exact (live registry, not its
  file — its own file is excluded from the merge to avoid double
  counting);
- sibling numbers lag by at most one flush interval, and a scrape loop
  converges as flushes land (counters only grow);
- a worker that died keeps contributing its last flushed snapshot's
  MONOTONIC totals (counters, histograms) — its already-served requests
  must not vanish from service totals, exactly as a restarted pod's
  Prometheus counters persist in recording rules — but its GAUGES are
  aged out: a point-in-time reading of a process that no longer exists
  is a lie (a crashed replica's last queue-depth would otherwise read
  high forever after the supervisor respawns it). Liveness is a
  zero-signal ``kill(pid, 0)`` probe against the snapshot's recorded
  pid — same-host by construction, since the snapshot dir is the
  serving process's own — cross-checked against the recorded
  ``/proc/<pid>/stat`` start time so a RECYCLED pid can never
  resurrect a dead worker's gauges.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from pathlib import Path

from bodywork_tpu.obs.registry import (
    Registry,
    merge_snapshots,
    render_snapshot,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("obs.multiproc")

__all__ = [
    "SNAPSHOT_PREFIX",
    "DEFAULT_FLUSH_INTERVAL_S",
    "MetricsFlusher",
    "read_sibling_snapshots",
    "aggregated_snapshot",
    "aggregated_render",
]

SNAPSHOT_PREFIX = "obs-metrics-"
DEFAULT_FLUSH_INTERVAL_S = 0.25


def _snapshot_path(directory: str | Path, pid: int) -> Path:
    return Path(directory) / f"{SNAPSHOT_PREFIX}{pid}.json"


def _pid_start(pid: int) -> int | None:
    """The kernel's process start time (jiffies since boot, field 22 of
    ``/proc/<pid>/stat``) — what makes the liveness probe PID-REUSE
    proof: a recycled pid carries a different start time, so a dead
    worker's gauges can never be resurrected by an unrelated process
    inheriting its pid. None off-procfs (the probe then degrades to the
    existence check alone)."""
    try:
        stat = Path(f"/proc/{pid}/stat").read_bytes()
        # split after the comm field (parenthesised, may embed spaces):
        # the remaining fields start at field 3, so starttime (22) is
        # the 20th of them
        return int(stat.rsplit(b")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


def write_snapshot(registry: Registry, directory: str | Path,
                   pid: int | None = None) -> Path:
    """Atomically persist one process's snapshot (tmp file + rename, so a
    concurrent reader never sees a torn write)."""
    pid = os.getpid() if pid is None else pid
    directory = Path(directory)
    # deliberately NO mkdir: the service owner creates the directory and
    # may delete it at teardown — a worker's final flush racing that
    # deletion must fail (caught by the flusher) rather than resurrect
    # the directory and leak it
    payload = json.dumps({
        "pid": pid,
        "pid_start": _pid_start(pid),
        "snapshot": registry.snapshot(),
    })
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".obs-tmp-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
        final = _snapshot_path(directory, pid)
        os.replace(tmp, final)
        return final
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _pid_alive(pid: int) -> bool:
    """Zero-signal liveness probe. PermissionError means the pid exists
    under another uid — alive; only ProcessLookupError means gone."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def _age_out_dead(payload: dict) -> dict:
    """A DEAD worker's snapshot contributes its monotonic totals only:
    counters and histograms persist (served requests must not vanish
    from service totals), gauges are dropped (a dead process's
    point-in-time readings — queue depth, watchdog state — would
    otherwise poison the merged view forever; the module docstring's
    stale-worker rule). Dead = the pid is gone OR its recorded start
    time no longer matches (pid recycled to an unrelated process)."""
    pid = payload.get("pid")
    snap = payload["snapshot"]
    if isinstance(pid, int):
        alive = _pid_alive(pid)
        if alive:
            recorded = payload.get("pid_start")
            current = _pid_start(pid)
            if (
                recorded is not None
                and current is not None
                and recorded != current
            ):
                alive = False  # pid reused by a different process
        if not alive:
            return {
                name: entry for name, entry in snap.items()
                if entry.get("type") != "gauge"
            }
    return snap


def read_sibling_snapshots(
    directory: str | Path, exclude_pid: int | None = None
) -> list[dict]:
    """Every flushed snapshot in ``directory`` except ``exclude_pid``'s
    own file, with dead workers' gauges aged out (:func:`_age_out_dead`).
    Unreadable/torn files are skipped (a worker mid-first-flush must not
    fail the whole scrape)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    snaps = []
    for path in sorted(directory.glob(f"{SNAPSHOT_PREFIX}*.json")):
        if exclude_pid is not None and path.name == (
            f"{SNAPSHOT_PREFIX}{exclude_pid}.json"
        ):
            continue
        try:
            payload = json.loads(path.read_text())
            snaps.append(_age_out_dead(payload))
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return snaps


def aggregated_snapshot(
    registry: Registry, directory: str | Path | None
) -> dict:
    """This process's LIVE snapshot merged with its siblings' flushed
    ones — the service-wide view a ``/metrics`` scrape should return.
    With no directory (single-process serving) it is just the registry."""
    own = registry.snapshot()
    if directory is None:
        return own
    siblings = read_sibling_snapshots(directory, exclude_pid=os.getpid())
    if not siblings:
        return own
    return merge_snapshots([own, *siblings])


def aggregated_render(registry: Registry, directory: str | Path | None) -> str:
    return render_snapshot(aggregated_snapshot(registry, directory))


class MetricsFlusher:
    """Background thread flushing this process's registry snapshot to the
    shared directory every ``interval_s`` (plus once on ``stop``, so a
    cleanly-exiting worker's final counts always land)."""

    def __init__(
        self,
        registry: Registry,
        directory: str | Path,
        interval_s: float = DEFAULT_FLUSH_INTERVAL_S,
    ):
        self.registry = registry
        self.directory = Path(directory)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="obs-metrics-flusher", daemon=True
        )

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()
        self.flush()  # final flush on stop

    def flush(self) -> None:
        try:
            write_snapshot(self.registry, self.directory)
        except OSError as exc:  # never take the serving path down
            log.warning(f"metrics snapshot flush failed: {exc!r}")

    def start(self) -> "MetricsFlusher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=5)
