"""Dependency-free metrics registry (counters, gauges, histograms).

Design constraints that shaped this module:

- **Stdlib only.** The scoring hot path and every per-stage pod import
  it; it must not widen any stage's pinned dependency closure
  (``pipeline/spec.py STAGE_REQUIREMENTS``).
- **Snapshot-centric.** A metric's state serialises to a plain dict
  (``Registry.snapshot``) and ALL rendering goes through snapshots
  (``render_snapshot``), so the multiprocess aggregation path
  (:mod:`~bodywork_tpu.obs.multiproc`) merges worker snapshots and
  renders them through exactly the code path a single process uses —
  one exposition format, not two.
- **Name lint at registration.** Every metric name must match
  ``bodywork_tpu_[a-z0-9_]+`` AND end in a recognised unit suffix
  (:data:`UNIT_SUFFIXES`); counters must end ``_total``. A telemetry
  namespace degrades one unlintable name at a time — rejecting at
  registration is the only point where the author is still present.
- **Fixed-bucket histograms.** Cumulative bucket counts merge across
  processes by element-wise addition, which is what makes the
  multi-worker ``/metrics`` view coherent; adaptive buckets would not.

Thread safety: one lock per metric guards its label children; values are
plain floats mutated under that lock (the GIL alone is not enough for
read-modify-write ``+=``).
"""
from __future__ import annotations

import re
import threading

__all__ = [
    "METRIC_NAME_RE",
    "UNIT_SUFFIXES",
    "DEFAULT_LATENCY_BUCKETS",
    "validate_metric_name",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "merge_snapshots",
    "render_snapshot",
]

#: the framework's metric namespace: lowercase snake_case under one prefix
METRIC_NAME_RE = re.compile(r"^bodywork_tpu_[a-z0-9_]+$")

#: recognised unit suffixes (Prometheus naming conventions, plus the
#: domain units this framework measures). ``_total`` is reserved for
#: counters; ``_loss`` is the (unitless) training-loss channel;
#: ``_state`` is a small-integer state-machine gauge (breaker
#: closed/half-open/open, serve healthy/degraded/no-model — the value
#: encoding lives with each metric in docs/RESILIENCE.md); ``_depth``
#: is a queue-occupancy gauge (requests currently held — the admission
#: layer's saturation signal, docs/OBSERVABILITY.md); ``_in_flight`` is
#: an outstanding-work gauge counted in requests (the socket
#: row-queue's consumed transport credits).
UNIT_SUFFIXES = (
    "_total",
    "_seconds",
    "_bytes",
    "_rows",
    "_requests",
    "_ratio",
    "_count",
    "_info",
    "_loss",
    "_state",
    "_depth",
    "_in_flight",
)

#: default histogram buckets, tuned for this service's latency regime:
#: sub-ms device dispatches up through multi-second stage times
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def validate_metric_name(name: str, metric_type: str) -> None:
    """The registration-time metric-name lint. Raises ``ValueError`` for
    names outside the ``bodywork_tpu_`` namespace, names without a
    recognised unit suffix, counters not ending ``_total``, and
    non-counters ending ``_total``."""
    if not METRIC_NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must match {METRIC_NAME_RE.pattern}"
        )
    if not name.endswith(UNIT_SUFFIXES):
        raise ValueError(
            f"metric name {name!r} must end in a unit suffix "
            f"{UNIT_SUFFIXES}"
        )
    if metric_type == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter {name!r} must end in '_total'")
    if metric_type != "counter" and name.endswith("_total"):
        raise ValueError(
            f"{metric_type} {name!r} must not end in '_total' "
            "(reserved for counters)"
        )


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Shared labelled-sample machinery. Subclasses define the per-label
    value struct and how to mutate it."""

    type = "untyped"

    def __init__(self, name: str, help: str = ""):
        validate_metric_name(name, self.type)
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._samples: dict[tuple, object] = {}

    def _sample(self, labels: dict):
        key = _label_key(labels)
        sample = self._samples.get(key)
        if sample is None:
            sample = self._samples[key] = self._new_sample()
        return sample

    def _peek(self, labels: dict):
        """Read-path lookup: NEVER inserts — probing a label set that was
        never observed must not add a phantom zero series to the
        exposition. Returns None when absent."""
        return self._samples.get(_label_key(labels))


class _ScalarMetric(_Metric):
    """Shared machinery for single-float-per-label-set metrics: the
    read path (peek-or-zero, never inserting) and snapshot shape live
    here ONCE so counter and gauge cannot diverge."""

    def _new_sample(self):
        return [0.0]

    def value(self, **labels) -> float:
        with self._lock:
            sample = self._peek(labels)
            return 0.0 if sample is None else sample[0]

    def snapshot_samples(self) -> list[dict]:
        with self._lock:
            return [
                {"labels": dict(k), "value": v[0]}
                for k, v in self._samples.items()
            ]


class Counter(_ScalarMetric):
    """Monotonic counter. Multiprocess merge: sum."""

    type = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._sample(labels)[0] += amount


class Gauge(_ScalarMetric):
    """Point-in-time value. ``aggregate`` declares the multiprocess merge
    semantics: "max" (default — e.g. a high-water mark), "min", "sum"
    (e.g. per-worker in-flight counts), or "mean"."""

    type = "gauge"

    def __init__(self, name: str, help: str = "", aggregate: str = "max"):
        if aggregate not in ("max", "min", "sum", "mean"):
            raise ValueError(f"unknown gauge aggregate {aggregate!r}")
        super().__init__(name, help)
        self.aggregate = aggregate

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._sample(labels)[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._sample(labels)[0] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative counts, Prometheus semantics).
    Multiprocess merge: element-wise bucket addition."""

    type = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
    ):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"buckets must be ascending, got {buckets!r}")
        super().__init__(name, help)
        self.buckets = tuple(float(b) for b in buckets)

    def _new_sample(self):
        # per-bucket NON-cumulative counts + sum + count; rendered
        # cumulatively (the snapshot keeps them additive for merging —
        # cumulative counts also merge additively, but non-cumulative is
        # harder to mis-merge). "exemplars" holds the LAST exemplar
        # (trace id + observed value) per bucket, None where never set —
        # the tie from a fat latency bucket to a replayable trace
        # (docs/OBSERVABILITY.md tracing section).
        return {
            "buckets": [0] * (len(self.buckets) + 1),  # +1: the +Inf bucket
            "sum": 0.0,
            "count": 0,
            "exemplars": [None] * (len(self.buckets) + 1),
        }

    def observe(self, value: float, exemplar: str | None = None, **labels) -> None:
        """Record one observation; ``exemplar`` (a trace id) is kept as
        the bucket's last exemplar. Exemplar-less observes leave the
        slot untouched — unsampled requests cost nothing extra here."""
        value = float(value)
        with self._lock:
            sample = self._sample(labels)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    break
            else:
                i = len(self.buckets)
            sample["buckets"][i] += 1
            if exemplar is not None:
                sample["exemplars"][i] = {
                    "trace_id": exemplar, "value": value,
                }
            sample["sum"] += value
            sample["count"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            sample = self._peek(labels)
            return 0 if sample is None else sample["count"]

    def sum(self, **labels) -> float:
        with self._lock:
            sample = self._peek(labels)
            return 0.0 if sample is None else sample["sum"]

    def snapshot_samples(self) -> list[dict]:
        with self._lock:
            out = []
            for k, v in self._samples.items():
                entry = {
                    "labels": dict(k),
                    "buckets": list(v["buckets"]),
                    "sum": v["sum"],
                    "count": v["count"],
                }
                if any(e is not None for e in v["exemplars"]):
                    entry["exemplars"] = [
                        dict(e) if e is not None else None
                        for e in v["exemplars"]
                    ]
                out.append(entry)
            return out

    def exemplars(self, **labels) -> dict[str, str]:
        """``{bucket upper bound: trace id}`` for every bucket holding an
        exemplar — the /healthz view tying fat buckets to traces."""
        with self._lock:
            sample = self._peek(labels)
            if sample is None:
                return {}
            bounds = [_fmt_value(b) for b in self.buckets] + ["+Inf"]
            return {
                bounds[i]: e["trace_id"]
                for i, e in enumerate(sample["exemplars"])
                if e is not None
            }


class Registry:
    """Process-local metric registry. ``counter``/``gauge``/``histogram``
    are idempotent get-or-create (two call sites naming the same metric
    share it; a type or bucket conflict raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.type}, not {cls.type}"
                    )
                buckets = kwargs.get("buckets")
                if buckets is not None and tuple(buckets) != existing.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"different buckets"
                    )
                aggregate = kwargs.get("aggregate")
                if aggregate is not None and aggregate != existing.aggregate:
                    # two call sites declaring different multiprocess
                    # merge semantics is a bug, not a preference
                    raise ValueError(
                        f"gauge {name!r} already registered with "
                        f"aggregate={existing.aggregate!r}, not "
                        f"{aggregate!r}"
                    )
                return existing
            if kwargs.get("aggregate", "absent") is None:
                kwargs = {**kwargs, "aggregate": "max"}  # creation default
            metric = cls(name, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help=help)

    def gauge(
        self, name: str, help: str = "", aggregate: str | None = None
    ) -> Gauge:
        """``aggregate`` None means "no opinion": creation defaults to
        "max", and re-registration accepts whatever was declared. An
        EXPLICIT mode that conflicts with the existing one raises."""
        metric = self._get_or_create(
            Gauge, name, help=help, aggregate=aggregate
        )
        return metric

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help=help, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> dict:
        """JSON-able state of every metric — the single source both the
        in-process exposition and the multiprocess merge consume."""
        with self._lock:
            metrics = list(self._metrics.values())
        snap: dict = {}
        for m in metrics:
            entry: dict = {
                "type": m.type,
                "help": m.help,
                "samples": m.snapshot_samples(),
            }
            if isinstance(m, Histogram):
                entry["buckets"] = list(m.buckets)
            if isinstance(m, Gauge):
                entry["aggregate"] = m.aggregate
            snap[m.name] = entry
        return snap

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4) of this registry."""
        return render_snapshot(self.snapshot())

    def reset(self) -> None:
        """Drop every registered metric (tests and bench isolation)."""
        with self._lock:
            self._metrics.clear()


def _fmt_value(v) -> str:
    f = float(v)
    if f != f:  # NaN: the text format's literal, never a crash mid-scrape
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    # the text format requires \\ and \n escaping on HELP lines too — an
    # unescaped newline would turn the continuation into a malformed sample
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


def render_snapshot(snapshot: dict) -> str:
    """Render a (possibly merged) snapshot to Prometheus text format."""
    lines: list[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        if entry["help"]:
            lines.append(f"# HELP {name} {_escape_help(entry['help'])}")
        lines.append(f"# TYPE {name} {entry['type']}")
        if entry["type"] == "histogram":
            bounds = entry["buckets"]
            for sample in entry["samples"]:
                cumulative = 0
                for bound, n in zip(bounds, sample["buckets"]):
                    cumulative += n
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(sample['labels'], {'le': _fmt_value(bound)})}"
                        f" {cumulative}"
                    )
                cumulative += sample["buckets"][-1]
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(sample['labels'], {'le': '+Inf'})}"
                    f" {cumulative}"
                )
                lines.append(
                    f"{name}_sum{_fmt_labels(sample['labels'])}"
                    f" {_fmt_value(sample['sum'])}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(sample['labels'])}"
                    f" {sample['count']}"
                )
                # exemplar annotations (comment lines: the 0.0.4 text
                # format has no native exemplar syntax, so they ride as
                # parser-invisible comments — docs/OBSERVABILITY.md) —
                # the last trace id observed into each bucket
                exemplars = sample.get("exemplars")
                if exemplars:
                    le_bounds = [_fmt_value(b) for b in bounds] + ["+Inf"]
                    for i, exemplar in enumerate(exemplars):
                        if exemplar is None:
                            continue
                        lines.append(
                            f"# EXEMPLAR {name}_bucket"
                            f"{_fmt_labels(sample['labels'], {'le': le_bounds[i]})}"
                            f" trace_id={exemplar['trace_id']}"
                            f" value={_fmt_value(exemplar['value'])}"
                        )
        else:
            for sample in entry["samples"]:
                lines.append(
                    f"{name}{_fmt_labels(sample['labels'])}"
                    f" {_fmt_value(sample['value'])}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def merge_snapshots(snapshots: list[dict]) -> dict:
    """Merge per-process snapshots into one coherent view: counters and
    histograms add; gauges combine per their declared ``aggregate``
    mode. Metrics appearing in only some snapshots merge from those."""
    merged: dict = {}
    # gauge "mean" needs the contributing-count; track per (name, labelkey)
    gauge_counts: dict[tuple, int] = {}
    for snap in snapshots:
        for name, entry in snap.items():
            target = merged.get(name)
            if target is None:
                target = merged[name] = {
                    "type": entry["type"],
                    "help": entry["help"],
                    "samples": [],
                    "_by_labels": {},
                }
                if "buckets" in entry:
                    target["buckets"] = list(entry["buckets"])
                if "aggregate" in entry:
                    target["aggregate"] = entry["aggregate"]
            elif target["type"] != entry["type"] or (
                "buckets" in entry
                and target.get("buckets") != list(entry["buckets"])
            ):
                # irreconcilable definitions (e.g. two code versions):
                # keep the first, skip the conflicting contribution
                continue
            for sample in entry["samples"]:
                key = _label_key(sample["labels"])
                existing = target["_by_labels"].get(key)
                if existing is None:
                    copy = dict(sample)
                    if "buckets" in copy:
                        copy["buckets"] = list(copy["buckets"])
                    target["_by_labels"][key] = copy
                    gauge_counts[(name, key)] = 1
                    continue
                if entry["type"] == "histogram":
                    existing["buckets"] = [
                        a + b
                        for a, b in zip(existing["buckets"], sample["buckets"])
                    ]
                    existing["sum"] += sample["sum"]
                    existing["count"] += sample["count"]
                    # exemplars: any contributor's exemplar beats none;
                    # between two, the later-merged snapshot wins (the
                    # semantics are "the LAST trace seen per bucket" and
                    # merge inputs carry no ordering evidence)
                    incoming = sample.get("exemplars")
                    if incoming:
                        current = existing.get("exemplars")
                        if current is None:
                            existing["exemplars"] = [
                                dict(e) if e is not None else None
                                for e in incoming
                            ]
                        else:
                            existing["exemplars"] = [
                                (dict(b) if b is not None else a)
                                for a, b in zip(current, incoming)
                            ]
                elif entry["type"] == "counter":
                    existing["value"] += sample["value"]
                else:  # gauge
                    # the TARGET's (first-seen) mode, not each entry's:
                    # two code versions declaring different modes must
                    # not make the merge order-dependent
                    mode = target.get("aggregate", "max")
                    if mode == "sum":
                        existing["value"] += sample["value"]
                    elif mode == "min":
                        existing["value"] = min(existing["value"], sample["value"])
                    elif mode == "mean":
                        n = gauge_counts[(name, key)]
                        existing["value"] = (
                            existing["value"] * n + sample["value"]
                        ) / (n + 1)
                        gauge_counts[(name, key)] = n + 1
                    else:  # max
                        existing["value"] = max(existing["value"], sample["value"])
    for entry in merged.values():
        entry["samples"] = list(entry.pop("_by_labels").values())
    return merged


#: the process-wide default registry every instrumented layer shares
_DEFAULT = Registry()


def get_registry() -> Registry:
    return _DEFAULT
