"""Stage spans: structured per-day run reports + Chrome trace events.

The pipeline runner times its stages (``DayResult.stage_seconds``) but
those numbers evaporate with the process, and overlap work (lookahead
train, dataset prefetch, prewarm compiles) is invisible in a flat
per-stage table — exactly the work whose scheduling the runner exists to
optimise. A :class:`SpanRecorder` collects named spans (stages AND the
background overlaps) on a single perf_counter timeline and renders them
two ways:

- :func:`day_report` — a structured JSON run report per ``run_day``
  (machine-diffable: day, wall clock, per-stage seconds, every span);
- :func:`chrome_trace` — a Chrome trace-event file (``ph: "X"`` complete
  events on per-thread tracks) loadable in Perfetto / ``chrome://tracing``,
  where the lookahead-train bar visibly overlapping the test-stage bar IS
  the optimisation working.

Stage spans are recorded from the SAME measurements as
``DayResult.stage_seconds`` (the runner passes the timings in rather
than re-measuring), so trace durations sum-check exactly against the
existing per-day numbers.

Stdlib-only, like the rest of :mod:`bodywork_tpu.obs`.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from contextlib import contextmanager
from pathlib import Path

__all__ = [
    "Span",
    "SpanRecorder",
    "chrome_trace",
    "day_report",
    "write_chrome_trace",
    "write_day_report",
]

#: span categories with defined meanings (free-form ones are allowed too)
CATEGORY_STAGE = "stage"      # a DAG stage at its DAG position
CATEGORY_OVERLAP = "overlap"  # background work overlapping the DAG (lookahead)
CATEGORY_PREFETCH = "prefetch"  # dataset prefetch worker
CATEGORY_PREWARM = "prewarm"  # bucket-compile prewarm
CATEGORY_DAY = "day"          # the whole run_day envelope


@dataclasses.dataclass
class Span:
    """One named interval on the recorder's timeline (seconds since the
    recorder's epoch)."""

    name: str
    category: str
    start_s: float
    duration_s: float
    thread: str
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def end_s(self) -> float:
        return self.start_s + self.duration_s

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "category": self.category,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
            "thread": self.thread,
        }
        if self.meta:
            d["meta"] = dict(self.meta)
        return d


class SpanRecorder:
    """Thread-safe append-only span log on one perf_counter timeline.

    One recorder per runner: background threads (prefetch, lookahead
    train) capture the recorder at span start, so their spans land on
    the same timeline as the stages they overlap. ``mark``/``since``
    let ``run_day`` slice out the spans recorded during its window."""

    def __init__(self, label: str = "run"):
        self.label = label
        self._t0 = time.perf_counter()
        #: wall-clock anchor for the perf_counter epoch (report metadata)
        self.epoch_unix_s = time.time()
        self._lock = threading.Lock()
        self._spans: list[Span] = []

    def now(self) -> float:
        """Seconds since the recorder's epoch."""
        return time.perf_counter() - self._t0

    def add(
        self,
        name: str,
        category: str,
        start_s: float,
        duration_s: float,
        **meta,
    ) -> Span:
        """Record an already-measured interval (the runner's stage path:
        the span duration IS ``stage_seconds[name]``, not a re-measure)."""
        span = Span(
            name=name,
            category=category,
            start_s=start_s,
            duration_s=duration_s,
            thread=threading.current_thread().name,
            meta=meta,
        )
        with self._lock:
            self._spans.append(span)
        return span

    @contextmanager
    def span(self, name: str, category: str = CATEGORY_STAGE, **meta):
        """Measure-and-record context manager for background work."""
        start = self.now()
        try:
            yield
        finally:
            self.add(name, category, start, self.now() - start, **meta)

    def mark(self) -> int:
        """Position token for :meth:`since` (spans recorded so far)."""
        with self._lock:
            return len(self._spans)

    def since(self, mark: int) -> list[Span]:
        with self._lock:
            return list(self._spans[mark:])

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)


def day_report(
    result, spans: list[Span] | None = None, fsck: dict | None = None,
    tenant: str | None = None,
) -> dict:
    """Structured JSON-able run report for one ``DayResult``.

    ``spans`` defaults to ``result.spans`` (the runner attaches the
    day-window slice). ``fsck`` (an integrity-scrub report from
    ``audit.run_fsck`` — what ``cli run-day --scrub`` produces) adds a
    findings block so the daily report carries the store's integrity
    verdict next to its timings. Schema (stable; tests/test_obs.py pins
    it)::

        {"schema": "bodywork_tpu.day_report/1",
         "day": "YYYY-MM-DD", "wall_clock_s": float,
         "stage_seconds": {stage: float},
         "spans": [{name, category, start_s, duration_s, thread, meta?}],
         "fsck"?: {"clean", "ok", "keys_scanned", "by_severity",
                   "findings": [...]},
         "tenant"?: str}

    ``tenant`` names the tenant namespace the day ran in; the default
    (root) namespace OMITS the field, keeping default-tenant reports
    byte-identical to pre-tenancy ones.
    """
    spans = result.spans if spans is None else spans
    report = {
        "schema": "bodywork_tpu.day_report/1",
        "day": str(result.day),
        "wall_clock_s": round(result.wall_clock_s, 6),
        "stage_seconds": {
            name: round(secs, 6)
            for name, secs in result.stage_seconds.items()
        },
        "spans": [s.to_dict() for s in spans],
    }
    if tenant is not None and tenant != "default":
        report["tenant"] = tenant
    if fsck is not None:
        report["fsck"] = {
            "clean": fsck["clean"],
            "ok": fsck["ok"],
            "keys_scanned": fsck["keys_scanned"],
            "by_severity": fsck["by_severity"],
            "findings": fsck["findings"],
        }
    return report


def write_day_report(path: str | Path, report: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=2) + "\n")
    return path


def chrome_trace(
    spans: list[Span], process_name: str = "bodywork_tpu"
) -> dict:
    """Chrome trace-event JSON (the ``traceEvents`` object form) from a
    span list: one complete (``ph: "X"``) event per span on a per-thread
    track, plus name metadata so Perfetto labels the tracks."""
    threads = {}
    events: list[dict] = []
    for span in spans:
        tid = threads.setdefault(span.thread, len(threads) + 1)
        events.append(
            {
                "name": span.name,
                "cat": span.category,
                "ph": "X",
                "ts": round(span.start_s * 1e6, 3),
                "dur": round(span.duration_s * 1e6, 3),
                "pid": 1,
                "tid": tid,
                **({"args": dict(span.meta)} if span.meta else {}),
            }
        )
    meta_events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "args": {"name": process_name},
        }
    ]
    for thread_name, tid in threads.items():
        meta_events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": thread_name},
            }
        )
    return {"traceEvents": meta_events + events, "displayTimeUnit": "ms"}


def write_chrome_trace(
    path: str | Path, spans: list[Span], process_name: str = "bodywork_tpu"
) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(chrome_trace(spans, process_name)) + "\n")
    return path
