"""Request-scoped tracing through the serving hot path (ISSUE 13).

The obs subsystem so far is aggregate-only: phase histograms say *that*
p99 moved, stage spans cover the pipeline runner — but nothing can
follow ONE request through admission -> coalescer fan-in -> AOT device
dispatch -> canary routing -> sanity-firewall fallback -> serialize, and
an SLO-watchdog abort ships zero per-request evidence of which requests
burned the budget. This module is that layer, and it is what produces
the per-request span corpus the future learned cost model trains on
(ROADMAP item 5).

Design contract (every piece deterministic on purpose):

- **W3C-compatible IDs.** A request arriving with a ``traceparent``
  header (``00-<32 hex trace id>-<16 hex parent span id>-<flags>``)
  keeps its trace id; otherwise one is MINTED as a pure function of
  ``(sampling seed, request body bytes)`` — so a seeded traffic replay
  (or the chaos twin of the same run) mints the identical ids, and two
  engines serving the same request agree byte-for-byte on the id.
  Identical payloads therefore share a minted trace id: that is the
  determinism contract, not a bug (spans are per-execution either way).
- **Deterministic head sampling.** The keep/drop decision is a pure
  function of ``(sampling seed, trace id)`` — ``sha256(seed|trace_id)``
  compared against ``fraction`` of the 2^64 space, the same construction
  as canary routing (``serve.app.routes_to_canary``) — so chaos twins
  and seeded replays sample identical requests.
- **Bounded hot-path cost.** An unsampled request pays the id mint +
  the sampling hash + one branch, and allocates exactly one small
  context object (``RequestTrace`` with ``spans=None``); no span list,
  no lock traffic, no store I/O anywhere on the request path. Trace ids
  ride ONLY the :data:`TRACE_ID_HEADER` response header — never a
  response body — which is what lets the chaos byte-identity soak run
  with tracing enabled (the comparator reads bodies, like the
  model-key header).
- **Three consumers** make the spans load-bearing: the in-process
  :class:`FlightRecorder` ring buffer the SLO watchdog dumps to the
  store at every abort/promote verdict (``obs/flightrec/`` prefix,
  schema :data:`FLIGHT_RECORD_SCHEMA`, digest sidecar via the audit
  layer); histogram **exemplars** (``obs.registry.Histogram``) tying a
  fat latency bucket to a replayable trace id on ``/metrics`` and
  ``/healthz``; and ``cli trace`` (``show``/``tail``/``export
  --chrome``) rendering stored dumps through the existing Chrome-trace
  emitter (:mod:`bodywork_tpu.obs.spans`).

Stdlib-only, like the rest of :mod:`bodywork_tpu.obs`.
"""
from __future__ import annotations

import contextvars
import hashlib
import json
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager

from bodywork_tpu.utils.integrity import stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("obs.tracing")

__all__ = [
    "FLIGHT_RECORD_SCHEMA",
    "TRACEPARENT_HEADER",
    "TRACE_ID_HEADER",
    "FlightRecorder",
    "RequestTrace",
    "TraceSpan",
    "Tracer",
    "annotate_active",
    "configure_tracing",
    "configured_tracing",
    "flight_record_doc",
    "flight_trace_spans",
    "get_tracer",
    "head_sampled",
    "mint_trace_id",
    "parse_traceparent",
    "validate_flight_record",
    "write_flight_record",
]

#: W3C ingress header both engines accept (case-insensitive per HTTP)
TRACEPARENT_HEADER = "traceparent"
#: the ONLY place a trace id leaves the service: a response header, never
#: a body — the chaos byte-identity comparator reads bodies, so tracing
#: on/off twins stay byte-identical (same rule as the model-key header)
TRACE_ID_HEADER = "X-Bodywork-Trace-Id"

FLIGHT_RECORD_SCHEMA = "bodywork_tpu.flight_record/1"

#: env knobs (read once at tracer construction; ``configure_tracing``
#: overrides in-process). Sampling defaults to a light head fraction so
#: the flight recorder has evidence out of the box; 0 disables tracing
#: entirely (no mint, no header, zero overhead).
SAMPLE_ENV = "BODYWORK_TPU_TRACE_SAMPLE"
SEED_ENV = "BODYWORK_TPU_TRACE_SEED"
DEFAULT_SAMPLE_FRACTION = 0.1
#: completed sampled traces the in-process ring buffer retains — the
#: evidence window a watchdog verdict dumps (oldest evicted first)
DEFAULT_RECORDER_CAPACITY = 256

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def parse_traceparent(value: str | None) -> tuple[str, str] | None:
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header,
    or None for an absent/malformed one (malformed ingress context is
    DROPPED, per the spec — the request then mints its own id; an
    all-zero trace id is invalid too)."""
    if not value:
        return None
    match = _TRACEPARENT_RE.match(value.strip().lower())
    if match is None:
        return None
    trace_id, parent = match.group(1), match.group(2)
    if trace_id == "0" * 32 or parent == "0" * 16:
        return None
    return trace_id, parent


def mint_trace_id(seed: int, payload: bytes) -> str:
    """A 32-hex-char trace id minted as a PURE function of ``(seed,
    request body bytes)`` — seeded replays and chaos twins mint
    identical ids for identical requests (module docstring)."""
    digest = hashlib.sha256(
        str(int(seed)).encode("ascii") + b"|trace|" + payload
    ).digest()
    return digest[:16].hex()


def head_sampled(seed: int, trace_id: str, fraction: float) -> bool:
    """The deterministic head-sampling decision: a pure function of
    ``(sampling seed, trace id)`` — one sha256 + one compare, the same
    unbiased top-64-bits construction as canary routing."""
    if fraction >= 1.0:
        return True
    if fraction <= 0.0:
        return False
    digest = hashlib.sha256(
        str(int(seed)).encode("ascii") + b"|sample|" + trace_id.encode("ascii")
    ).digest()
    return int.from_bytes(digest[:8], "big") < int(fraction * 2.0**64)


def _derived_span_id(trace_id: str, name: str, ordinal: int) -> str:
    """16-hex span id, deterministic within a trace (replay-stable)."""
    return hashlib.sha256(
        f"{trace_id}|{name}|{ordinal}".encode("ascii")
    ).digest()[:8].hex()


class TraceSpan:
    """One interval inside a request trace (seconds relative to the
    trace's begin). ``meta`` is open: the dispatch path records bucket /
    AOT-cache facts, the coalescer records batch fan-in links."""

    __slots__ = ("name", "span_id", "parent_id", "start_s", "duration_s", "meta")

    def __init__(self, name: str, span_id: str, parent_id: str,
                 start_s: float, duration_s: float | None = None, meta=None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.duration_s = duration_s
        self.meta = meta if meta is not None else {}

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            # an unclosed span (handler raised mid-flight) reports zero
            # duration rather than poisoning the dump
            "duration_s": round(self.duration_s or 0.0, 6),
        }
        if self.meta:
            d["meta"] = dict(self.meta)
        return d


class RequestTrace:
    """The per-request span context both engines thread through the hot
    path. Unsampled traces carry ``spans=None`` and every record method
    is a no-op behind one branch — the context object is the only
    allocation the unsampled path pays."""

    __slots__ = (
        "trace_id", "parent_span_id", "root_span_id", "sampled",
        "_t0", "spans", "_lock", "_n", "route", "status", "meta",
    )

    def __init__(self, trace_id: str, sampled: bool,
                 parent_span_id: str | None = None):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.root_span_id = _derived_span_id(trace_id, "request", 0)
        self.sampled = sampled
        self._t0 = time.perf_counter()
        self.route: str | None = None
        self.status: int | None = None
        # span storage only exists for sampled traces; the lock is for
        # the coalescer's dispatcher thread recording into a request's
        # trace concurrently with the request thread
        self.spans: list[TraceSpan] | None = [] if sampled else None
        self._lock = threading.Lock() if sampled else None
        self._n = 0
        self.meta: dict = {}

    def now(self) -> float:
        """Seconds since this trace began (perf_counter timeline)."""
        return time.perf_counter() - self._t0

    def rel(self, t_perf: float) -> float:
        return t_perf - self._t0

    def add(self, name: str, t_start_perf: float, t_end_perf: float,
            **meta) -> TraceSpan | None:
        """Record an already-measured interval (absolute perf_counter
        endpoints — the timestamps the metrics path already takes)."""
        if self.spans is None:
            return None
        with self._lock:
            self._n += 1
            span = TraceSpan(
                name, _derived_span_id(self.trace_id, name, self._n),
                self.root_span_id, self.rel(t_start_perf),
                t_end_perf - t_start_perf, meta,
            )
            self.spans.append(span)
        return span

    def start_span(self, name: str, **meta) -> TraceSpan | None:
        """Open a span NOW (closed via :meth:`end_span`) — for paths
        that want mid-flight annotation (the AOT dispatch)."""
        if self.spans is None:
            return None
        with self._lock:
            self._n += 1
            span = TraceSpan(
                name, _derived_span_id(self.trace_id, name, self._n),
                self.root_span_id, self.now(), None, meta,
            )
            self.spans.append(span)
        return span

    def end_span(self, span: TraceSpan | None) -> None:
        if span is not None:
            span.duration_s = self.now() - span.start_s

    def annotate(self, **meta) -> None:
        """Attach request-level facts (stream, model key, …) to the
        trace root. No-op when unsampled."""
        if self.spans is not None:
            self.meta.update(meta)

    def to_dict(self) -> dict:
        doc = {
            "trace_id": self.trace_id,
            "root_span_id": self.root_span_id,
            "route": self.route,
            "status": self.status,
            "duration_s": round(self.now(), 6),
            "spans": [s.to_dict() for s in (self.spans or ())],
        }
        if self.parent_span_id:
            doc["parent_span_id"] = self.parent_span_id
        if self.meta:
            doc["meta"] = dict(self.meta)
        return doc


# -- the active-span channel (predictor annotations) -----------------------

_ACTIVE_SPAN: contextvars.ContextVar[TraceSpan | None] = contextvars.ContextVar(
    "bodywork_tpu_active_span", default=None
)


def set_active_span(span: TraceSpan | None):
    """Install ``span`` as the thread/task's active span; returns the
    reset token. Only the sampled dispatch path sets one."""
    return _ACTIVE_SPAN.set(span)


def reset_active_span(token) -> None:
    _ACTIVE_SPAN.reset(token)


def annotate_active(**meta) -> None:
    """Attach facts to whatever span is active (the predictor's lazy
    AOT-compile seam). One contextvar read + a branch when nothing is —
    safe to call from any depth."""
    span = _ACTIVE_SPAN.get()
    if span is not None:
        span.meta.update(meta)


# -- flight recorder -------------------------------------------------------


class FlightRecorder:
    """Bounded in-process ring buffer of COMPLETED sampled traces — the
    evidence the SLO watchdog dumps to the store at every abort/promote
    verdict, so each auto-rollback ships the requests that convicted
    (or acquitted) the canary."""

    def __init__(self, capacity: int = DEFAULT_RECORDER_CAPACITY):
        self._lock = threading.Lock()
        self._traces: deque = deque(maxlen=int(capacity))

    def add(self, trace_doc: dict) -> None:
        with self._lock:
            self._traces.append(trace_doc)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class Tracer:
    """The process-wide tracing front door (one per serving process,
    like the metrics registry): config + the flight recorder. Both
    engines call :meth:`begin` on scoring ingress and :meth:`finish`
    once the response is built."""

    def __init__(self, sample_fraction: float | None = None,
                 seed: int | None = None,
                 recorder_capacity: int = DEFAULT_RECORDER_CAPACITY):
        if sample_fraction is None:
            sample_fraction = _env_fraction()
        if seed is None:
            seed = _env_seed()
        self.sample_fraction = float(sample_fraction)
        self.seed = int(seed)
        self.recorder = FlightRecorder(recorder_capacity)
        self._m_sampled = None

    @property
    def enabled(self) -> bool:
        """Fraction 0 turns tracing OFF entirely: no mint, no header,
        no per-request work at all — the tracing-off twin."""
        return self.sample_fraction > 0.0

    def begin(self, traceparent: str | None, payload: bytes) -> RequestTrace:
        """One request's trace context: ingress id when a valid
        ``traceparent`` arrived, minted otherwise; sampled by the
        deterministic head decision. The unsampled path is exactly the
        mint + one hash + one branch + this object."""
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent = parsed
        else:
            trace_id, parent = mint_trace_id(self.seed, payload), None
        sampled = head_sampled(self.seed, trace_id, self.sample_fraction)
        return RequestTrace(trace_id, sampled, parent)

    def finish(self, trace: RequestTrace, route: str, status: int) -> None:
        """Complete a trace; sampled ones land in the flight recorder
        (and count). Unsampled: one branch, nothing else."""
        if trace.spans is None:
            return
        trace.route = route
        trace.status = int(status)
        self.recorder.add(trace.to_dict())
        if self._m_sampled is None:
            from bodywork_tpu.obs.registry import get_registry

            self._m_sampled = get_registry().counter(
                "bodywork_tpu_trace_sampled_total",
                "Scoring requests head-sampled into the flight recorder, "
                "by route",
            )
        self._m_sampled.inc(route=route)


def _env_fraction() -> float:
    raw = os.environ.get(SAMPLE_ENV, "").strip()
    if raw:
        try:
            value = float(raw)
            if 0.0 <= value <= 1.0:
                return value
        except ValueError:
            pass
        log.warning(f"ignoring {SAMPLE_ENV}={raw!r} (need a fraction in [0, 1])")
    return DEFAULT_SAMPLE_FRACTION


def _env_seed() -> int:
    raw = os.environ.get(SEED_ENV, "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            log.warning(f"ignoring {SEED_ENV}={raw!r} (need an integer)")
    return 0


#: THE process-wide tracer (configure_tracing mutates it IN PLACE so
#: apps that captured the reference see config changes immediately)
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def configure_tracing(sample_fraction: float, seed: int = 0,
                      recorder_capacity: int | None = None) -> Tracer:
    """Reconfigure the process tracer in place (CLI / harness entry).
    Clears the recorder: evidence must belong to the configured run."""
    if not 0.0 <= sample_fraction <= 1.0:
        raise ValueError(
            f"sample_fraction must be in [0, 1], got {sample_fraction}"
        )
    _TRACER.sample_fraction = float(sample_fraction)
    _TRACER.seed = int(seed)
    if recorder_capacity is not None:
        _TRACER.recorder = FlightRecorder(recorder_capacity)
    else:
        _TRACER.recorder.clear()
    return _TRACER


@contextmanager
def configured_tracing(sample_fraction: float, seed: int = 0):
    """Scoped tracer config (harnesses and tests): configure, yield the
    tracer, restore the previous (fraction, seed) — the recorder is
    cleared on entry so the scope's evidence is its own."""
    previous = (_TRACER.sample_fraction, _TRACER.seed)
    tracer = configure_tracing(sample_fraction, seed)
    try:
        yield tracer
    finally:
        _TRACER.sample_fraction, _TRACER.seed = previous
        _TRACER.recorder.clear()


# -- flight-record documents (store schema bodywork_tpu.flight_record/1) ---


def flight_record_doc(
    traces: list[dict],
    verdict: str,
    reason: str,
    canary_key: str | None = None,
    production_key: str | None = None,
    window: dict | None = None,
    sampling: dict | None = None,
) -> dict:
    """The dump document the SLO watchdog persists at a verdict. A pure
    function of its inputs (no wall clock — trace timings are relative
    offsets), stamped with a ``doc_digest`` like every other mutable
    JSON class so fsck can see rot."""
    return stamp_doc({
        "schema": FLIGHT_RECORD_SCHEMA,
        "verdict": verdict,
        "reason": reason,
        "canary_key": canary_key,
        "production_key": production_key,
        "window": window or {},
        "sampling": sampling or {},
        "n_traces": len(traces),
        "traces": list(traces),
    })


def validate_flight_record(doc) -> bool:
    """Schema-tag + shape + embedded-digest validation — what fsck's
    ``obs/flightrec/`` auditor and ``cli trace`` readers share."""
    if not isinstance(doc, dict) or doc.get("schema") != FLIGHT_RECORD_SCHEMA:
        return False
    if verify_doc(doc) is False:
        return False
    traces = doc.get("traces")
    if not isinstance(traces, list) or doc.get("n_traces") != len(traces):
        return False
    return all(
        isinstance(t, dict) and t.get("trace_id") and isinstance(
            t.get("spans"), list
        )
        for t in traces
    )


def write_flight_record(store, doc: dict) -> str:
    """Persist one dump under ``obs/flightrec/``. The key leads with a
    sequence number (count of dumps already stored — listing order IS
    write order, no wall clock) and embeds the content digest, so a
    re-write of the SAME document is idempotent (returns the existing
    key) while concurrent distinct documents never collide. An
    AuditedStore records the digest sidecar exactly as for any other
    covered class."""
    from bodywork_tpu.store.schema import FLIGHTREC_PREFIX, flight_record_key

    fragment = doc["doc_digest"].removeprefix("sha256:")[:16]
    existing = store.list_keys(FLIGHTREC_PREFIX)
    for key in existing:
        if key.endswith(f"-{doc['verdict']}-{fragment}.json"):
            return key  # same document already dumped
    key = flight_record_key(len(existing), doc["verdict"], doc["doc_digest"])
    store.put_bytes(
        key, json.dumps(doc, sort_keys=True, indent=1).encode("utf-8")
    )
    return key


def iter_flight_records(store):
    """``(key, doc)`` for every VALID stored dump, newest-keyed last;
    invalid ones are skipped with a warning (``cli trace`` and fsck
    both read through validation)."""
    from bodywork_tpu.store.schema import FLIGHTREC_PREFIX

    for key in store.list_keys(FLIGHTREC_PREFIX):
        try:
            doc = json.loads(store.get_bytes(key).decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            log.warning(f"skipping unreadable flight record {key}")
            continue
        if not validate_flight_record(doc):
            log.warning(f"skipping invalid flight record {key}")
            continue
        yield key, doc


def find_trace(store, trace_id: str):
    """``(dump_key, trace_doc)`` for a stored trace by full id or any
    unambiguous prefix; ``(None, None)`` when absent."""
    trace_id = trace_id.strip().lower()
    for key, doc in iter_flight_records(store):
        for trace in doc["traces"]:
            if trace["trace_id"].startswith(trace_id):
                return key, trace
    return None, None


def flight_trace_spans(trace_doc: dict):
    """A stored trace rendered as :class:`bodywork_tpu.obs.spans.Span`
    objects (one Chrome-trace track per trace), so ``cli trace export
    --chrome`` reuses the existing Perfetto emitter unchanged."""
    from bodywork_tpu.obs.spans import Span

    track = f"trace-{trace_doc['trace_id'][:8]}"
    meta = dict(trace_doc.get("meta") or {})
    meta["trace_id"] = trace_doc["trace_id"]
    out = [Span(
        name=f"request {trace_doc.get('route') or ''}".strip(),
        category="request",
        start_s=0.0,
        duration_s=trace_doc.get("duration_s") or 0.0,
        thread=track,
        meta={**meta, "status": trace_doc.get("status")},
    )]
    for span in trace_doc.get("spans", ()):
        out.append(Span(
            name=span["name"],
            category="serve",
            start_s=span.get("start_s") or 0.0,
            duration_s=span.get("duration_s") or 0.0,
            thread=track,
            meta=dict(span.get("meta") or {}),
        ))
    return out
