from bodywork_tpu.ops.mlp_kernel import (
    ROW_TILE,
    fold_scaler_into_net,
    make_pallas_mlp_apply,
)

__all__ = ["ROW_TILE", "fold_scaler_into_net", "make_pallas_mlp_apply"]
