"""Pallas TPU kernel: the whole MLP scoring forward as ONE fused kernel.

The serving hot path (reference ``stage_2_serve_model.py:78``, here
``models.mlp.mlp_apply``) is standardise -> dense/relu stack ->
unstandardise. XLA already fuses this well; the Pallas version exists for
the serving regime where it can do strictly better: every weight stays
resident in VMEM across the whole forward (one HBM->VMEM load per weight
per kernel, amortised over the row grid), and the scaler is folded into
the first/last layers' weights ahead of time so the kernel is a pure
dense stack.

Design (see /opt/skills/guides/pallas_guide.md):

- **Scaler folding** (host-side algebra, done once per model):
  ``W1' = W1 / x_std[:, None]``, ``b1' = b1 - (x_mean / x_std) @ W1``,
  ``WL' = WL * y_std``, ``bL' = bL * y_std + y_mean`` — numerically
  identical to ``mlp_apply`` up to float32 rounding.
- **Lane padding**: all layer widths are zero-padded to multiples of 128
  (the TPU lane width). Zero columns/rows are inert through matmul and
  relu, so padding never changes results.
- **Grid over rows**: each grid step processes a ``ROW_TILE x width``
  block; weights use a constant index map (the compiler keeps them in
  VMEM across steps).

Used by serving when ``engine="pallas"`` (``serve.predictor``); tests run
the kernel in interpreter mode on CPU against the XLA reference.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

ROW_TILE = 256
LANE = 128


def _pad_to(x: jax.Array, rows: int | None = None, cols: int | None = None):
    """Zero-pad a 1-D/2-D array up to (rows, cols)."""
    if x.ndim == 1:
        out = jnp.zeros((cols,), x.dtype)
        return out.at[: x.shape[0]].set(x)
    out = jnp.zeros((rows, cols), x.dtype)
    return out.at[: x.shape[0], : x.shape[1]].set(x)


def fold_scaler_into_net(params: dict) -> list[tuple[jax.Array, jax.Array]]:
    """Fold the standardisation scaler into the dense stack's first and
    last layers; returns [(W, b), ...] equivalent to ``mlp_apply``."""
    s = params["scaler"]
    layers = [(layer["w"], layer["b"]) for layer in params["net"]["layers"]]
    w1, b1 = layers[0]
    inv = 1.0 / s["x_std"]
    w1f = w1 * inv[:, None]
    b1f = b1 - (s["x_mean"] * inv) @ w1
    layers[0] = (w1f, b1f)
    # for a single-layer net layers[-1] IS layers[0], so the y-fold below
    # correctly composes with the x-fold above
    wl, bl = layers[-1]
    layers[-1] = (wl * s["y_std"], bl * s["y_std"] + s["y_mean"])
    return layers


def _mlp_kernel(n_layers: int, operand_dtype, *refs):
    """Fused dense stack: x_ref, w0,b0, w1,b1, ..., out_ref.

    ``operand_dtype`` is the matmul-operand dtype: f32 (default engine) or
    bf16 (the weights arrive pre-cast; activations are cast at each dot).
    Accumulation is always f32 (``preferred_element_type``), as are the
    bias adds and relu, so only the multiplies lose precision."""
    x_ref, out_ref = refs[0], refs[-1]
    h = x_ref[:]
    for i in range(n_layers):
        w = refs[1 + 2 * i][:]
        b = refs[2 + 2 * i][:]
        h = jnp.dot(
            h.astype(operand_dtype), w,
            preferred_element_type=jnp.float32,
        ) + b[None, :]
        if i < n_layers - 1:
            h = jnp.maximum(h, 0.0)
    out_ref[:] = h


def _mlp_kernel_int8(n_layers: int, *refs):
    """Int8-weight fused dense stack: x_ref, (wq, scale, b) per layer,
    out_ref. Weights sit in VMEM as int8 (a quarter of the f32 bytes —
    ~2x the servable width before spilling vs bf16) and dequantize
    per-tile right before the dot; scales/biases/accumulation stay f32."""
    x_ref, out_ref = refs[0], refs[-1]
    h = x_ref[:]
    for i in range(n_layers):
        wq = refs[1 + 3 * i][:]
        scale = refs[2 + 3 * i][:]
        b = refs[3 + 3 * i][:]
        w = wq.astype(jnp.float32) * scale[None, :]
        h = jnp.dot(h, w, preferred_element_type=jnp.float32) + b[None, :]
        if i < n_layers - 1:
            h = jnp.maximum(h, 0.0)
    out_ref[:] = h


def make_pallas_mlp_apply(params: dict, interpret: bool = False,
                          compute_dtype: str | None = None,
                          row_tile: int | None = None):
    """Build ``apply(X) -> y`` running the folded MLP as one Pallas kernel.

    Weights are padded/folded once at build time and stay on device;
    ``apply`` pads the batch to a ``row_tile`` multiple (default
    :data:`ROW_TILE`) and returns the first column (the regression head)
    unpadded. A smaller ``row_tile`` (still a multiple of 8, the f32
    sublane) is the coalesced-batch serving shape: a 16-row coalescer
    flush grids over two 8-row tiles instead of padding to 256 rows —
    the whole fused-kernel path becomes usable for cross-request
    micro-batching, not just bulk scoring.

    ``compute_dtype="bfloat16"`` stores the padded weights in bf16 (half
    the VMEM bytes per weight; since square-layer weight bytes grow as
    width², that buys ~1.4x the width before spilling, not 2x) and runs
    the matmuls with bf16 operands on the MXU's native path; accumulation,
    biases, and relu stay f32 here — slightly *tighter* numerics than the
    ``xla-bf16`` engine, whose activations and biases are bf16 end-to-end
    — so the two bf16 engines agree only to bf16 precision, not bitwise.
    Same ~3-significant-digit prediction trade, opt-in the same way.

    ``compute_dtype="int8"`` stores the padded weights as symmetric
    per-output-channel int8 (``models.fused.quantize_int8`` applied to
    the FOLDED weights, so the scaler fold costs no extra error source)
    with f32 scales, dequantized per-tile inside the kernel — a quarter
    of f32's weight VMEM/HBM bytes. Same quality-gate contract as the
    XLA int8 engine (serve.server).
    """
    from jax.experimental import pallas as pl

    tile = int(row_tile or ROW_TILE)
    if tile < 8 or tile % 8 != 0:
        raise ValueError(
            f"row_tile must be a positive multiple of 8, got {tile}"
        )
    int8_weights = compute_dtype == "int8"
    operand_dtype = (
        jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32
    )
    folded = fold_scaler_into_net(params)
    d_in = folded[0][0].shape[0]
    widths = [d_in] + [w.shape[1] for w, _ in folded]
    padded = [max(LANE, -(-w // LANE) * LANE) for w in widths]

    weights = []
    for (w, b), rows, cols in zip(folded, padded[:-1], padded[1:]):
        if int8_weights:
            from bodywork_tpu.models.fused import quantize_int8

            q, scale = quantize_int8(w)
            weights.append(_pad_to(jnp.asarray(q), rows, cols))
            # zero-pad scales: padded columns hold q=0, any scale works
            weights.append(_pad_to(jnp.asarray(scale), cols=cols))
        else:
            # only the matmul LHS/RHS drop to bf16; biases stay f32 and
            # are added to the f32 accumulator
            weights.append(_pad_to(w, rows, cols).astype(operand_dtype))
        weights.append(_pad_to(b, cols=cols))

    n_layers = len(folded)
    kernel = (
        partial(_mlp_kernel_int8, n_layers)
        if int8_weights
        else partial(_mlp_kernel, n_layers, operand_dtype)
    )
    in_width, out_width = padded[0], padded[-1]

    @jax.jit
    def apply(X: jax.Array) -> jax.Array:
        X = jnp.asarray(X, jnp.float32)
        if X.ndim == 1:
            X = X[:, None]
        if X.shape[1] != d_in:
            # zero-filling a short row would silently score garbage; match
            # the XLA engine, which raises on a feature-count mismatch
            raise ValueError(
                f"expected {d_in} feature(s), got {X.shape[1]}"
            )
        n = X.shape[0]
        n_pad = -(-n // tile) * tile
        Xp = jnp.zeros((n_pad, in_width), jnp.float32)
        Xp = Xp.at[:n, : X.shape[1]].set(X)

        grid = (n_pad // tile,)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_pad, out_width), jnp.float32),
            grid=grid,
            in_specs=[
                pl.BlockSpec((tile, in_width), lambda i: (i, 0)),
            ]
            + [
                # constant index map: weights/biases identical every step,
                # so they stay VMEM-resident across the row grid
                pl.BlockSpec(w.shape, lambda i, nd=w.ndim: (0,) * nd)
                for w in weights
            ],
            out_specs=pl.BlockSpec((tile, out_width), lambda i: (i, 0)),
            interpret=interpret,
        )(Xp, *weights)
        return out[:n, 0]

    return apply
