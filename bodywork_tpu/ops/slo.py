"""SLO watchdog: the error-budget engine that closes the live release loop.

The registry gate (PR 5) adjudicates *offline* signals and the chaos
harness proves infrastructure-fault survival — but a model that passes
every offline gate can still degrade on real traffic: NaN or absurd
predictions under drifted inputs, latency blowups, error spikes. The
canary machinery (``registry`` canary slot + ``serve.app`` routing)
exposes a candidate to a seeded fraction of live traffic; THIS module is
the judge. It runs inside the reload-watcher loop
(:class:`~bodywork_tpu.serve.reload.CheckpointWatcher`), reads the
per-model-key stream metrics the serving layer records into the obs
registry while a canary is live, and decides:

- **Abort** (breach): the canary is retired with ONE compare-and-swap of
  the alias document (:meth:`~bodywork_tpu.registry.manager.
  ModelRegistry.canary_abort` — the same CAS primitive as PR 5's
  rollback), the lineage event records why, and in-process routing is
  cleared immediately — no operator, no pager, no second write.
- **Promote** (survived the window healthy): the canary graduates to
  production in one CAS and the already-warm canary bundle starts
  taking 100% of traffic in-process.

Breach signals (:class:`SloPolicy`), all computed over a sliding window
of the last ``window_requests`` canary requests — windowing is what
makes the burn rate a RATE (a canary must not be condemned forever for
one bad minute, nor saved by a long healthy prefix):

- **Error-budget burn** — the canary stream's windowed error rate
  divided by ``max_error_rate`` (the budget). Burn >= ``burn_rate_
  threshold`` with at least ``min_requests`` observed is a breach.
- **Sanity violations** — predictions the firewall caught (non-finite /
  outside the training-label band). More than
  ``max_sanity_violations`` in the window is an immediate breach: the
  firewall already kept the garbage off the wire, the watchdog's job is
  to stop paying for it.
- **p99 latency ratio** — canary windowed p99 over production windowed
  p99 (both from the same histogram family, measured on comparable
  traffic by construction of the hash router). Ratio >=
  ``max_p99_latency_ratio`` with ``min_latency_samples`` on each stream
  is a breach.

Determinism: verdicts are pure functions of the window's metric deltas
(:func:`SloPolicy.verdict`), so a seeded traffic replay reproduces the
same abort at the same poll — the property the canary chaos acceptance
(``chaos/canary.py``) pins.

Every verdict additionally ships its evidence: the tracer's in-process
flight recorder (``obs/tracing.py`` — the ring buffer of completed
sampled request traces) is dumped to the store under ``obs/flightrec/``
at each abort AND promote, so an auto-rollback arrives with the very
requests that convicted the canary (firewall-fallback child spans
included) and an auto-promote with the healthy window that acquitted
it. The dump key lands in the published watchdog state (``/healthz``
``watchdog.flight_record``) and the SLO runbook starts from it
(docs/RESILIENCE.md §9). A dump failure is logged and swallowed — the
CAS verdict must never block on evidence I/O.

Metrics: ``bodywork_tpu_slo_watchdog_state`` (0 idle / 1 watching / 2
breached), ``bodywork_tpu_slo_burn_rate_ratio``,
``bodywork_tpu_slo_p99_latency_ratio``,
``bodywork_tpu_slo_breaches_total{reason}``,
``bodywork_tpu_slo_canary_promotions_total``,
``bodywork_tpu_flight_record_dumps_total{verdict}``
(docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import dataclasses
import math

from bodywork_tpu.utils.logging import get_logger

log = get_logger("ops.slo")

__all__ = [
    "SERVICE_LATENCY_METRIC",
    "SERVICE_REQUESTS_METRIC",
    "SloPolicy",
    "SloWatchdog",
    "histogram_quantile",
    "policy_from_env",
    "serve_window_snapshot",
    "serve_window_delta",
]

#: bodywork_tpu_slo_watchdog_state encoding
STATE_IDLE, STATE_WATCHING, STATE_BREACHED = 0.0, 1.0, 2.0

#: metric families the watchdog reads (written by serve.app while a
#: canary is live) — one place, so the reader and writer cannot drift
REQUESTS_METRIC = "bodywork_tpu_serve_model_requests_total"
ERRORS_METRIC = "bodywork_tpu_serve_model_errors_total"
LATENCY_METRIC = "bodywork_tpu_serve_model_latency_seconds"
VIOLATIONS_METRIC = "bodywork_tpu_serve_sanity_violations_total"

#: families the CONFIG guard reads (:func:`serve_window_snapshot`).
#: Deliberately NOT the per-stream families above: those are observed
#: only while a model canary is live (zero hot-path cost otherwise),
#: but a config change affects every request, always — so its guard
#: reads the always-on whole-service counters
SERVICE_REQUESTS_METRIC = "bodywork_tpu_http_requests_total"
SERVICE_LATENCY_METRIC = "bodywork_tpu_scoring_latency_seconds"


@dataclasses.dataclass
class SloPolicy:
    """The watchdog's knobs. Defaults are sized for the coalesced
    serving regime: a 200-request window judges a canary within seconds
    under even light production traffic, while ``min_requests`` keeps a
    handful of unlucky first requests from condemning it."""

    #: sliding evaluation window, in canary requests
    window_requests: int = 200
    #: canary requests observed before error/latency verdicts may fire
    min_requests: int = 25
    #: the error budget: tolerated windowed error rate on the canary
    max_error_rate: float = 0.02
    #: breach when windowed error rate >= threshold x budget
    burn_rate_threshold: float = 1.0
    #: breach when canary windowed p99 >= this multiple of production's
    max_p99_latency_ratio: float = 3.0
    #: latency samples required on EACH stream before the ratio fires.
    #: Calibrated live: nearest-rank p99 over a few dozen samples IS the
    #: window max, and one GIL-contention outlier on a loaded box read
    #: as a 4x "regression" — 100 samples puts p99 at the 99th value,
    #: not the worst
    min_latency_samples: int = 100
    #: consecutive polls the latency verdict must persist before it
    #: aborts: a one-poll spike is scheduling noise, a real latency
    #: regression is still there next poll (sanity and error-budget
    #: verdicts stay immediate — they are counts, not tail estimates)
    latency_breach_polls: int = 2
    #: sanity violations tolerated per window (0: any violation aborts)
    max_sanity_violations: int = 0
    #: canary requests a healthy canary must survive to auto-promote
    promote_after_requests: int = 200

    def validate(self) -> None:
        if self.window_requests < 1:
            raise ValueError("window_requests must be >= 1")
        if self.min_requests < 1:
            raise ValueError("min_requests must be >= 1")
        if not 0.0 < self.max_error_rate <= 1.0:
            raise ValueError("max_error_rate must be in (0, 1]")
        if self.burn_rate_threshold <= 0.0:
            raise ValueError("burn_rate_threshold must be > 0")
        if self.max_p99_latency_ratio <= 0.0:
            raise ValueError("max_p99_latency_ratio must be > 0")
        if self.min_latency_samples < 1:
            raise ValueError("min_latency_samples must be >= 1")
        if self.latency_breach_polls < 1:
            raise ValueError("latency_breach_polls must be >= 1")
        if self.max_sanity_violations < 0:
            raise ValueError("max_sanity_violations must be >= 0")
        if self.promote_after_requests < 1:
            raise ValueError("promote_after_requests must be >= 1")

    def verdict(self, window: dict) -> str | None:
        """The breach decision for one evaluation window — a pure
        function of the window's deltas (no clocks, no RNG), so seeded
        replays reproduce the same abort. ``window`` carries
        ``requests``, ``errors``, ``violations``, ``canary_p99_s``,
        ``production_p99_s``, ``canary_latency_samples``,
        ``production_latency_samples``. Returns the breach reason
        (``"sanity"`` | ``"error_budget"`` | ``"latency"``) or None."""
        if window.get("violations", 0) > self.max_sanity_violations:
            return "sanity"
        requests = window.get("requests", 0)
        if requests >= self.min_requests:
            error_rate = window.get("errors", 0) / max(requests, 1)
            if error_rate / self.max_error_rate >= self.burn_rate_threshold:
                return "error_budget"
        c_p99 = window.get("canary_p99_s")
        p_p99 = window.get("production_p99_s")
        if (
            c_p99 is not None
            and p_p99 is not None
            and p_p99 > 0.0
            and window.get("canary_latency_samples", 0)
            >= self.min_latency_samples
            and window.get("production_latency_samples", 0)
            >= self.min_latency_samples
            and c_p99 / p_p99 >= self.max_p99_latency_ratio
        ):
            return "latency"
        return None


def policy_from_env() -> SloPolicy:
    """The deployed watchdog knobs from the pod environment — the k8s
    serve Deployment materialises them as ``BODYWORK_TPU_SLO_*`` env
    vars (``pipeline/k8s.py``) so an operator retunes the breach
    thresholds with a ``kubectl set env``, no image rebuild. Malformed
    values are ignored with a warning (the same contract as the serving
    engine knobs): a typo degrades to the default, never crashes the
    serving pod."""
    import os

    policy = SloPolicy()
    for env_name, field, cast, floor in (
        ("BODYWORK_TPU_SLO_WINDOW_REQUESTS", "window_requests", int, 1),
        ("BODYWORK_TPU_SLO_MIN_REQUESTS", "min_requests", int, 1),
        ("BODYWORK_TPU_SLO_MAX_ERROR_RATE", "max_error_rate", float, None),
        (
            "BODYWORK_TPU_SLO_MAX_P99_RATIO",
            "max_p99_latency_ratio", float, None,
        ),
        (
            "BODYWORK_TPU_SLO_MAX_SANITY_VIOLATIONS",
            "max_sanity_violations", int, 0,
        ),
        (
            "BODYWORK_TPU_SLO_PROMOTE_AFTER_REQUESTS",
            "promote_after_requests", int, 1,
        ),
    ):
        raw = os.environ.get(env_name, "").strip()
        if not raw:
            continue
        try:
            value = cast(raw)
            # floor None = strictly-positive float; else integer floor
            if (floor is None and value <= 0.0) or (
                floor is not None and value < floor
            ):
                raise ValueError(raw)
        except ValueError:
            log.warning(f"ignoring {env_name}={raw!r} (malformed or out of range)")
            continue
        # per-knob degrade contract: validate the FULL field range here,
        # so one out-of-range value (e.g. max_error_rate=1.5) reverts
        # only ITS field — the operator's other overrides must survive
        previous = getattr(policy, field)
        setattr(policy, field, value)
        try:
            policy.validate()
        except ValueError as exc:
            log.warning(f"ignoring {env_name}={raw!r} ({exc})")
            setattr(policy, field, previous)
    return policy


def histogram_quantile(bounds, bucket_counts, q: float) -> float | None:
    """Nearest-rank quantile over fixed-bucket histogram counts
    (``bucket_counts`` has ``len(bounds) + 1`` entries, the last being
    +Inf). Returns the upper bound of the bucket holding the target
    rank — the standard Prometheus-style conservative estimate — or
    None on an empty window."""
    total = sum(bucket_counts)
    if total <= 0:
        return None
    target = max(1, math.ceil(q * total))
    cumulative = 0
    for bound, n in zip(list(bounds) + [math.inf], bucket_counts):
        cumulative += n
        if cumulative >= target:
            return float(bound)
    return math.inf


def _sum_counter(name: str, **labels) -> float:
    """Sum a counter's samples whose labels are a superset match of
    ``labels`` (the violations counter carries an extra ``reason``
    label the watchdog aggregates over)."""
    from bodywork_tpu.obs import get_registry

    metric = get_registry().get(name)
    if metric is None:
        return 0.0
    total = 0.0
    for sample in metric.snapshot_samples():
        if all(sample["labels"].get(k) == v for k, v in labels.items()):
            total += sample["value"]
    return total


def _hist_buckets(name: str, **labels):
    """``(bounds, bucket_counts, count)`` summed over matching samples;
    bucket_counts has the +Inf slot appended."""
    from bodywork_tpu.obs import get_registry

    metric = get_registry().get(name)
    if metric is None:
        return (), [], 0
    bounds = list(getattr(metric, "buckets", ()))
    counts = [0] * (len(bounds) + 1)
    total = 0
    for sample in metric.snapshot_samples():
        if all(sample["labels"].get(k) == v for k, v in labels.items()):
            for i, n in enumerate(sample["buckets"]):
                counts[i] += n
            total += sample["count"]
    return bounds, counts, total


def serve_window_snapshot() -> dict:
    """Cumulative WHOLE-SERVICE serving counters: scoring requests,
    errors, and the success-latency histogram. The watchdog above
    judges one canary STREAM against another through the stream
    families (which only flow while a canary is live); a config change
    (tuned knobs going live, :mod:`bodywork_tpu.tune.online`) affects
    every request, always, so its guard reads the always-on families:
    ``bodywork_tpu_http_requests_total`` over the scoring routes and
    ``bodywork_tpu_scoring_latency_seconds``. An error here is a 5xx
    OR a 429 — a config that sheds traffic the previous config served
    (an absurd ``max_pending``) is exactly as reverted as one that
    crashes requests, and it leaves no latency samples to catch it
    otherwise."""
    from bodywork_tpu.obs import get_registry

    requests = errors = 0.0
    metric = get_registry().get(SERVICE_REQUESTS_METRIC)
    if metric is not None:
        for sample in metric.snapshot_samples():
            labels = sample["labels"]
            if not labels.get("route", "").startswith("/score"):
                continue
            requests += sample["value"]
            status = labels.get("status", "")
            if status == "429" or status.startswith("5"):
                errors += sample["value"]
    bounds, buckets, count = _hist_buckets(SERVICE_LATENCY_METRIC)
    return {
        "requests": requests,
        "errors": errors,
        "bounds": bounds,
        "buckets": buckets,
        "count": count,
    }


def serve_window_delta(base: dict, now: dict) -> dict:
    """The service-wide window between two :func:`serve_window_snapshot`
    calls — a pure function of the two snapshots (no clocks, no RNG),
    the same determinism contract as :meth:`SloPolicy.verdict`. Returns
    ``requests``, ``errors``, ``error_rate``, ``p99_s`` (None on an
    empty window), and ``latency_samples``."""
    if len(base.get("buckets", [])) == len(now["buckets"]):
        delta_buckets = [
            b - a for a, b in zip(base["buckets"], now["buckets"])
        ]
    else:
        # the histogram family first appeared mid-window: the base has
        # no buckets to subtract, the cumulative counts ARE the delta
        delta_buckets = list(now["buckets"])
    requests = int(now["requests"] - base.get("requests", 0))
    errors = int(now["errors"] - base.get("errors", 0))
    return {
        "requests": requests,
        "errors": errors,
        "error_rate": errors / max(requests, 1),
        "p99_s": histogram_quantile(now["bounds"], delta_buckets, 0.99),
        "latency_samples": int(now["count"] - base.get("count", 0)),
    }


class SloWatchdog:
    """Watches the live canary from inside the reload-watcher loop.

    ``poll()`` is called once per watcher cycle (and directly by tests /
    the chaos acceptance): it snapshots the stream metrics, evaluates
    the policy over the sliding window, and applies the verdict through
    the registry — abort and promote are each ONE alias CAS. The
    watchdog holds no lock against the request path: it reads counters
    the serving threads write, and its registry mutations race other
    watchdogs safely (the CAS loser finds the slot already cleared)."""

    def __init__(self, store, apps, policy: SloPolicy | None = None,
                 registry=None):
        from bodywork_tpu.obs import get_registry
        from bodywork_tpu.registry import ModelRegistry

        self.store = store
        self.apps = list(apps) if isinstance(apps, (list, tuple)) else [apps]
        self.policy = policy or SloPolicy()
        self.policy.validate()
        self.manager = registry or ModelRegistry(store)
        #: the canary the current window belongs to + its baseline
        self._canary_key: str | None = None
        self._snapshots: list[dict] = []
        #: the canary stream's request count when this canary appeared —
        #: total-exposure floor for the auto-promote decision
        self._exposure_floor: float = 0.0
        #: consecutive polls the latency verdict has held (see
        #: SloPolicy.latency_breach_polls)
        self._latency_streak: int = 0
        self._last_state: dict = {"state": "idle"}
        reg = get_registry()
        self._g_state = reg.gauge(
            "bodywork_tpu_slo_watchdog_state",
            "SLO watchdog: 0=idle (no canary), 1=watching, 2=breached "
            "(abort applied this poll)",
            aggregate="max",
        )
        self._g_burn = reg.gauge(
            "bodywork_tpu_slo_burn_rate_ratio",
            "Canary windowed error rate over the error budget "
            "(>= threshold aborts)",
            aggregate="max",
        )
        self._g_p99_ratio = reg.gauge(
            "bodywork_tpu_slo_p99_latency_ratio",
            "Canary windowed p99 latency over production's",
            aggregate="max",
        )
        self._m_breaches = reg.counter(
            "bodywork_tpu_slo_breaches_total",
            "Canary SLO breaches by reason "
            "(sanity|error_budget|latency) — each one auto-aborted",
        )
        self._m_promotions = reg.counter(
            "bodywork_tpu_slo_canary_promotions_total",
            "Canaries auto-promoted after surviving their window healthy",
        )
        self._m_dumps = reg.counter(
            "bodywork_tpu_flight_record_dumps_total",
            "Flight-recorder dumps written to obs/flightrec/ at watchdog "
            "verdicts, by verdict (abort|promote)",
        )
        self._g_state.set(STATE_IDLE)

    # -- state -------------------------------------------------------------

    def state(self) -> dict:
        """The /healthz watchdog block (also pushed onto each app's
        ``slo_state`` every poll)."""
        return dict(self._last_state)

    def _publish(self, state: dict) -> None:
        self._last_state = state
        for app in self.apps:
            app.slo_state = dict(state)

    # -- metric snapshots --------------------------------------------------

    def _snapshot(self, canary_key: str, production_key: str) -> dict:
        c_bounds, c_buckets, c_count = _hist_buckets(
            LATENCY_METRIC, model_key=canary_key, stream="canary"
        )
        p_bounds, p_buckets, p_count = _hist_buckets(
            LATENCY_METRIC, model_key=production_key, stream="production"
        )
        return {
            # the baseline the production-stream numbers belong to: a
            # mid-canary production change (gate promote/rollback keeps
            # the slot live) must restart the window, or deltas would
            # subtract the OLD key's cumulative counts from the new
            # key's
            "production_key": production_key,
            "requests": _sum_counter(
                REQUESTS_METRIC, model_key=canary_key, stream="canary"
            ),
            "errors": _sum_counter(
                ERRORS_METRIC, model_key=canary_key, stream="canary"
            ),
            "violations": _sum_counter(
                VIOLATIONS_METRIC, model_key=canary_key, stream="canary"
            ),
            "canary_bounds": c_bounds,
            "canary_buckets": c_buckets,
            "canary_count": c_count,
            "production_bounds": p_bounds,
            "production_buckets": p_buckets,
            "production_count": p_count,
        }

    @staticmethod
    def _window_deltas(base: dict, now: dict) -> dict:
        """The sliding window's deltas between two snapshots — what the
        pure :meth:`SloPolicy.verdict` consumes."""
        canary_delta = [
            b - a for a, b in zip(base["canary_buckets"], now["canary_buckets"])
        ]
        production_delta = [
            b - a
            for a, b in zip(
                base["production_buckets"], now["production_buckets"]
            )
        ]
        return {
            "requests": int(now["requests"] - base["requests"]),
            "errors": int(now["errors"] - base["errors"]),
            "violations": int(now["violations"] - base["violations"]),
            "canary_p99_s": histogram_quantile(
                now["canary_bounds"], canary_delta, 0.99
            ),
            "production_p99_s": histogram_quantile(
                now["production_bounds"], production_delta, 0.99
            ),
            "canary_latency_samples": int(
                now["canary_count"] - base["canary_count"]
            ),
            "production_latency_samples": int(
                now["production_count"] - base["production_count"]
            ),
        }

    # -- the loop ----------------------------------------------------------

    def poll(self) -> str | None:
        """One watchdog cycle. Returns the action applied this poll:
        ``"abort"``, ``"promote"``, or None (idle/still watching).
        Exceptions never escape to the caller's loop beyond what the
        registry raises on a genuinely broken store."""
        app = self.apps[0]
        canary_key = app.canary_key
        if canary_key is None:
            if self._canary_key is not None:
                self._canary_key = None
                self._snapshots = []
            self._g_state.set(STATE_IDLE)
            self._publish({"state": "idle"})
            return None
        production_key = app.model_key or "unknown"
        snap = self._snapshot(canary_key, production_key)
        if canary_key != self._canary_key:
            # a new canary: this snapshot is the window's floor
            self._canary_key = canary_key
            self._snapshots = [snap]
            self._exposure_floor = snap["requests"]
            self._latency_streak = 0
            self._g_state.set(STATE_WATCHING)
            self._publish({
                "state": "watching", "canary_key": canary_key,
                "window": {"requests": 0},
            })
            return None
        if self._snapshots[-1].get("production_key") != production_key:
            # production moved under a live canary (gate promote /
            # rollback preserves the slot): the old snapshots' production
            # stream belongs to a different key — restart the breach
            # window on the new baseline (exposure keeps accumulating:
            # the canary-stream counters are unaffected). The append
            # below rebuilds the floor, so this poll's deltas are zero.
            log.info(
                "production baseline changed mid-canary "
                f"({self._snapshots[-1].get('production_key')} -> "
                f"{production_key}); restarting the breach window"
            )
            self._snapshots = []
            self._latency_streak = 0
        self._snapshots.append(snap)
        # slide: drop leading snapshots once the NEXT one still spans >=
        # window_requests — the base stays the oldest snapshot within
        # (or just beyond) the window
        while (
            len(self._snapshots) >= 2
            and snap["requests"] - self._snapshots[1]["requests"]
            >= self.policy.window_requests
        ):
            self._snapshots.pop(0)
        window = self._window_deltas(self._snapshots[0], snap)
        burn = (
            (window["errors"] / max(window["requests"], 1))
            / self.policy.max_error_rate
        )
        p99_ratio = None
        if (
            window["canary_p99_s"] is not None
            and window["production_p99_s"]
            and window["production_p99_s"] > 0.0
        ):
            p99_ratio = window["canary_p99_s"] / window["production_p99_s"]
        self._g_burn.set(burn)
        if p99_ratio is not None:
            self._g_p99_ratio.set(p99_ratio)
        reason = self.policy.verdict(window)
        state = {
            "state": "watching",
            "canary_key": canary_key,
            "window": {
                "requests": window["requests"],
                "errors": window["errors"],
                "violations": window["violations"],
                "burn_rate": round(burn, 6),
                "p99_ratio": (
                    round(p99_ratio, 6) if p99_ratio is not None else None
                ),
            },
        }
        breach_pending = False
        if reason == "latency":
            # a tail-estimate verdict must PERSIST before it aborts: one
            # poll's p99 spike is scheduling noise on a loaded box, a
            # real regression is still breaching next poll
            self._latency_streak += 1
            if self._latency_streak >= self.policy.latency_breach_polls:
                return self._abort(canary_key, reason, state, window)
            state["window"]["latency_breach_streak"] = self._latency_streak
            breach_pending = True  # mid-streak: promotion must wait too
            reason = None
        else:
            self._latency_streak = 0
        if reason is not None:
            return self._abort(canary_key, reason, state, window)
        # auto-promote reads TOTAL exposure since this canary appeared
        # (the sliding window above is for breach detection only): a
        # canary promotes once promote_after_requests landed on it with
        # no breach verdict outstanding — a mid-streak latency verdict
        # IS outstanding, so the promote defers to the next poll's
        # abort-or-clear decision
        exposure = int(snap["requests"] - self._exposure_floor)
        state["window"]["exposure"] = exposure
        if not breach_pending and exposure >= self.policy.promote_after_requests:
            return self._promote(canary_key, state)
        self._publish(state)
        return None

    def _dump_flight_record(self, verdict: str, reason: str,
                            canary_key: str, window: dict | None) -> str | None:
        """Persist the tracer's flight recorder at a verdict — each
        auto-rollback (and promote) ships the sampled request traces
        that decided it. Best-effort by design: evidence I/O must never
        block or fail the one-CAS verdict itself."""
        from bodywork_tpu.obs.tracing import (
            flight_record_doc,
            get_tracer,
            write_flight_record,
        )

        tracer = get_tracer()
        if not tracer.enabled:
            return None
        try:
            doc = flight_record_doc(
                tracer.recorder.snapshot(),
                verdict=verdict,
                reason=reason,
                canary_key=canary_key,
                production_key=self.apps[0].model_key,
                window=window,
                sampling={
                    "seed": tracer.seed,
                    "fraction": tracer.sample_fraction,
                },
            )
            key = write_flight_record(self.store, doc)
        except Exception as exc:  # noqa: BLE001 — evidence, not verdict
            log.error(f"flight-record dump failed: {exc!r}")
            return None
        self._m_dumps.inc(verdict=verdict)
        log.info(
            f"flight record: {doc['n_traces']} trace(s) -> {key} "
            f"({verdict}: {reason})"
        )
        return key

    def _abort(self, canary_key: str, reason: str, state: dict,
               window: dict) -> str:
        """The breach action: ONE CAS retiring the canary + immediate
        in-process routing clear. Idempotent against concurrent
        watchdogs: a lost race means another worker already applied it."""
        from bodywork_tpu.registry import PromotionConflict

        detail = (
            f"slo breach: {reason} "
            f"(requests={window['requests']}, errors={window['errors']}, "
            f"violations={window['violations']})"
        )
        log.error(f"canary {canary_key} BREACHED — auto-aborting: {detail}")
        try:
            self.manager.canary_abort(reason=detail)
        except PromotionConflict:
            log.warning("canary abort lost the alias race (already applied)")
        for app in self.apps:
            app.clear_canary()
        dump_key = self._dump_flight_record(
            "abort", reason, canary_key, state.get("window")
        )
        self._m_breaches.inc(reason=reason)
        self._g_state.set(STATE_BREACHED)
        self._canary_key = None
        self._snapshots = []
        self._publish({
            **state, "state": "breached", "verdict": reason,
            "detail": detail, "flight_record": dump_key,
        })
        return "abort"

    def _promote(self, canary_key: str, state: dict) -> str | None:
        """The healthy-window action: one CAS graduating the canary,
        then the already-warm bundle takes 100% in-process."""
        from bodywork_tpu.registry import PromotionConflict, RegistryError

        log.info(
            f"canary {canary_key} survived its SLO window healthy — "
            "auto-promoting"
        )
        try:
            self.manager.canary_promote()
        except PromotionConflict:
            log.warning(
                "canary promotion lost the alias race; leaving routing "
                "for the next poll to reconcile"
            )
            return None
        except RegistryError as exc:
            # e.g. another watchdog already promoted (slot empty)
            log.warning(f"canary promotion not applied: {exc}")
            return None
        for app in self.apps:
            app.promote_canary_bundle()
        dump_key = self._dump_flight_record(
            "promote", "healthy window survived", canary_key,
            state.get("window"),
        )
        self._m_promotions.inc()
        self._g_state.set(STATE_IDLE)
        self._canary_key = None
        self._snapshots = []
        self._publish({
            **state, "state": "promoted", "verdict": "healthy",
            "flight_record": dump_key,
        })
        return "promote"
