from bodywork_tpu.parallel.mesh import (
    make_mesh,
    multihost_init,
    multihost_shutdown,
    split_devices,
)
from bodywork_tpu.parallel.sharding import (
    DataParallelPredictor,
    ShardedMLPPredictor,
    make_data_parallel_predict,
    mlp_param_sharding,
)
from bodywork_tpu.parallel.train_step import train_mlp_sharded

__all__ = [
    "make_mesh",
    "multihost_init",
    "multihost_shutdown",
    "split_devices",
    "DataParallelPredictor",
    "ShardedMLPPredictor",
    "make_data_parallel_predict",
    "mlp_param_sharding",
    "train_mlp_sharded",
]
