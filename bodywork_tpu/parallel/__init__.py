from bodywork_tpu.parallel.mesh import (
    make_mesh,
    multihost_init,
    split_devices,
)
from bodywork_tpu.parallel.sharding import (
    DataParallelPredictor,
    make_data_parallel_predict,
    mlp_param_sharding,
)
from bodywork_tpu.parallel.train_step import (
    ShardedTrainState,
    make_sharded_train_step,
    train_mlp_sharded,
)

__all__ = [
    "make_mesh",
    "multihost_init",
    "split_devices",
    "DataParallelPredictor",
    "make_data_parallel_predict",
    "mlp_param_sharding",
    "ShardedTrainState",
    "make_sharded_train_step",
    "train_mlp_sharded",
]
