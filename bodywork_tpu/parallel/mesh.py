"""Device-mesh utilities — the framework's distributed-communication layer.

The reference has **no** distributed backend (SURVEY.md §5: no NCCL/MPI/
Gloo; inter-stage transport is S3 + HTTP). The TPU-native replacement is
``jax.sharding.Mesh`` over a v5e slice: computations are jitted with named
shardings and XLA compiles the collectives (all-reduce/all-gather/…) onto
ICI. Multi-host pools extend the same mesh over DCN via
``jax.distributed.initialize`` — no hand-written communication anywhere.

Axis convention: ``data`` (batch parallel) × ``model`` (tensor parallel).
"""
from __future__ import annotations

import os

import jax
from jax.sharding import Mesh

from bodywork_tpu.utils.logging import get_logger

log = get_logger("parallel.mesh")


def make_mesh(
    data: int | None = None,
    model: int = 1,
    devices=None,
) -> Mesh:
    """A ``(data, model)`` mesh over the available devices.

    Defaults: all devices on the ``data`` axis (pure DP) — the right shape
    for batched scoring on a v5e-4 (BASELINE.json config 4). ``model > 1``
    splits off a tensor-parallel axis (e.g. ``data=4, model=2`` on v5e-8).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        data = n // model
    if data * model != n:
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {n}"
        )
    import numpy as np

    dev_array = np.asarray(devices).reshape(data, model)
    mesh = Mesh(dev_array, axis_names=("data", "model"))
    log.info(f"mesh data={data} model={model} over {n} {devices[0].platform} device(s)")
    _record_mesh_info(data, model)
    return mesh


def _record_mesh_info(data: int, model: int) -> None:
    """Publish the mesh topology on the metrics registry: one ``_info``
    gauge sample per (data, model) shape built this process, so a scrape
    of any training or serving pod answers "what mesh is this process
    actually running?" without log archaeology."""
    from bodywork_tpu.obs import get_registry

    get_registry().gauge(
        "bodywork_tpu_parallel_mesh_info",
        "Device-mesh topology in use: one sample per (data, model) mesh "
        "shape built by this process (value is always 1)",
    ).set(1.0, data=str(data), model=str(model))


def split_devices(n_groups: int, devices=None) -> list[list]:
    """Partition devices into disjoint equal groups.

    Device-level isolation for concurrent pipelines sharing one pool —
    BASELINE.json config 5 (two A/B train+serve pipelines on a v5e-8).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % n_groups != 0:
        raise ValueError(f"cannot split {n} devices into {n_groups} equal groups")
    per = n // n_groups
    return [devices[i * per : (i + 1) * per] for i in range(n_groups)]


def _distributed_initialized() -> bool:
    """Whether this process already joined a ``jax.distributed`` cluster —
    version-portable. Newer JAX exposes ``jax.distributed.is_initialized``;
    older releases (e.g. 0.4.37, the pinned toolchain) only carry the
    global client state object, so probe that with ``getattr`` fallbacks
    rather than crash every worker on an ``AttributeError`` before the
    cluster can even form."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    if state is None:
        try:
            from jax._src import distributed as _dist
        except ImportError:  # pragma: no cover - future-proofing only
            return False
        state = getattr(_dist, "global_state", None)
    return getattr(state, "client", None) is not None


def _arm_cpu_collectives() -> None:
    """Give a multi-process CPU cluster a cross-process collectives
    backend BEFORE the CPU client is created. XLA:CPU implements
    cross-process computations only through a pluggable collectives
    layer (Gloo, in the standard jaxlib wheels); without it every
    collective dies with "Multiprocess computations aren't implemented
    on the CPU backend". TPU/GPU backends carry their own collectives
    (ICI/DCN, NCCL) — the flag only governs the CPU client, so arming
    it unconditionally is safe there too. Best-effort: a JAX without
    the flag (or with backends already live) keeps whatever it has."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as exc:  # unknown flag / already-initialized backend
        log.warning(f"could not arm CPU gloo collectives: {exc!r}")


def multihost_shutdown() -> bool:
    """Leave the ``jax.distributed`` cluster if this process joined one
    (the paired exit for :func:`multihost_init`, so a finishing
    Indexed-Job worker releases its coordinator connection instead of
    holding it until process teardown). Idempotent: a no-op (False)
    when the process never initialized or already shut down."""
    if not _distributed_initialized():
        return False
    jax.distributed.shutdown()
    log.info("left the distributed cluster")
    return True


def multihost_init() -> bool:
    """Join a multi-host JAX cluster if the standard coordinator env vars are
    present (GKE TPU pod slices set these); no-op on a single host.

    When the process topology is also in the env — ``NUM_PROCESSES`` plus a
    process id (``PROCESS_ID``, or the ``JOB_COMPLETION_INDEX`` Kubernetes
    injects into every Indexed-Job pod, which is exactly what the emitted
    multi-host manifests are) — it is passed explicitly, so generic
    clusters work too, not only environments JAX's cluster auto-detection
    recognises. After this, ``jax.devices()`` spans all hosts and meshes
    built on it compile collectives over ICI within a slice and DCN across
    slices.
    """
    addr = os.environ.get("COORDINATOR_ADDRESS") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not addr:
        return False
    # idempotent: the daily retrain loop calls this every day, but
    # jax.distributed.initialize raises RuntimeError on a second call
    if _distributed_initialized():
        return True
    _arm_cpu_collectives()
    n_proc = os.environ.get("NUM_PROCESSES") or os.environ.get(
        "JAX_NUM_PROCESSES"
    )
    proc_id = (
        os.environ.get("PROCESS_ID")
        or os.environ.get("JAX_PROCESS_ID")
        or os.environ.get("JOB_COMPLETION_INDEX")
    )
    if n_proc is not None and proc_id is not None:
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(n_proc),
            process_id=int(proc_id),
        )
    else:
        jax.distributed.initialize()  # cluster auto-detection (GKE TPU)
    log.info(
        f"joined distributed cluster: process {jax.process_index()} / "
        f"{jax.process_count()}, {jax.device_count()} global devices"
    )
    return True
