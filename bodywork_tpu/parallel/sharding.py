"""Sharded inference: data-parallel batched scoring over a device mesh.

BASELINE.json config 4: "batched scoring service: 1k-row predict() requests,
over v5e-4". The reference scales scoring with 2 HTTP replicas
(``bodywork.yaml:40``); here a single service process shards each batch
across the mesh's ``data`` axis — params replicated in every chip's HBM,
rows split by NamedSharding, XLA compiling any cross-chip traffic onto ICI.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.serve.predictor import PaddedPredictor
from bodywork_tpu.utils.logging import get_logger

log = get_logger("parallel.sharding")


def mlp_param_sharding(mesh: Mesh, params: dict) -> dict:
    """Tensor-parallel PartitionSpecs for the MLP params pytree.

    Megatron-style dense sharding: first hidden layer column-parallel
    (``P(None, "model")``), middle layers' inputs row-parallel so XLA
    inserts one all-reduce per boundary, output layer replicated (its width
    is 1). The scaler is replicated.
    """

    def spec_for_layer(i: int, n_layers: int, leaf: str):
        if i == 0:
            # column parallel: out-features split
            return P(None, "model") if leaf == "w" else P("model")
        if i < n_layers - 1:
            # row parallel on input dim; output replicated via psum
            return P("model", None) if leaf == "w" else P()
        return P()  # final (tiny) layer replicated

    n_layers = len(params["net"]["layers"])
    layer_specs = [
        {"w": spec_for_layer(i, n_layers, "w"), "b": spec_for_layer(i, n_layers, "b")}
        for i in range(n_layers)
    ]
    scaler_specs = {k: P() for k in params["scaler"]}
    return {"net": {"layers": layer_specs}, "scaler": scaler_specs}


def _named(mesh: Mesh, tree):
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), tree,
                        is_leaf=lambda x: isinstance(x, P))


def make_data_parallel_apply(model: Regressor, mesh: Mesh):
    """Build the sharded apply: params replicated into each device's HBM
    once, rows split over the mesh ``data`` axis by NamedSharding. Returns
    ``(dispatch, n_data)`` where ``dispatch(X)`` pads the batch to a
    multiple of the data-axis size and returns the UN-materialised device
    result (no device->host transfer)."""
    apply_fn = type(model).apply
    if apply_fn is None:
        raise TypeError(
            f"{type(model).__name__} does not define an apply function"
        )

    replicated = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("data", None))
    out_sharded = NamedSharding(mesh, P("data"))
    params = jax.device_put(model.params, jax.tree.map(lambda _: replicated, model.params))

    sharded_apply = jax.jit(
        apply_fn,
        in_shardings=(jax.tree.map(lambda _: replicated, model.params), row_sharded),
        out_shardings=out_sharded,
    )
    n_data = mesh.shape["data"]

    def dispatch(X: np.ndarray):
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        pad = (-X.shape[0]) % n_data
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        return sharded_apply(params, X)

    return dispatch, n_data


def make_data_parallel_predict(model: Regressor, mesh: Mesh):
    """A predict fn sharding rows over the mesh ``data`` axis (materialises
    the result on host; see :func:`make_data_parallel_apply` for the
    dispatch-only path)."""
    dispatch, _ = make_data_parallel_apply(model, mesh)

    def predict(X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        return np.asarray(dispatch(X))[: X.shape[0]]

    return predict


class DataParallelPredictor(PaddedPredictor):
    """A :class:`PaddedPredictor` whose bucket execution shards rows across
    the mesh ``data`` axis — the serving path for BASELINE.json config 4.
    Reuses the bucket/pad/chunk logic from the base class; only the
    padded-batch execution differs."""

    def __init__(self, model: Regressor, mesh: Mesh,
                 buckets: tuple[int, ...] | None = None):
        if buckets is None:
            buckets = (64, 512, 4096)
        n_data = mesh.shape["data"]
        # round each bucket up to a multiple of the data-axis size so every
        # padded batch splits evenly across the mesh (stable XLA shapes)
        buckets = tuple(sorted({b + (-b) % n_data for b in buckets}))
        super().__init__(model, buckets)
        self.mesh = mesh
        self._sharded_dispatch, _ = make_data_parallel_apply(model, mesh)

    def _aot_fn(self):
        # mesh-sharded dispatch owns its own jit cache; the single-device
        # AOT executable cache does not apply
        return None

    def _dispatch_padded(self, Xp: np.ndarray):
        # the *sharded* program, un-materialised: warmup compiles and
        # enqueues without paying a device->host transfer; the base
        # _predict_padded materialises this result for real requests
        return self._sharded_dispatch(Xp)

    def _warm_key_extra(self) -> tuple:
        return (
            tuple(self.mesh.shape.items()),
            tuple(d.id for d in self.mesh.devices.flat),
        )
