"""Sharded inference: data-parallel batched scoring over a device mesh.

BASELINE.json config 4: "batched scoring service: 1k-row predict() requests,
over v5e-4". The reference scales scoring with 2 HTTP replicas
(``bodywork.yaml:40``); here a single service process shards each batch
across the mesh's ``data`` axis — params replicated in every chip's HBM,
rows split by NamedSharding, XLA compiling any cross-chip traffic onto ICI.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.serve.predictor import PaddedPredictor
from bodywork_tpu.utils.logging import get_logger

log = get_logger("parallel.sharding")


def mlp_param_sharding(mesh: Mesh, params: dict) -> dict:
    """Tensor-parallel PartitionSpecs for the MLP params pytree.

    Megatron-style dense sharding: first hidden layer column-parallel
    (``P(None, "model")``), middle layers' inputs row-parallel so XLA
    inserts one all-reduce per boundary, output layer replicated (its width
    is 1). The scaler is replicated.
    """

    def spec_for_layer(i: int, n_layers: int, leaf: str):
        if i == 0:
            # column parallel: out-features split
            return P(None, "model") if leaf == "w" else P("model")
        if i < n_layers - 1:
            # row parallel on input dim; output replicated via psum
            return P("model", None) if leaf == "w" else P()
        return P()  # final (tiny) layer replicated

    n_layers = len(params["net"]["layers"])
    layer_specs = [
        {"w": spec_for_layer(i, n_layers, "w"), "b": spec_for_layer(i, n_layers, "b")}
        for i in range(n_layers)
    ]
    scaler_specs = {k: P() for k in params["scaler"]}
    return {"net": {"layers": layer_specs}, "scaler": scaler_specs}


def _named(mesh: Mesh, tree):
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), tree,
                        is_leaf=lambda x: isinstance(x, P))


def make_data_parallel_apply(model: Regressor, mesh: Mesh):
    """Build the sharded apply: params replicated into each device's HBM
    once, rows split over the mesh ``data`` axis by NamedSharding. Returns
    ``(dispatch, n_data)`` where ``dispatch(X)`` pads the batch to a
    multiple of the data-axis size and returns the UN-materialised device
    result (no device->host transfer)."""
    apply_fn = type(model).apply
    if apply_fn is None:
        raise TypeError(
            f"{type(model).__name__} does not define an apply function"
        )

    replicated = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("data", None))
    out_sharded = NamedSharding(mesh, P("data"))
    params = jax.device_put(model.params, jax.tree.map(lambda _: replicated, model.params))

    sharded_apply = jax.jit(
        apply_fn,
        in_shardings=(jax.tree.map(lambda _: replicated, model.params), row_sharded),
        out_shardings=out_sharded,
    )
    n_data = mesh.shape["data"]

    def dispatch(X: np.ndarray):
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        pad = (-X.shape[0]) % n_data
        if pad:
            X = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
        return sharded_apply(params, X)

    return dispatch, n_data


def make_data_parallel_predict(model: Regressor, mesh: Mesh):
    """A predict fn sharding rows over the mesh ``data`` axis (materialises
    the result on host; see :func:`make_data_parallel_apply` for the
    dispatch-only path)."""
    dispatch, _ = make_data_parallel_apply(model, mesh)

    def predict(X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        return np.asarray(dispatch(X))[: X.shape[0]]

    return predict


def _round_buckets_to_axis(buckets, n_data: int) -> tuple[int, ...]:
    """Round each padding bucket up to a multiple of the mesh's data-axis
    size so every padded batch splits evenly across the mesh (stable XLA
    shapes; a non-divisible batch dimension does not even lower). Shared
    by every mesh predictor so their padded-shape policies cannot
    diverge."""
    return tuple(sorted({b + (-b) % n_data for b in buckets}))


def param_partition_specs(model: Regressor, mesh: Mesh):
    """PartitionSpecs for serving a model's params over ``mesh``: the
    Megatron-style dense sharding for MLPs (:func:`mlp_param_sharding`),
    full replication for everything else (the linear model's params are
    two scalars — there is nothing to split; ``model > 1`` without an
    MLP is refused by the predictor, not silently replicated)."""
    from bodywork_tpu.models.mlp import MLPRegressor

    if isinstance(model, MLPRegressor):
        return mlp_param_sharding(mesh, model.params)
    return jax.tree.map(lambda _: P(), model.params)


class ShardedMLPPredictor(PaddedPredictor):
    """Mesh-sharded serving through the process-wide AOT executable cache.

    The serving counterpart of :func:`~bodywork_tpu.parallel.train_step.
    train_mlp_sharded`: params are placed ONCE with ``NamedSharding``
    over the ``data x model`` mesh (MLP weights Megatron-sharded on the
    ``model`` axis, everything else replicated), each padded request
    batch is sharded on the ``data`` axis, and XLA compiles whatever
    collectives the shardings imply. Unlike
    :class:`DataParallelPredictor` (per-instance jit), the programs here
    ride the same AOT :class:`~bodywork_tpu.serve.predictor.
    ExecutableCache` single-device serving uses — the lowering pins the
    leaf shardings (``_leaf_struct``) and the cache key carries the mesh
    shape + device set (:meth:`_warm_key_extra`), so a same-architecture
    same-mesh hot swap re-binds params to already-compiled executables
    (zero compiles, the config-12 acceptance bar) while two mesh shapes
    can never collide on one executable.

    Per-row results are the single-device program's rows exactly — the
    HTTP byte-identity contract tests/test_sharded_serve.py pins over
    both engines.
    """

    def __init__(self, model: Regressor, mesh: Mesh,
                 buckets: tuple[int, ...] | None = None):
        from bodywork_tpu.models.mlp import MLPRegressor
        from bodywork_tpu.serve.predictor import DEFAULT_BUCKETS

        if mesh.shape["model"] > 1 and not isinstance(model, MLPRegressor):
            raise ValueError(
                f"tensor-parallel serving (mesh model axis "
                f"{mesh.shape['model']}) requires an MLP; got {model.info}"
            )
        if buckets is None:
            buckets = DEFAULT_BUCKETS
        n_data = mesh.shape["data"]
        super().__init__(model, _round_buckets_to_axis(buckets, n_data))
        self.mesh = mesh
        specs = param_partition_specs(model, mesh)
        self._sharded_params = jax.device_put(model.params, _named(mesh, specs))
        self._x_sharding = NamedSharding(mesh, P("data", None))
        self._mesh_label = f"{n_data}x{mesh.shape['model']}"
        self._dispatch_counter = None

    # -- AOT plumbing: same cache, mesh-aware programs ----------------------
    def _exec_params(self):
        return self._sharded_params

    def _aot_ok(self) -> bool:
        # the whole params tree is mesh-placed by construction and the
        # lowering pins every leaf's NamedSharding — always AOT-safe
        # (the base-class bypass exists for MIXED host/mesh pytrees)
        return True

    def _x_struct(self, bucket: int, n_features: int):
        return jax.ShapeDtypeStruct(
            (bucket, n_features), np.float32, sharding=self._x_sharding
        )

    def _out_shardings(self):
        # keep the output row-sharded: the host fetch in _predict_padded
        # gathers shards without forcing an in-program all-gather
        return NamedSharding(self.mesh, P("data"))

    def _warm_key_extra(self) -> tuple:
        # the mesh shape AND its device set: same-shape meshes over
        # different device subsets are different programs, and two mesh
        # shapes must never share an executable
        return (
            "sharded",
            tuple(self.mesh.shape.items()),
            tuple(d.id for d in self.mesh.devices.flat),
        )

    def _dispatch_padded(self, Xp: np.ndarray):
        if self._dispatch_counter is None:
            from bodywork_tpu.obs import get_registry

            self._dispatch_counter = get_registry().counter(
                "bodywork_tpu_serve_sharded_dispatch_total",
                "Padded device dispatches executed through a mesh-sharded "
                "serving predictor, by mesh shape (data x model)",
            )
        self._dispatch_counter.inc(mesh=self._mesh_label)
        # the compiled executable's input spec carries the row sharding;
        # a host numpy batch is transferred shard-wise by the call itself
        return super()._dispatch_padded(Xp)


class DataParallelPredictor(PaddedPredictor):
    """A :class:`PaddedPredictor` whose bucket execution shards rows across
    the mesh ``data`` axis — the serving path for BASELINE.json config 4.
    Reuses the bucket/pad/chunk logic from the base class; only the
    padded-batch execution differs."""

    def __init__(self, model: Regressor, mesh: Mesh,
                 buckets: tuple[int, ...] | None = None):
        if buckets is None:
            buckets = (64, 512, 4096)
        n_data = mesh.shape["data"]
        super().__init__(model, _round_buckets_to_axis(buckets, n_data))
        self.mesh = mesh
        self._sharded_dispatch, _ = make_data_parallel_apply(model, mesh)

    def _aot_fn(self):
        # mesh-sharded dispatch owns its own jit cache; the single-device
        # AOT executable cache does not apply
        return None

    def _dispatch_padded(self, Xp: np.ndarray):
        # the *sharded* program, un-materialised: warmup compiles and
        # enqueues without paying a device->host transfer; the base
        # _predict_padded materialises this result for real requests
        return self._sharded_dispatch(Xp)

    def _warm_key_extra(self) -> tuple:
        return (
            tuple(self.mesh.shape.items()),
            tuple(d.id for d in self.mesh.devices.flat),
        )
