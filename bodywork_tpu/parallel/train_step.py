"""Sharded (dp x tp) MLP training over a device mesh.

The multi-chip training path: the minibatch is sharded over the ``data``
axis, hidden-layer weights over the ``model`` axis (Megatron-style column/
row parallel — see :func:`~bodywork_tpu.parallel.sharding.mlp_param_sharding`),
and the whole optimisation run is one jitted ``lax.scan``. Gradients are
combined by the collectives XLA derives from the shardings (a psum over
``data`` for the batch dimension, a psum over ``model`` at the row-parallel
boundary) — nothing is hand-scheduled, per the scaling-book recipe: pick a
mesh, annotate shardings, let XLA insert collectives.

The reference trains sklearn OLS on one CPU (``stage_1:105-106``); this
module is the no-parity-constraint TPU growth path (BASELINE.json configs
3-5).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bodywork_tpu.models.mlp import (
    MLPConfig,
    MLPRegressor,
    _loss,
    _masked_stats,
    init_mlp_params,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("parallel.train_step")


@partial(
    jax.jit,
    static_argnames=("cfg",),
    donate_argnums=(0, 1),
)
def _scan_train(net, opt_state, batches_x, batches_y, batches_w, cfg: MLPConfig):
    opt = optax.adam(cfg.learning_rate)

    def body(carry, batch):
        net, opt_state = carry
        xb, yb, wb = batch
        loss, grads = jax.value_and_grad(_loss)(net, xb, yb, wb)
        updates, opt_state = opt.update(grads, opt_state, net)
        net = optax.apply_updates(net, updates)
        return (net, opt_state), loss

    (net, opt_state), losses = jax.lax.scan(
        body, (net, opt_state), (batches_x, batches_y, batches_w)
    )
    return net, opt_state, losses


def train_mlp_sharded(
    X: np.ndarray,
    y: np.ndarray,
    cfg: MLPConfig,
    mesh: Mesh,
    seed: int | None = None,
    timings: dict | None = None,
) -> MLPRegressor:
    """Full dp x tp training run compiled as ONE XLA program.

    Pre-samples the whole batch schedule host-side (with-replacement, same
    scheme as the single-device path), shards it ``P(None, "data", None)``
    (steps x rows x features), and scans over steps on-device. Returns a
    fitted :class:`MLPRegressor` whose params can be checkpointed/served
    exactly like the single-device model.

    ``timings``, when given a dict, receives ``staging_s`` (host-side
    batch-schedule construction + host->device transfer — work the
    single-device path performs inside its compiled program) and
    ``scan_s`` (the blocked optimisation scan itself), so benchmarks can
    report device throughput without billing the one-time staging to it.
    """
    import time as _time
    t_start = _time.perf_counter()
    X = np.asarray(X, dtype=np.float32)
    if X.ndim == 1:
        X = X[:, None]
    y = np.asarray(y, dtype=np.float32).ravel()
    n = X.shape[0]

    key = jax.random.PRNGKey(cfg.seed if seed is None else seed)
    k_init, k_batch = jax.random.split(key)

    # standardise (full data, no padding needed here — stats on host)
    w_all = np.ones(n, dtype=np.float32)
    x_mean, x_std = jax.vmap(_masked_stats, in_axes=(1, None), out_axes=0)(
        jnp.asarray(X), jnp.asarray(w_all)
    )
    y_mean, y_std = _masked_stats(jnp.asarray(y), jnp.asarray(w_all))
    Xs = (X - np.asarray(x_mean)) / np.asarray(x_std)
    ys = (y - float(y_mean)) / float(y_std)

    # batch schedule: (steps, batch) indices sampled with replacement
    idx = jax.random.randint(k_batch, (cfg.n_steps, cfg.batch_size), 0, n)
    idx = np.asarray(idx)
    bx = Xs[idx]                      # (steps, batch, d)
    by = ys[idx]                      # (steps, batch)
    bw = np.ones_like(by)

    from bodywork_tpu.parallel.sharding import mlp_param_sharding

    sizes = (X.shape[1],) + cfg.hidden + (1,)
    net = init_mlp_params(k_init, sizes)
    specs = mlp_param_sharding(mesh, {"net": net, "scaler": {}})["net"]
    net = jax.device_put(
        net,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    opt_state = optax.adam(cfg.learning_rate).init(net)

    batch_shard = NamedSharding(mesh, P(None, "data", None))
    batch1_shard = NamedSharding(mesh, P(None, "data"))
    bx = jax.device_put(jnp.asarray(bx), batch_shard)
    by = jax.device_put(jnp.asarray(by), batch1_shard)
    bw = jax.device_put(jnp.asarray(bw), batch1_shard)
    jax.block_until_ready((bx, by, bw))
    t_staged = _time.perf_counter()

    net, opt_state, losses = _scan_train(net, opt_state, bx, by, bw, cfg)
    if timings is not None:
        jax.block_until_ready(losses)
        timings["staging_s"] = t_staged - t_start
        timings["scan_s"] = _time.perf_counter() - t_staged
    log.info(
        f"sharded train: {cfg.n_steps} steps over mesh "
        f"{dict(mesh.shape)}; final loss {float(losses[-1]):.5f}"
    )

    params = {
        "net": net,
        "scaler": {
            "x_mean": x_mean, "x_std": x_std, "y_mean": y_mean, "y_std": y_std
        },
    }
    fitted = MLPRegressor(cfg, params)
    fitted.final_loss = float(losses[-1])
    return fitted
