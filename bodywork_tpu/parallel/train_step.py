"""Sharded (dp x tp) MLP training over a device mesh.

The multi-chip training path: the minibatch is sharded over the ``data``
axis, hidden-layer weights over the ``model`` axis (Megatron-style column/
row parallel — see :func:`~bodywork_tpu.parallel.sharding.mlp_param_sharding`),
and the whole optimisation run is one jitted ``lax.scan``. Gradients are
combined by the collectives XLA derives from the shardings (a psum over
``data`` for the batch dimension, a psum over ``model`` at the row-parallel
boundary) — nothing is hand-scheduled, per the scaling-book recipe: pick a
mesh, annotate shardings, let XLA insert collectives.

The reference trains sklearn OLS on one CPU (``stage_1:105-106``); this
module is the no-parity-constraint TPU growth path (BASELINE.json configs
3-5).
"""
from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bodywork_tpu.models.mlp import (
    MLPConfig,
    MLPRegressor,
    _loss,
    _masked_stats,
    init_mlp_params,
)
from bodywork_tpu.utils.logging import get_logger
from bodywork_tpu.utils.sync import fence

log = get_logger("parallel.train_step")


@functools.lru_cache(maxsize=32)
def _sharded_train_fn(mesh: Mesh, cfg: MLPConfig):
    """The whole dp x tp optimisation run as ONE jitted ``lax.scan``, with
    per-step minibatch sampling INSIDE the compiled program (mirroring the
    single-device scheme at ``models/mlp.py`` ``_train_core``): each step
    splits the carried PRNG key, draws with-replacement indices, and a
    sharding constraint puts the index vector on the ``data`` axis, so the
    gather from the (replicated) dataset is shard-local and the batch comes
    out dp-sharded. Nothing step-count-sized ever exists host-side.

    Cached per (mesh, cfg): the jit closure captures the mesh's shardings,
    so rebuilding it per call would recompile per call."""
    idx_sharding = NamedSharding(mesh, P("data"))

    @partial(jax.jit, donate_argnums=(0, 1))
    def run(net, opt_state, Xs, ys, key):
        opt = optax.adam(cfg.learning_rate)
        wb = jnp.ones((cfg.batch_size,), Xs.dtype)

        def body(carry, _):
            net, opt_state, key = carry
            key, k_idx = jax.random.split(key)
            idx = jax.random.randint(
                k_idx, (cfg.batch_size,), 0, Xs.shape[0]
            )
            idx = jax.lax.with_sharding_constraint(idx, idx_sharding)
            xb, yb = Xs[idx], ys[idx]
            loss, grads = jax.value_and_grad(_loss)(
                net, xb, yb, wb, cfg.compute_dtype
            )
            updates, opt_state = opt.update(grads, opt_state, net)
            net = optax.apply_updates(net, updates)
            return (net, opt_state, key), loss

        (net, opt_state, _), losses = jax.lax.scan(
            body, (net, opt_state, key), None, length=cfg.n_steps
        )
        return net, opt_state, losses

    return run


def train_mlp_sharded(
    X: np.ndarray,
    y: np.ndarray,
    cfg: MLPConfig,
    mesh: Mesh,
    seed: int | None = None,
    timings: dict | None = None,
) -> MLPRegressor:
    """Full dp x tp training run compiled as ONE XLA program.

    The dataset is standardised once, replicated over the mesh (day-history
    scale data — a broadcast is one transfer and makes every per-step gather
    shard-local; a row-sharded dataset would trade that for a per-step
    all-gather), and per-step minibatches are sampled with replacement
    INSIDE the jitted scan (see :func:`_sharded_train_fn`), exactly like
    the single-device path (``models/mlp.py`` ``_train_core``). Host-side
    staging is therefore O(dataset), independent of ``n_steps``. Returns a
    fitted :class:`MLPRegressor` whose params can be checkpointed/served
    exactly like the single-device model.

    ``timings``, when given a dict, receives ``staging_s`` (host-side
    standardisation + the one dataset transfer) and ``scan_s`` (the
    blocked optimisation scan itself), so benchmarks can report device
    throughput without billing the one-time staging to it.
    """
    import time as _time
    t_start = _time.perf_counter()
    X = np.asarray(X, dtype=np.float32)
    if X.ndim == 1:
        X = X[:, None]
    y = np.asarray(y, dtype=np.float32).ravel()
    n = X.shape[0]

    key = jax.random.PRNGKey(cfg.seed if seed is None else seed)
    k_init, k_batch = jax.random.split(key)

    # standardise (full data, no padding needed here — stats on host)
    w_all = np.ones(n, dtype=np.float32)
    x_mean, x_std = jax.vmap(_masked_stats, in_axes=(1, None), out_axes=0)(
        jnp.asarray(X), jnp.asarray(w_all)
    )
    y_mean, y_std = _masked_stats(jnp.asarray(y), jnp.asarray(w_all))
    Xs = (X - np.asarray(x_mean)) / np.asarray(x_std)
    ys = (y - float(y_mean)) / float(y_std)

    from bodywork_tpu.parallel.sharding import mlp_param_sharding

    sizes = (X.shape[1],) + cfg.hidden + (1,)
    net = init_mlp_params(k_init, sizes)
    specs = mlp_param_sharding(mesh, {"net": net, "scaler": {}})["net"]
    net = jax.device_put(
        net,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    opt_state = optax.adam(cfg.learning_rate).init(net)

    # the dataset crosses to the devices ONCE, replicated; every per-step
    # gather is then shard-local (see _sharded_train_fn)
    replicated = NamedSharding(mesh, P())
    Xd = jax.device_put(Xs.astype(np.float32), replicated)
    yd = jax.device_put(ys.astype(np.float32), replicated)
    fence((Xd, yd))
    t_staged = _time.perf_counter()

    net, opt_state, losses = _sharded_train_fn(mesh, cfg)(
        net, opt_state, Xd, yd, k_batch
    )
    if timings is not None:
        fence(losses)
        timings["staging_s"] = t_staged - t_start
        timings["scan_s"] = _time.perf_counter() - t_staged
    log.info(
        f"sharded train: {cfg.n_steps} steps over mesh "
        f"{dict(mesh.shape)}; final loss {float(losses[-1]):.5f}"
    )

    params = {
        "net": net,
        "scaler": {
            "x_mean": x_mean, "x_std": x_std, "y_mean": y_mean, "y_std": y_std
        },
    }
    fitted = MLPRegressor(cfg, params)
    fitted.final_loss = float(losses[-1])
    return fitted
