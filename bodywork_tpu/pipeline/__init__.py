from bodywork_tpu.pipeline.spec import (
    PipelineSpec,
    ResourceSpec,
    StageSpec,
    default_pipeline,
    parse_dag,
)
from bodywork_tpu.pipeline.runner import DayResult, LocalRunner, StageFailure
from bodywork_tpu.pipeline.k8s import generate_manifests, write_manifests
from bodywork_tpu.pipeline.k8s_validate import (
    ManifestError,
    validate_manifest,
    validate_manifests,
)
from bodywork_tpu.pipeline.ab import (
    PipelineVariant,
    VariantResult,
    compare_report,
    run_ab_simulation,
    variants_from_model_types,
)

__all__ = [
    "PipelineVariant",
    "VariantResult",
    "compare_report",
    "run_ab_simulation",
    "variants_from_model_types",
    "PipelineSpec",
    "ResourceSpec",
    "StageSpec",
    "default_pipeline",
    "parse_dag",
    "DayResult",
    "LocalRunner",
    "StageFailure",
    "generate_manifests",
    "write_manifests",
    "ManifestError",
    "validate_manifest",
    "validate_manifests",
]
