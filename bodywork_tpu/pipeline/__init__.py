from bodywork_tpu.pipeline.spec import (
    PipelineSpec,
    ResourceSpec,
    StageSpec,
    default_pipeline,
    parse_dag,
)
from bodywork_tpu.pipeline.runner import DayResult, LocalRunner, StageFailure
from bodywork_tpu.pipeline.k8s import generate_manifests, write_manifests

__all__ = [
    "PipelineSpec",
    "ResourceSpec",
    "StageSpec",
    "default_pipeline",
    "parse_dag",
    "DayResult",
    "LocalRunner",
    "StageFailure",
    "generate_manifests",
    "write_manifests",
]
