"""Concurrent A/B pipelines sharing one TPU pool (BASELINE.json config 5).

The reference can only run one pipeline per Bodywork deployment; comparing
two models means two cluster deployments. Here N variants (e.g. an A/B
model comparison) run concurrently in one process against one device pool:

- **store isolation**: each variant gets its own namespace directory, so
  the four-prefix artefact schema never collides;
- **device isolation**: the pool is partitioned into disjoint device
  groups (``parallel.split_devices``) and each variant's runner pins ALL
  its computations — including its own worker threads (prefetch, lookahead
  train, concurrent DAG steps) — to its group's lead chip via the runner's
  ``device`` knob. On a v5e-8 with two variants, each owns a 4-chip group
  (serving can additionally shard over the group via ``mesh_data``). With
  fewer devices than variants the pool is shared (single-chip dev boxes
  still work, just without isolation).

The per-variant loop is the standard :class:`LocalRunner` daily
simulation, so every overlap optimisation (lookahead train, prefetch)
applies per variant, and variants additionally overlap each other.
"""
from __future__ import annotations

import dataclasses
import threading
from datetime import date
from pathlib import Path

from bodywork_tpu.pipeline.runner import DayResult, LocalRunner
from bodywork_tpu.pipeline.spec import PipelineSpec, default_pipeline
from bodywork_tpu.store import ArtefactStore, FilesystemStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("pipeline.ab")


@dataclasses.dataclass
class PipelineVariant:
    """One arm of a concurrent comparison."""

    name: str
    spec: PipelineSpec
    # lazy type (data.generator imports jax; a manifests-only or
    # test-stage process must not pull the accelerator runtime)
    drift: "DriftConfig | None" = None  # noqa: F821


@dataclasses.dataclass
class VariantResult:
    name: str
    results: list[DayResult]
    #: None when the arm failed before its store could be constructed
    store: "ArtefactStore | None"
    error: BaseException | None = None


def variants_from_model_types(model_types: list[str]) -> list[PipelineVariant]:
    """Shorthand: one variant per model type, e.g. ``["linear", "mlp"]``."""
    return [
        PipelineVariant(
            name=f"{chr(ord('a') + i)}-{mt}",
            spec=default_pipeline(model_type=mt, scoring_mode="batch",
                                  overlap_generate=True),
        )
        for i, mt in enumerate(model_types)
    ]


def run_ab_simulation(
    variants: list[PipelineVariant],
    root: str | Path,
    start: date,
    days: int,
    devices=None,
) -> dict[str, VariantResult]:
    """Run every variant's N-day simulation concurrently.

    Each variant writes to ``<root>/<variant.name>/`` (``root`` may be a
    local path or a ``gs://`` URL) and, when the pool divides evenly,
    computes only on its own disjoint device group. Returns per-variant
    results; a failed variant carries its error rather than killing its
    siblings (they are independent deployments).
    """
    import jax

    from bodywork_tpu.parallel.mesh import split_devices

    names = [v.name for v in variants]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate variant names: {names}")

    pool = list(devices if devices is not None else jax.devices())
    if len(variants) > 1 and len(pool) % len(variants) == 0 and len(pool) >= len(variants):
        groups = split_devices(len(variants), pool)
    else:
        if len(variants) > 1:
            log.warning(
                f"{len(pool)} device(s) not partitionable into "
                f"{len(variants)} groups; variants share the pool"
            )
        groups = [None] * len(variants)

    out: dict[str, VariantResult] = {}

    def _variant_store(name: str):
        from bodywork_tpu.store import open_store

        if isinstance(root, str) and "://" in root:
            return open_store(root.rstrip("/") + "/" + name)
        return FilesystemStore(Path(root) / name)

    def _run(variant: PipelineVariant, group) -> None:
        # everything inside the try: a failure ANYWHERE (e.g. a bad gs://
        # root in store construction) must surface as the variant's error,
        # not die silently on the thread leaving the arm absent from `out`
        store = None
        try:
            store = _variant_store(variant.name)
            # the runner's device knob pins every thread it spawns (DAG
            # step threads, prefetch worker, lookahead train) — a bare
            # jax.default_device() here would be thread-local and miss them
            runner = LocalRunner(
                variant.spec,
                store,
                drift=variant.drift,
                device=group[0] if group else None,
            )
            results = runner.run_simulation(start, days)
            out[variant.name] = VariantResult(variant.name, results, store)
        except BaseException as exc:
            log.error(f"variant {variant.name} failed: {exc!r}")
            out[variant.name] = VariantResult(variant.name, [], store, exc)

    threads = [
        threading.Thread(
            target=_run, args=(v, g), name=f"pipeline-{v.name}"
        )
        for v, g in zip(variants, groups)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def compare_report(results: dict[str, VariantResult]):
    """Side-by-side drift report: one row per (day, variant) with the
    train/live metric gap — the A/B deliverable."""
    import pandas as pd

    from bodywork_tpu.monitor.analytics import drift_report

    frames = []
    for name, vr in results.items():
        if vr.error is not None:
            continue
        rep = drift_report(vr.store)
        rep.insert(0, "variant", name)
        frames.append(rep)
    if not frames:
        return pd.DataFrame()
    return pd.concat(frames, ignore_index=True).sort_values(
        ["date", "variant"]
    ).reset_index(drop=True)
