"""Per-stage image build contexts (reference ``bodywork.yaml:10-16,
29-35,50-54,67-72``).

The reference's per-stage dependency isolation is Bodywork pip-installing
each stage's own pinned ``requirements`` into a shared base image at pod
start. The container-native equivalent: each stage with a
``requirements`` pin set gets its OWN image, derived deterministically
from those pins, and this module emits the build context (Dockerfile +
requirements.txt + build script) that produces it. Stages therefore
deploy and upgrade independently — bumping one stage's pins changes only
that stage's image tag, and the manifest generator picks the new tag up
automatically (``k8s.py`` resolves ``stage_image``).

Tags are content-addressed: ``<repo>-<stage>:<12-hex digest of base
image + sorted pins>``. Rebuilding with unchanged pins reproduces the
same tag (idempotent deploys); any pin change rolls the tag (no stale
``latest`` pulls).
"""
from __future__ import annotations

import hashlib
from pathlib import Path

from bodywork_tpu.pipeline.spec import PipelineSpec, StageSpec

__all__ = ["stage_image_tag", "uses_derived_tag", "write_stage_images"]

_DEFAULT_BASE = "python:3.12-slim"


def uses_derived_tag(stage: StageSpec) -> bool:
    """True when manifests for ``stage`` reference a DERIVED
    content-addressed image tag — one that exists only after its build
    context is emitted and built. The single source of truth for
    "deploy must refuse without build contexts" (``cli deploy``): an
    explicit ``stage.image`` override is the operator's own tag and is
    never second-guessed. Must stay in lockstep with
    :func:`stage_image_tag`'s priority rule."""
    return bool(stage.requirements) and not stage.image


def stage_image_tag(stage: StageSpec, image: str,
                    base_image: str = _DEFAULT_BASE) -> str | None:
    """The per-stage image reference for the manifests.

    Priority: an explicit ``stage.image`` override wins; a stage with
    ``requirements`` gets the derived content-addressed tag; otherwise
    ``None`` (caller uses the pipeline-wide image)."""
    if stage.image:
        return stage.image
    if not stage.requirements:
        return None
    # strip only a TAG — a ':' after the last '/'. "localhost:5000/app"
    # is an untagged registry:port reference whose ':' must survive.
    head, sep, tail = image.rpartition(":")
    repo = head if sep and "/" not in tail else image
    digest = hashlib.sha256(
        "\n".join([base_image, *sorted(stage.requirements)]).encode()
    ).hexdigest()[:12]
    return f"{repo}-{stage.name}:{digest}"


def write_stage_images(
    spec: PipelineSpec,
    out_dir: str | Path,
    image: str = "bodywork-tpu/runtime:latest",
    base_image: str = _DEFAULT_BASE,
) -> list[Path]:
    """Emit one build context per requirements-pinned stage, plus a
    ``build.sh`` driving all of them. Returns the written paths."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    written: list[Path] = []
    build_lines = [
        "#!/bin/sh",
        "# build every per-stage image. The docker build CONTEXT must be",
        "# the framework repo root (the Dockerfiles COPY the package in):",
        "# pass it as $1 or set BODYWORK_TPU_ROOT; never assumed from the",
        "# emit directory's location.",
        "set -eu",
        'cd "$(dirname "$0")"',
        'ROOT="${1:-${BODYWORK_TPU_ROOT:-}}"',
        'if [ -z "$ROOT" ]; then',
        '  echo "usage: $0 <repo-root> (or set BODYWORK_TPU_ROOT)" >&2',
        "  exit 2",
        "fi",
    ]
    for name, stage in spec.stages.items():
        if not stage.requirements or stage.image:
            continue  # nothing to build: shared image or explicit override
        tag = stage_image_tag(stage, image, base_image)
        ctx = out / name
        ctx.mkdir(exist_ok=True)
        reqs = ctx / "requirements.txt"
        reqs.write_text("\n".join(stage.requirements) + "\n")
        dockerfile = ctx / "Dockerfile"
        dockerfile.write_text(
            f"# stage {name} — pins: content-addressed tag {tag}\n"
            f"FROM {base_image}\n"
            "COPY requirements.txt /tmp/requirements.txt\n"
            "RUN pip install --no-cache-dir -r /tmp/requirements.txt\n"
            # the framework itself rides on top of the stage's pins; the
            # build context is the repo root (-f selects this Dockerfile)
            "COPY . /opt/bodywork-tpu\n"
            "RUN pip install --no-cache-dir --no-deps /opt/bodywork-tpu\n"
            'ENTRYPOINT ["python", "-m", "bodywork_tpu.cli"]\n'
        )
        build_lines.append(
            f'docker build -f {name}/Dockerfile -t {tag} "$ROOT"'
        )
        written += [reqs, dockerfile]
    script = out / "build.sh"
    script.write_text("\n".join(build_lines) + "\n")
    script.chmod(0o755)
    written.append(script)
    return written
