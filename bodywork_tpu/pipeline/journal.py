"""Durable day-run journal: crash-resumable pipeline runs.

A runner process that dies between or inside stages — pod eviction,
OOM-kill, preemption — must leave enough durable state behind that a
restart converges to the same artefacts WITHOUT re-executing work that
already completed. This module is that state: one JSON document per
simulated day at ``runs/<date>/journal.json``
(:func:`bodywork_tpu.store.schema.run_journal_key`), mutated EXCLUSIVELY
through the store's compare-and-swap primitive
(``ArtefactStore.put_bytes_if_match``, PR 5) — the same discipline as
the registry alias document, and for the same reason: concurrent
writers must never tear or clobber it.

Two cooperating records live in the document:

- **Per-stage entries** — write-ahead ``intent`` marks before a stage
  executes, and ``complete`` marks after, each ``complete`` carrying the
  stage's artefact keys plus **content digests** (sha256 of the bytes,
  never a backend version token — so a journal written against one
  backend verifies against a copy of the store on another). A resuming
  run skips a completed stage only after re-hashing every recorded
  artefact against the store ("verify, never trust blindly"); a digest
  mismatch or missing artefact re-runs the stage. Stages left at
  ``intent`` (the process died inside them) re-execute — every batch
  stage is idempotent by construction (deterministic writers over
  date-keyed keys), so a half-written attempt is simply overwritten.

- **The run lease** — a ``(owner, expires_at, fence)`` block acquired
  and renewed through the same CAS writes. A rescheduled CronJob pod
  and a still-alive original can never interleave journal writes for
  one day: the holder renews on every write, a second runner finding a
  live foreign lease exits cleanly with :class:`LeaseLost` (``cli
  run-day`` maps it to its documented exit code), and a takeover of an
  EXPIRED lease bumps the fence so the previous holder's next CAS fails
  cleanly — the classic fencing shape, here carried by the store's own
  conditional-write token. Artefact writes by a fenced-out zombie are
  deterministic same-byte overwrites, so even that race converges.

Corrupt/torn journals degrade to a SAFE FULL RE-RUN, never an error:
every read validates JSON + schema and retries a bounded number of
times (attempts 3 > the chaos plan's default ``max_consecutive`` cap of
2, the registry-reader convention that keeps seeded soaks
deterministic); a document still unreadable past the budget is counted
on ``bodywork_tpu_runner_journal_corrupt_total``, its version token is
KEPT, and the next acquire CAS-overwrites it with a fresh journal — a
repair, not a blind create.

Journals are operational state, not results: the chaos comparison
(``chaos.sim.compare_stores``) excludes ``runs/`` from the
byte-identity check (lease owners and expiry wall-clocks legitimately
differ between twins) but still requires every journal to be loadable
and day-complete.
"""
from __future__ import annotations

import json
import os
import socket
import time
import uuid
from datetime import date

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, CasConflict
from bodywork_tpu.store.schema import run_journal_key
from bodywork_tpu.utils.integrity import stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("pipeline.journal")

__all__ = [
    "JOURNAL_SCHEMA",
    "LEASE_LOST_EXIT",
    "LeaseLost",
    "RESUMED_NOOP_EXIT",
    "RunJournal",
    "artefact_digest",
    "default_owner",
]

JOURNAL_SCHEMA = "bodywork_tpu.run_journal/1"

#: ``cli run-day`` exit when another runner holds the day's lease — the
#: loser stops cleanly and a CronJob backoff retries later. Distinct
#: from 1 (stage failure), 2 (usage), 3 (backend unreachable,
#: utils.watchdog), 4 (drift gate), 86 (chaos kill), 143 (SIGTERM).
LEASE_LOST_EXIT = 5

#: ``cli run-day`` exit when the journal already marked the day complete
#: and every recorded artefact digest verified — nothing re-ran. NOT 0:
#: an operator re-running a day wants to KNOW it was a no-op (and a
#: wrapper that considers it success can `|| test $? -eq 6`).
RESUMED_NOOP_EXIT = 6

#: default lease time-to-live. Renewed on every journal write (one per
#: DAG step boundary), so a live holder effectively never expires; a
#: dead holder's lease blocks a rescheduled twin for at most this long.
#: Env ``BODYWORK_TPU_RUN_LEASE_TTL_S`` overrides (the crash-resume
#: harness shrinks it so restarted runners take over in ~1 s); size it
#: above your longest DAG step in production.
DEFAULT_LEASE_TTL_S = 900.0

#: validation-read retry budget: 1 + retries attempts, chosen (like the
#: registry readers') to exceed the chaos plan's default
#: ``max_consecutive`` cap of 2 so a seeded soak's corrupt journal reads
#: never escalate to a spurious full re-run.
CORRUPT_READ_RETRIES = 2

#: CAS attempts per journal write before concluding the race is real
_CAS_ATTEMPTS = 4


class LeaseLost(RuntimeError):
    """Another runner holds (or took) this day's run lease. The loser
    must stop writing and exit cleanly — ``cli run-day`` maps this to
    its documented lease-lost exit code so a CronJob's backoff retries
    later instead of fighting the holder."""


def default_owner() -> str:
    """An identity unique per runner process: ``host:pid:nonce`` (the
    nonce disambiguates pid reuse across pod restarts)."""
    return f"{socket.gethostname()}:{os.getpid()}:{uuid.uuid4().hex[:8]}"


def artefact_digest(data: bytes) -> str:
    """Content digest recorded per artefact — backend-independent (a
    version token would tie the journal to one backend instance) and
    the thing resume verification re-hashes. Delegates to the shared
    format (``utils.integrity.sha256_digest``) so the integrity scrub
    can cross-check it against sidecar and lineage evidence."""
    from bodywork_tpu.utils.integrity import sha256_digest

    return sha256_digest(data)


def _journal_tenant(store) -> str | None:
    """The tenant namespace this journal's store is scoped to, or None
    for the root namespace (whose journals must stay byte-identical to
    pre-tenancy ones — the field is simply omitted)."""
    from bodywork_tpu.store.schema import DEFAULT_TENANT
    from bodywork_tpu.tenancy.namespace import tenant_of

    tenant = tenant_of(store)
    return None if tenant == DEFAULT_TENANT else tenant


def _count_corrupt() -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_runner_journal_corrupt_total",
        "Run-journal reads that stayed invalid past the retry budget "
        "(each one degrades that day to a safe full re-run)",
    ).inc()


def count_resume(outcome: str) -> None:
    """``bodywork_tpu_runner_resumes_total{outcome}``: how each
    journal-aware ``run_day`` started — ``fresh`` (no prior journal),
    ``resumed`` (some stages skipped), ``noop`` (day already complete,
    nothing re-run), ``rerun_mismatch`` (a recorded digest no longer
    matched the store), ``rerun_corrupt`` (journal unreadable past the
    budget — full re-run)."""
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_runner_resumes_total",
        "run_day journal outcomes by kind",
    ).inc(outcome=outcome)


def _count_lease(event: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_runner_lease_events_total",
        "Run-lease protocol events (acquired/takeover/lost)",
    ).inc(event=event)


def lease_ttl_from_env(default: float = DEFAULT_LEASE_TTL_S) -> float:
    from bodywork_tpu.utils.env import positive_float_env

    return positive_float_env("BODYWORK_TPU_RUN_LEASE_TTL_S", default)


class RunJournal:
    """One day's write-ahead run journal + lease (module docstring).

    Lifecycle::

        journal = RunJournal(store, today)
        prior = journal.acquire()        # raises LeaseLost to a loser
        ... journal.completed_stages() -> what MAY be skipped ...
        journal.record_intents([...])    # before a DAG step executes
        journal.record_completes({stage: {key: digest}})  # after
        journal.record_day_complete()    # releases the lease

    Every mutation is a CAS read-modify-write that re-verifies lease
    ownership; a conflict whose re-read shows a foreign owner raises
    :class:`LeaseLost` and the caller must stop.
    """

    def __init__(
        self,
        store: ArtefactStore,
        day: date,
        owner: str | None = None,
        lease_ttl_s: float | None = None,
        clock=time.time,
    ):
        self.store = store
        self.day = day
        self.key = run_journal_key(day)
        self.owner = owner or default_owner()
        self.lease_ttl_s = (
            lease_ttl_s if lease_ttl_s is not None else lease_ttl_from_env()
        )
        self.clock = clock
        #: True when acquire() found the prior document corrupt past the
        #: retry budget (the runner counts + full-re-runs)
        self.was_corrupt = False
        self._doc: dict | None = None
        self._token = None
        self._prior_status: str | None = None
        self._prior_complete: dict[str, dict] = {}

    # -- reads -------------------------------------------------------------

    def _load(self) -> tuple[dict | None, object, bool]:
        """``(doc_or_None, version_token, corrupt)``. The token is read
        BEFORE the payload (the registry-reader pattern), so a CAS
        against it can only win if nothing changed since; a
        ``(None, token, True)`` triple means the key EXISTS but stays
        invalid past the retry budget — the CAS repair-overwrite case."""
        token = self.store.version_token(self.key)
        corrupt = False
        for _attempt in range(1 + CORRUPT_READ_RETRIES):
            try:
                raw = self.store.get_bytes(self.key)
            except ArtefactNotFound:
                return None, None, False
            try:
                doc = json.loads(raw.decode("utf-8"))
                if (
                    isinstance(doc, dict)
                    and doc.get("schema") == JOURNAL_SCHEMA
                    # embedded content digest (utils.integrity): a bit
                    # flip that leaves the JSON parseable — a digit in a
                    # recorded artefact digest, say — must still read as
                    # corrupt, or resume would trust poisoned state
                    and verify_doc(doc) is not False
                ):
                    return doc, token, False
            except (UnicodeDecodeError, ValueError):
                pass
            corrupt = True
            log.warning(f"corrupt run journal at {self.key!r}; re-reading")
        return None, token, corrupt

    @property
    def doc(self) -> dict | None:
        return self._doc

    @property
    def prior_status(self) -> str | None:
        """The day status the journal held when acquired (``running`` /
        ``complete`` / ``interrupted``), or None for a fresh day."""
        return self._prior_status

    def completed_stages(self) -> dict[str, dict]:
        """Stage entries recorded ``complete`` by a PRIOR run (captured
        at acquire time) — the candidates for verified skipping."""
        return dict(self._prior_complete)

    # -- the lease + write protocol ---------------------------------------

    def _lease_block(self, fence: int) -> dict:
        return {
            "owner": self.owner,
            "expires_at": self.clock() + self.lease_ttl_s,
            "fence": fence,
        }

    def _foreign_live_lease(self, doc: dict) -> dict | None:
        lease = doc.get("lease") or {}
        if (
            lease.get("owner")
            and lease["owner"] != self.owner
            and lease.get("expires_at", 0) > self.clock()
        ):
            return lease
        return None

    def acquire(self) -> dict | None:
        """Take (or retake) the day's run lease, creating the journal if
        absent and CAS-repairing it if corrupt. Returns the PRIOR
        document (None for a fresh day) after stashing its completed
        stages for :meth:`completed_stages`. Raises :class:`LeaseLost`
        when a live foreign lease holds the day."""
        for _attempt in range(_CAS_ATTEMPTS):
            doc, token, corrupt = self._load()
            if corrupt:
                self.was_corrupt = True
                _count_corrupt()
                log.error(
                    f"run journal for {self.day} unreadable past the retry "
                    "budget; repairing with a fresh journal (full re-run)"
                )
                doc = None
            if doc is not None:
                foreign = self._foreign_live_lease(doc)
                if foreign is not None:
                    _count_lease("lost")
                    raise LeaseLost(
                        f"run lease for {self.day} is held by "
                        f"{foreign['owner']!r} until ~{foreign['expires_at']:.0f}"
                    )
            prior = doc
            prior_lease = (doc or {}).get("lease") or {}
            takeover = bool(
                prior_lease.get("owner")
                and prior_lease["owner"] != self.owner
            )
            new_doc = {
                "schema": JOURNAL_SCHEMA,
                "day": str(self.day),
                "status": (doc or {}).get("status", "running"),
                "stages": dict((doc or {}).get("stages") or {}),
                "lease": self._lease_block(
                    int(prior_lease.get("fence", 0)) + 1
                ),
            }
            tenant = _journal_tenant(self.store)
            if tenant is not None:
                # provenance only; the default (root) namespace omits
                # the field so pre-tenancy journals stay byte-identical
                new_doc["tenant"] = tenant
            try:
                self._token = self.store.put_bytes_if_match(
                    self.key, _dumps(new_doc), token
                )
            except CasConflict:
                continue  # someone raced the acquire: re-read and re-decide
            self._doc = new_doc
            self._prior_status = (prior or {}).get("status")
            self._prior_complete = {
                name: entry
                for name, entry in ((prior or {}).get("stages") or {}).items()
                if entry.get("state") == "complete"
            }
            _count_lease("takeover" if takeover else "acquired")
            if takeover:
                log.warning(
                    f"took over the {self.day} run lease from expired "
                    f"holder {prior_lease.get('owner')!r} "
                    f"(fence {new_doc['lease']['fence']})"
                )
            return prior
        _count_lease("lost")
        raise LeaseLost(
            f"could not acquire the {self.day} run lease in "
            f"{_CAS_ATTEMPTS} attempts (persistent CAS contention)"
        )

    def _write(self, mutate, release: bool = False) -> None:
        """CAS read-modify-write of the journal under our lease:
        ``mutate(doc)`` edits in place; every write renews the lease —
        or, with ``release=True``, clears it in the SAME CAS (fence
        kept, so the next acquirer still bumps past us). A conflict
        re-reads — a foreign owner (live or not: someone ELSE wrote,
        our exclusivity is gone) raises :class:`LeaseLost`."""
        assert self._doc is not None, "acquire() before writing"
        doc = self._doc
        for _attempt in range(_CAS_ATTEMPTS):
            new_doc = {
                **doc,
                "stages": {
                    name: dict(entry)
                    for name, entry in (doc.get("stages") or {}).items()
                },
            }
            mutate(new_doc)
            fence = int((doc.get("lease") or {}).get("fence", 1))
            if release:
                new_doc["lease"] = {
                    "owner": None, "expires_at": 0.0, "fence": fence,
                }
            else:
                new_doc["lease"] = self._lease_block(fence)
            try:
                self._token = self.store.put_bytes_if_match(
                    self.key, _dumps(new_doc), self._token
                )
            except CasConflict:
                fresh, token, corrupt = self._load()
                if corrupt or fresh is None or (
                    (fresh.get("lease") or {}).get("owner") != self.owner
                ):
                    _count_lease("lost")
                    raise LeaseLost(
                        f"run lease for {self.day} was taken over "
                        "mid-run; stopping"
                    ) from None
                doc, self._token = fresh, token
                continue
            self._doc = new_doc
            return
        raise LeaseLost(
            f"journal write for {self.day} kept losing CAS races"
        )

    # -- stage records -----------------------------------------------------

    def record_intents(self, names: list[str]) -> None:
        """Write-ahead marks: these stages are ABOUT to execute (and may
        be found half-done by a resuming run, which re-executes them)."""

        def _mutate(doc: dict) -> None:
            for name in names:
                doc["stages"][name] = {"state": "intent"}
            doc["status"] = "running"

        self._write(_mutate)

    def record_completes(self, artefacts_by_stage: dict[str, dict]) -> None:
        """Mark stages complete, each with its ``{artefact key: content
        digest}`` map (empty for stages with nothing verifiable — a
        resuming run re-executes those rather than trusting blindly)."""

        def _mutate(doc: dict) -> None:
            for name, artefacts in artefacts_by_stage.items():
                doc["stages"][name] = {
                    "state": "complete",
                    "artefacts": dict(artefacts),
                }

        self._write(_mutate)

    def record_day_complete(self) -> None:
        """The whole day converged: ONE CAS marking ``complete`` AND
        releasing the lease (a later duplicate run sees a free, finished
        journal and exits resumed-noop without waiting on any TTL)."""
        self._write(
            lambda doc: doc.__setitem__("status", "complete"), release=True
        )

    def record_interrupted(self) -> None:
        """Graceful-shutdown mark (SIGTERM): the day stops cleanly
        mid-run; in-flight stages keep their ``intent`` entries, the
        lease is released in the same CAS so the rescheduled pod starts
        immediately instead of waiting out the TTL. Best-effort — a
        lease lost here just means a successor is already running."""
        try:
            self._write(
                lambda doc: doc.__setitem__("status", "interrupted"),
                release=True,
            )
        except Exception as exc:  # noqa: BLE001 — shutdown path
            log.warning(f"could not journal the interruption: {exc!r}")

    def release(self) -> None:
        """Release the lease without changing anything else — the
        resumed-noop and stage-failure exits (the day's status already
        says what happened; holding the lease for the TTL would only
        stall the next attempt). Best-effort, same rationale as
        :meth:`record_interrupted`."""
        try:
            self._write(lambda doc: None, release=True)
        except Exception as exc:  # noqa: BLE001 — exit path
            log.warning(f"could not release the run lease: {exc!r}")

    # -- resume verification ----------------------------------------------

    def verify_completed(self) -> tuple[dict[str, dict], bool]:
        """Re-hash every prior-``complete`` stage's recorded artefacts
        against the store. Returns ``(verified entries, any_mismatch)``:
        only stages whose EVERY artefact digest matches are returned;
        entries with no artefacts recorded are never returned (nothing
        verifiable means nothing skippable)."""
        verified: dict[str, dict] = {}
        mismatch = False
        for name, entry in self.completed_stages().items():
            artefacts = entry.get("artefacts") or {}
            if not artefacts:
                continue
            ok = True
            for key, digest in artefacts.items():
                try:
                    data = self.store.get_bytes(key)
                except ArtefactNotFound:
                    ok = False
                    break
                if artefact_digest(data) != digest:
                    ok = False
                    break
            if ok:
                verified[name] = entry
            else:
                mismatch = True
                log.warning(
                    f"journalled stage {name!r} failed digest "
                    "verification; re-running it"
                )
        return verified, mismatch


def _dumps(doc: dict) -> bytes:
    # every write stamps the embedded content digest, so a journal's
    # validity is verifiable without any out-of-band record — the
    # property the integrity scrubber's runs/ auditor rides
    return json.dumps(
        stamp_doc(doc), sort_keys=True, indent=1
    ).encode("utf-8")
