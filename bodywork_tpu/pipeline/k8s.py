"""GKE/Kubernetes manifest generation for TPU node pools (reference C1's
deployment half).

The reference delegates materialisation to the Bodywork controller: batch
stages become Jobs (retries/timeout — ``bodywork.yaml:19-21``), the service
stage a 2-replica Deployment + cluster Service on port 5000
(``bodywork.yaml:38-42``), secrets injected as env (``bodywork.yaml:22-26``).

Here the framework emits those manifests itself, targeting GKE TPU node
pools: stages with TPU resources get the standard GKE nodeSelectors
(``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology``) and a
``google.com/tpu`` resource request, per Google's TPU-on-GKE scheduling
model.

Artefacts flow through one of three store media (``store_volume=``),
preserving the reference contract that every stage shares one bucket
(``bodywork.yaml:22-26`` + S3 usage in all four stages):

- ``pvc`` (default for filesystem paths) — a ``ReadWriteMany``
  PersistentVolumeClaim (e.g. GKE Filestore CSI, ``standard-rwx``)
  mounted at the store path in every pod; correct on multi-node clusters,
  where train Jobs on the TPU node pool and serve Deployments on other
  nodes must see the same filesystem.
- ``hostpath`` — the node's own filesystem. **Single-node clusters
  only** (explicit opt-in): a hostPath volume is per-node, so on any
  multi-node cluster the stages would silently see different stores.
- ``gcs`` (default for ``gs://`` store paths) — no volume at all; every
  stage talks straight to the GCS artefact-store backend
  (``store/gcs.py``), the closest analogue of the reference's S3 bucket.

The daily loop is a CronJob running ``run-day`` (the reference re-runs the
whole Bodywork deployment daily — README.md:5).
"""
from __future__ import annotations

import dataclasses
import io
from pathlib import Path

import yaml

from bodywork_tpu.pipeline.images import stage_image_tag
from bodywork_tpu.pipeline.spec import PipelineSpec, ResourceSpec, StageSpec
from bodywork_tpu.utils.logging import get_logger

log = get_logger("pipeline.k8s")

_STORE_VOLUME = "artefact-store"
_SPEC_VOLUME = "pipeline-spec"
_SPEC_MOUNT = "/etc/bodywork"
_SPEC_FILE = "pipeline.yaml"
_DEFAULT_IMAGE = "bodywork-tpu/runtime:latest"

STORE_VOLUME_MODES = ("auto", "pvc", "hostpath", "gcs")

#: identity-checked sentinel: an implicit default schedule on a multi-host
#: spec is omitted with a warning; an EXPLICIT schedule raises
DEFAULT_DAILY_SCHEDULE = "0 6 * * *"


def _offset_schedule(schedule: str, minutes: int) -> str:
    """Shift a simple 5-field cron line's minute field by ``minutes``
    (mod 60, bumping a numeric hour field when it wraps) — used to run
    the drift gate after the day loop it audits. Non-numeric fields
    (``*``, lists, steps) keep the hour untouched: a wrapped minute
    under ``*`` hours still runs hourly, just offset. When the HOUR
    wraps past midnight and the schedule pins a day-of-month,
    day-of-week, or month, the shift is abandoned entirely: cron has no
    carry into the day/month fields, so ``45 23 * * 1`` shifted to
    ``15 0 * * 1`` would fire ~23h45m EARLY (Monday 00:15) instead of
    30 min late (and a pinned month's last day would shift clean out of
    the month) — running the gate at the unshifted time is the lesser
    error."""
    fields = schedule.split()
    if len(fields) != 5 or not fields[0].isdigit():
        return schedule  # macro or complex minute: run at the same time
    minute = int(fields[0]) + minutes
    if minute >= 60 and fields[1].isdigit():
        hour = int(fields[1]) + minute // 60
        if hour >= 24 and fields[2:5] != ["*", "*", "*"]:
            return schedule  # day would be wrong: keep the original time
        fields[1] = str(hour % 24)
    fields[0] = str(minute % 60)
    return " ".join(fields)


@dataclasses.dataclass(frozen=True)
class _StoreMedium:
    """How pods reach the shared artefact store (see module docstring)."""

    store_path: str
    mode: str  # "pvc" | "hostpath" | "gcs"
    claim_name: str = ""
    storage_class: str | None = None
    size: str = "10Gi"

    def volume(self) -> dict | None:
        if self.mode == "gcs":
            return None
        if self.mode == "hostpath":
            source = {
                "hostPath": {"path": self.store_path, "type": "DirectoryOrCreate"}
            }
        else:
            source = {"persistentVolumeClaim": {"claimName": self.claim_name}}
        return {"name": _STORE_VOLUME, **source}

    def mount(self) -> dict | None:
        if self.mode == "gcs":
            return None
        return {"name": _STORE_VOLUME, "mountPath": self.store_path}

    def pvc_doc(self, namespace: str) -> dict:
        assert self.mode == "pvc"
        pvc_spec: dict = {
            # ReadWriteMany: Jobs on the TPU node pool and the serve
            # Deployment mount it concurrently from different nodes
            "accessModes": ["ReadWriteMany"],
            "resources": {"requests": {"storage": self.size}},
        }
        if self.storage_class:
            pvc_spec["storageClassName"] = self.storage_class
        return {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": {"name": self.claim_name, "namespace": namespace},
            "spec": pvc_spec,
        }


def _resolve_store_medium(
    spec: PipelineSpec,
    store_path: str,
    store_volume: str,
    storage_class: str | None,
    pvc_size: str,
) -> _StoreMedium:
    if store_volume not in STORE_VOLUME_MODES:
        raise ValueError(
            f"store_volume must be one of {STORE_VOLUME_MODES}, "
            f"got {store_volume!r}"
        )
    is_gcs_path = store_path.startswith("gs://")
    if store_volume == "auto":
        store_volume = "gcs" if is_gcs_path else "pvc"
    if is_gcs_path != (store_volume == "gcs"):
        raise ValueError(
            f"store_volume={store_volume!r} does not fit "
            f"store_path={store_path!r}: use a gs:// path with 'gcs' and a "
            "filesystem path with 'pvc'/'hostpath'"
        )
    return _StoreMedium(
        store_path=store_path,
        mode=store_volume,
        claim_name=f"{spec.name}--store",
        storage_class=storage_class,
        size=pvc_size,
    )


def _spec_volume(spec: PipelineSpec) -> tuple[dict, dict]:
    """The deploy-time pipeline spec rides into every pod as a ConfigMap, so
    in-cluster entrypoints run exactly the deployed configuration (stage
    args, model/mode choices) rather than rebuilding defaults."""
    volume = {
        "name": _SPEC_VOLUME,
        "configMap": {"name": f"{spec.name}--spec"},
    }
    mount = {"name": _SPEC_VOLUME, "mountPath": _SPEC_MOUNT, "readOnly": True}
    return volume, mount


def _container(
    spec: PipelineSpec,
    stage: StageSpec,
    store: _StoreMedium,
    image: str,
    command: list[str],
) -> dict:
    mount = store.mount()
    _, spec_mount = _spec_volume(spec)
    resources: dict = {
        "requests": {
            "cpu": str(stage.resources.cpu_request),
            "memory": f"{stage.resources.memory_mb}Mi",
        }
    }
    if stage.resources.tpu_chips:
        resources["limits"] = {"google.com/tpu": stage.resources.tpu_chips}
    env = [{"name": k, "value": str(v)} for k, v in stage.env.items()]
    if store.mode != "gcs":
        # persistent XLA compilation cache on the shared store volume: a
        # one-shot daily pod re-pays every compile otherwise (the local
        # runner's prewarm machinery never reaches a fresh pod). Dotted
        # dir: invisible to the store's prefix/date-key listing protocol.
        # gcs mode is skipped — jax's gs:// cache needs extra deps.
        declared = set(stage.env)
        for name, value in (
            ("JAX_COMPILATION_CACHE_DIR", f"{store.store_path}/.xla-cache"),
            ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5"),
        ):
            if name not in declared:
                env.append({"name": name, "value": value})
    # required secrets fail fast at admission (CreateContainerConfigError);
    # optional ones back features that are no-ops when unconfigured (the
    # default pipeline's sentry-integration DSN, utils/errors.py)
    env_from = [{"secretRef": {"name": s}} for s in stage.secrets]
    env_from += [
        {"secretRef": {"name": s, "optional": True}}
        for s in stage.optional_secrets
    ]
    container = {
        "name": stage.name,
        "image": stage_image_tag(stage, image) or image,
        "command": command,
        "volumeMounts": [m for m in (mount, spec_mount) if m],
        "resources": resources,
    }
    if env:
        container["env"] = env
    if env_from:
        container["envFrom"] = env_from
    if stage.kind == "service" and stage.port:
        # serving front-end + admission knobs (serve.aio / serve.admission,
        # read by serve_stage at boot): materialised as env vars so an
        # operator flips the HTTP engine or the pending budget with one
        # `kubectl set env` — no image rebuild, next rollout picks it up.
        # Defaults preserve the deployed behaviour exactly: the threaded
        # engine with admission off (MAX_PENDING empty = unset; setting
        # ENGINE=aio arms admission at its default budget of 512).
        declared = {e["name"] for e in env}
        for name, value in (
            ("BODYWORK_TPU_SERVER_ENGINE", "thread"),
            ("BODYWORK_TPU_MAX_PENDING", ""),
            ("BODYWORK_TPU_RETRY_AFTER_MAX_S", ""),
            # serving precision (serve --dtype): flip to bfloat16/int8
            # with `kubectl set env` — the shadow quality gate still
            # decides per checkpoint whether the quantized variant serves
            ("BODYWORK_TPU_SERVE_DTYPE", "float32"),
            # serving mesh (serve --mesh-data/--mesh-model, read by
            # stages._serve_env_knobs): shard the forward pass over a
            # data x model device mesh with `kubectl set env` — empty =
            # single-device, the pre-mesh behaviour exactly
            ("BODYWORK_TPU_MESH_DATA", ""),
            ("BODYWORK_TPU_MESH_MODEL", ""),
            # disaggregated serving (serve --frontends, read by
            # stages._serve_fleet_env_knobs): N parse/admission
            # front-end processes feeding ONE device-owning dispatcher
            # over a shared-memory row-queue — scale parse capacity
            # with `kubectl set env` while batches keep coalescing from
            # the UNION of all front-ends' rows; empty = the flat
            # topology (docs/PERF.md §config 14)
            ("BODYWORK_TPU_FRONTENDS", ""),
            # cross-host row-queue transport (serve --transport /
            # --dispatcher-addr / --role, PR 18): "tcp" at generate
            # time splits the stage into front-end + dispatcher
            # Deployments; materialised here so `kubectl set env`
            # can flip a flat pod's knobs without editing manifests
            ("BODYWORK_TPU_SERVE_TRANSPORT", ""),
            ("BODYWORK_TPU_DISPATCHER_ADDR", ""),
            ("BODYWORK_TPU_SERVE_ROLE", ""),
            # dispatcher high availability (serve/leadership.py): a
            # truthy value emits an active/standby dispatcher PAIR
            # (replicas: 2) arbitrated by the CAS lease on the store
            ("BODYWORK_TPU_SERVE_STANDBY", ""),
            # coalescer + bucket knobs and the tuned-config pointer
            # (tune/config.py, read by stages._serve_tuned_env_knobs):
            # point BODYWORK_TPU_TUNED_CONFIG at a tuning/ document (or
            # "latest") with `kubectl set env` and the next rollout
            # serves `cli tune`'s fitted knobs; the per-knob vars
            # override individual values; empty = built-in defaults,
            # and a malformed/deleted document degrades to them too —
            # a bad tuned config can never crash-loop the pod
            ("BODYWORK_TPU_BATCH_WINDOW_MS", ""),
            ("BODYWORK_TPU_BATCH_MAX_ROWS", ""),
            ("BODYWORK_TPU_BUCKETS", ""),
            ("BODYWORK_TPU_TUNED_CONFIG", ""),
            # SLO-watchdog breach thresholds (ops/slo.py policy_from_env;
            # empty = the coded defaults): retune the canary abort
            # budget with `kubectl set env`, no rebuild/redeploy
            ("BODYWORK_TPU_SLO_WINDOW_REQUESTS", ""),
            ("BODYWORK_TPU_SLO_MIN_REQUESTS", ""),
            ("BODYWORK_TPU_SLO_MAX_ERROR_RATE", ""),
            ("BODYWORK_TPU_SLO_MAX_P99_RATIO", ""),
            ("BODYWORK_TPU_SLO_MAX_SANITY_VIOLATIONS", ""),
            ("BODYWORK_TPU_SLO_PROMOTE_AFTER_REQUESTS", ""),
            # online tuning control plane (tune/online.py
            # policy_from_env + cli serve): arm the drift-refit
            # controller and retune its drift/revert thresholds with
            # `kubectl set env` — empty = off / the coded defaults,
            # and a malformed value degrades per-field, never a
            # crash-looping pod
            ("BODYWORK_TPU_TUNE_ONLINE", ""),
            ("BODYWORK_TPU_TUNE_REQUEST_LOGS", ""),
            ("BODYWORK_TPU_TUNE_RESULTS_LOGS", ""),
            ("BODYWORK_TPU_TUNE_MIN_WINDOW_REQUESTS", ""),
            ("BODYWORK_TPU_TUNE_DRIFT_THRESHOLD", ""),
            ("BODYWORK_TPU_TUNE_COOLDOWN_POLLS", ""),
            ("BODYWORK_TPU_TUNE_VERDICT_POLLS", ""),
            ("BODYWORK_TPU_TUNE_MIN_VERDICT_REQUESTS", ""),
            ("BODYWORK_TPU_TUNE_REVERT_ERROR_RATE", ""),
            ("BODYWORK_TPU_TUNE_REVERT_P99_RATIO", ""),
            # cost-priced admission shed (tune/costmodel.py +
            # serve/admission.py): estimated dispatch-seconds budget
            ("BODYWORK_TPU_COST_BUDGET_S", ""),
        ):
            if name not in declared:
                env.append({"name": name, "value": value})
        container["env"] = env
        # one named port serves scoring AND the GET /metrics Prometheus
        # exposition (serve.app registers the route unconditionally); the
        # name is what the pod-template scrape annotations point at
        container["ports"] = [{"containerPort": stage.port, "name": "http"}]
        container["readinessProbe"] = {
            "httpGet": {"path": "/healthz", "port": stage.port},
            "initialDelaySeconds": 2,
            "periodSeconds": 3,
            "failureThreshold": int(stage.max_startup_time_s // 3) or 1,
            # readiness semantics under admission control: a replica AT
            # its pending budget keeps answering /healthz 200 (shedding
            # is the service doing its job — failing readiness would
            # pull it from the endpoints and dogpile its share onto the
            # siblings; serve.app.healthz_payload). Only a replica with
            # NO model (503) leaves the rotation. The tight timeout is
            # safe for the same reason: /healthz never queues behind
            # scoring work on either engine.
            "timeoutSeconds": 2,
        }
    return container


def _pod_spec(spec: PipelineSpec, stage: StageSpec, store: _StoreMedium,
              image: str, command: list[str], restart_policy: str,
              gate_on_deps: bool = True) -> dict:
    volume = store.volume()
    spec_volume, _ = _spec_volume(spec)
    pod: dict = {
        "containers": [_container(spec, stage, store, image, command)],
        "volumes": [v for v in (volume, spec_volume) if v],
        "restartPolicy": restart_policy,
    }
    if gate_on_deps:
        init_containers = _init_containers(spec, stage, store, image)
        if init_containers:
            pod["initContainers"] = init_containers
    r = stage.resources
    if r.tpu_accelerator:
        pod["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": r.tpu_accelerator,
            **({"cloud.google.com/gke-tpu-topology": r.tpu_topology}
               if r.tpu_topology else {}),
        }
    return pod


def _init_containers(
    spec: PipelineSpec, stage: StageSpec, store: _StoreMedium, image: str
) -> list[dict]:
    """DAG-ordering gates as initContainers.

    ``kubectl apply -f`` creates all Jobs at once; the reference relied on
    the Bodywork controller to sequence ``>>`` steps. Here each pod gates
    itself on the *observable effects* of its DAG predecessors via
    ``cli wait-for``: a produced artefact for batch predecessors, a healthy
    endpoint for service predecessors — no controller or RBAC needed.
    """
    conditions: list[str] = []
    # input precondition: training needs data to exist at all
    if stage.executable.endswith(":train_stage"):
        conditions += ["--dataset"]
    if stage.executable.endswith(":serve_stage"):
        conditions += ["--model"]
    # DAG predecessors, by the effect each one produces
    seen_self = False
    for step in reversed(spec.dag):
        if stage.name in step:
            seen_self = True
            continue
        if not seen_self:
            continue
        for pred_name in step:
            pred = spec.stages[pred_name]
            if pred.kind == "service" and pred.port:
                conditions += [
                    "--service-url",
                    f"http://{spec.service_dns(pred.name)}:{pred.port}/healthz",
                ]
            elif pred.executable.endswith(":generate_stage"):
                conditions += ["--dataset-newer-than-model"]
            elif pred.executable.endswith(":train_stage"):
                conditions += ["--model"]
        break  # only the immediately preceding step gates this stage
    if not conditions:
        return []
    _, spec_mount = _spec_volume(spec)
    return [
        {
            "name": "wait-for-deps",
            # the stage's own image (when overridden): the gate must run
            # in the same dependency set the stage was pinned to
            "image": stage_image_tag(stage, image) or image,
            "command": [
                "python", "-m", "bodywork_tpu.cli", "wait-for",
                "--store", store.store_path, *conditions,
            ],
            "volumeMounts": [m for m in (store.mount(), spec_mount) if m],
        }
    ]


def _stage_command(spec: PipelineSpec, stage: StageSpec, store_path: str) -> list[str]:
    cmd = [
        "python", "-m", "bodywork_tpu.cli", "run-stage",
        "--stage", stage.name,
        "--store", store_path,
        "--spec", f"{_SPEC_MOUNT}/{_SPEC_FILE}",
    ]
    service_stages = [s for s in spec.stages.values() if s.kind == "service"]
    if stage.kind == "batch" and service_stages:
        svc = service_stages[0]
        cmd += [
            "--scoring-url",
            f"http://{spec.service_dns(svc.name)}:{svc.port}/score/v1",
        ]
    return cmd


def generate_manifests(
    spec: PipelineSpec,
    store_path: str = "/mnt/artefact-store",
    image: str = _DEFAULT_IMAGE,
    namespace: str = "bodywork-tpu",
    daily_schedule: str | None = DEFAULT_DAILY_SCHEDULE,
    store_volume: str = "auto",
    storage_class: str | None = "standard-rwx",
    pvc_size: str = "10Gi",
) -> dict[str, dict]:
    """Emit all k8s objects for the pipeline, keyed by filename.

    ``store_volume`` selects the shared-store medium (module docstring):
    ``"auto"`` picks ``"gcs"`` for ``gs://`` store paths and ``"pvc"``
    (ReadWriteMany claim, ``storage_class``/``pvc_size``) otherwise;
    ``"hostpath"`` is a single-node-cluster opt-in.

    ``storage_class`` defaults to GKE Filestore CSI's ``standard-rwx``
    because a ReadWriteMany claim cannot provision against the usual
    RWO-only default class (stock GKE PD) — the claim would sit Pending
    forever. Pass ``None``/empty to use the cluster's default class
    (only correct if that class supports RWX).
    """
    for stage in spec.stages.values():
        if stage.kind == "service" and stage.resources.tpu_hosts > 1:
            # silently emitting a single-host Deployment would defer the
            # misconfiguration to runtime (a model sharded for N hosts
            # cannot fit one host's chips)
            raise ValueError(
                f"stage {stage.name!r}: tpu_hosts > 1 is only supported "
                "for batch stages (Indexed Jobs); multi-host serving "
                "Deployments are not materialisable"
            )
    multihost = any(s.resources.tpu_hosts > 1 for s in spec.stages.values())
    store = _resolve_store_medium(
        spec, store_path, store_volume, storage_class, pvc_size
    )
    docs: dict[str, dict] = {
        "00-namespace.yaml": {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": namespace},
        },
        "00-pipeline-spec-configmap.yaml": {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": f"{spec.name}--spec", "namespace": namespace},
            "data": {_SPEC_FILE: spec.to_yaml()},
        },
    }
    if store.mode == "pvc":
        docs["00-store-pvc.yaml"] = store.pvc_doc(namespace)
    labels_base = {"app.kubernetes.io/part-of": spec.name}
    for i, step in enumerate(spec.dag, start=1):
        for stage_name in step:
            stage = spec.stages[stage_name]
            labels = {**labels_base, "app": spec.service_dns(stage.name)}
            command = _stage_command(spec, stage, store_path)
            meta = {
                "name": spec.service_dns(stage.name),
                "namespace": namespace,
                "labels": labels,
            }
            if stage.kind == "batch":
                job_spec: dict = {
                    "backoffLimit": stage.retries,
                    "activeDeadlineSeconds": int(stage.max_completion_time_s),
                    "template": {
                        "metadata": {"labels": labels},
                        "spec": _pod_spec(
                            spec, stage, store, image, command, "Never"
                        ),
                    },
                }
                n_hosts = stage.resources.tpu_hosts
                if n_hosts > 1:
                    # one worker failure cascades to ALL n pods (the
                    # coordinator heartbeat kills the slice), so a logical
                    # retry costs n pod failures — scale the budget or a
                    # single failure exhausts it
                    job_spec["backoffLimit"] = stage.retries * n_hosts
                    # multi-host TPU slice: one Indexed pod per worker host.
                    # Indexed pods get stable hostnames <job>-<index>; with
                    # `subdomain` + the headless Service below, pod 0 is
                    # resolvable as the JAX coordinator, which is the env
                    # trigger parallel.multihost_init keys on (GKE's TPU
                    # webhook supplies worker ids/hostnames to
                    # jax.distributed.initialize itself).
                    job_name = meta["name"]
                    job_spec["completions"] = n_hosts
                    job_spec["parallelism"] = n_hosts
                    job_spec["completionMode"] = "Indexed"
                    pod = job_spec["template"]["spec"]
                    pod["subdomain"] = job_name
                    container = pod["containers"][0]
                    container.setdefault("env", []).extend(
                        [
                            {
                                "name": "JAX_COORDINATOR_ADDRESS",
                                "value": f"{job_name}-0.{job_name}:8476",
                            },
                            # with JOB_COMPLETION_INDEX (k8s-injected per
                            # Indexed pod) this gives multihost_init the
                            # full explicit topology — no reliance on
                            # JAX's cluster auto-detection
                            {
                                "name": "NUM_PROCESSES",
                                "value": str(n_hosts),
                            },
                        ]
                    )
                    docs[f"{i:02d}-{stage.name}-workers-headless.yaml"] = {
                        "apiVersion": "v1",
                        "kind": "Service",
                        "metadata": meta,
                        "spec": {
                            "clusterIP": "None",
                            # per-pod DNS must exist BEFORE readiness, or
                            # workers racing ahead of pod 0 get NXDOMAIN
                            # on the coordinator name at startup
                            "publishNotReadyAddresses": True,
                            "selector": {"app": labels["app"]},
                            "ports": [{"port": 8476, "name": "jax-coord"}],
                        },
                    }
                docs[f"{i:02d}-{stage.name}-job.yaml"] = {
                    "apiVersion": "batch/v1",
                    "kind": "Job",
                    "metadata": meta,
                    "spec": job_spec,
                }
            else:
                # cross-host disaggregated serving (serve.netqueue,
                # docs/RESILIENCE.md §14): a service stage that DECLARES
                # the tcp row-queue transport in its env splits into two
                # separately scalable Deployments — jax-free front-ends
                # (this doc, keeping the stage's standard name so the
                # Service/Ingress/HPA below keep targeting it) and one
                # device-owning dispatcher reached through its own
                # Service. Both run `cli serve` directly with an
                # explicit --role: the in-process run-stage entrypoint
                # cannot run either half of a process fleet.
                split = (
                    str(stage.env.get(
                        "BODYWORK_TPU_SERVE_TRANSPORT", ""
                    )).strip() == "tcp"
                )
                standby = str(stage.env.get(
                    "BODYWORK_TPU_SERVE_STANDBY", ""
                )).strip().lower() in ("1", "true", "yes", "on")
                dispatcher_dns = f"{meta['name']}--dispatcher"
                if split:
                    from bodywork_tpu.serve.netqueue import (
                        DEFAULT_DISPATCHER_PORT,
                    )

                    command = [
                        "python", "-m", "bodywork_tpu.cli", "serve",
                        "--store", store_path,
                        "--host", "0.0.0.0", "--port", str(stage.port),
                        "--role", "frontend", "--transport", "tcp",
                        "--dispatcher-addr",
                        f"{dispatcher_dns}:{DEFAULT_DISPATCHER_PORT}",
                    ]
                docs[f"{i:02d}-{stage.name}-deployment.yaml"] = {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": meta,
                    "spec": {
                        "replicas": stage.replicas,
                        "selector": {"matchLabels": {"app": labels["app"]}},
                        "template": {
                            "metadata": {
                                "labels": labels,
                                # standard Prometheus pod discovery: every
                                # serving replica exposes GET /metrics on
                                # its serving port (serve.app); scraping
                                # per POD keeps per-replica visibility —
                                # the Service would collapse replicas
                                # into whichever endpoint answered
                                "annotations": {
                                    "prometheus.io/scrape": "true",
                                    "prometheus.io/port": str(stage.port),
                                    "prometheus.io/path": "/metrics",
                                },
                            },
                            "spec": {
                                **_pod_spec(
                                    spec, stage, store, image, command,
                                    "Always",
                                ),
                                # explicit grace window matching `cli
                                # serve`'s SIGTERM drain (utils/shutdown
                                # DEFAULT_GRACE_S 20 < 30): admission
                                # sheds new work with Retry-After and
                                # in-flight requests finish before the
                                # kubelet's SIGKILL
                                "terminationGracePeriodSeconds": 30,
                            },
                        },
                    },
                }
                if split:
                    fe_pod = docs[
                        f"{i:02d}-{stage.name}-deployment.yaml"
                    ]["spec"]["template"]["spec"]
                    fe_container = fe_pod["containers"][0]
                    # the front-ends are jax-free parse/admission
                    # processes: the stage's TPU chips belong to the
                    # dispatcher alone (holding chips a pod never
                    # touches would starve the scheduler)
                    fe_container["resources"].pop("limits", None)
                    fe_pod.pop("nodeSelector", None)
                    dlabels = {**labels_base, "app": dispatcher_dns}
                    dmeta = {
                        "name": dispatcher_dns,
                        "namespace": namespace,
                        "labels": dlabels,
                    }
                    dispatcher_cmd = [
                        "python", "-m", "bodywork_tpu.cli", "serve",
                        "--store", store_path,
                        "--role", "dispatcher", "--transport", "tcp",
                        "--dispatcher-addr",
                        f"0.0.0.0:{DEFAULT_DISPATCHER_PORT}",
                    ]
                    if standby:
                        # each pod supervises warm candidates in the
                        # CAS election (serve/leadership.py); with the
                        # Deployment scaled to 2, a whole-pod death
                        # still leaves the OTHER pod's candidates to
                        # take over within the lease TTL — only the
                        # global leader binds :9091, so the tcpSocket
                        # readiness below IS leadership-gated and the
                        # ClusterIP routes to the leader alone
                        dispatcher_cmd.append("--standby")
                    dpod = _pod_spec(
                        spec, stage, store, image, dispatcher_cmd,
                        "Always",
                    )
                    dcontainer = dpod["containers"][0]
                    dcontainer["name"] = f"{stage.name}-dispatcher"
                    # the dispatcher serves the socket row-queue, not
                    # HTTP: readiness is "the listener accepts" (it
                    # binds only after the model is loaded —
                    # dispatcher_main arms the listener before ready,
                    # after load), probed at the TCP layer
                    dcontainer["ports"] = [{
                        "containerPort": DEFAULT_DISPATCHER_PORT,
                        "name": "rowqueue",
                    }]
                    dcontainer["readinessProbe"] = {
                        "tcpSocket": {"port": DEFAULT_DISPATCHER_PORT},
                        "initialDelaySeconds": 2,
                        "periodSeconds": 3,
                        "failureThreshold":
                            int(stage.max_startup_time_s // 3) or 1,
                        "timeoutSeconds": 2,
                    }
                    docs[f"{i:02d}-{stage.name}-dispatcher-deployment"
                         ".yaml"] = {
                        "apiVersion": "apps/v1",
                        "kind": "Deployment",
                        "metadata": dmeta,
                        "spec": {
                            # exactly ONE SERVING dispatcher: the
                            # row-queue contract is N front-ends -> one
                            # scorer (batches coalesce from the union
                            # of all front-ends' rows); scale
                            # FRONT-ENDS via the HPA, dispatchers only
                            # by deploying more services. Standby mode
                            # scales to 2 PODS — warm candidates, CAS
                            # lease arbitration, one leader serving —
                            # the only scaled dispatcher shape the
                            # validator accepts (k8s_validate.py)
                            "replicas": 2 if standby else 1,
                            "selector": {
                                "matchLabels": {"app": dispatcher_dns},
                            },
                            "template": {
                                "metadata": {"labels": dlabels},
                                "spec": {
                                    **dpod,
                                    "terminationGracePeriodSeconds": 30,
                                },
                            },
                        },
                    }
                    docs[f"{i:02d}-{stage.name}-dispatcher-service"
                         ".yaml"] = {
                        "apiVersion": "v1",
                        "kind": "Service",
                        "metadata": dmeta,
                        "spec": {
                            "selector": {"app": dispatcher_dns},
                            "ports": [{
                                "port": DEFAULT_DISPATCHER_PORT,
                                "targetPort": DEFAULT_DISPATCHER_PORT,
                                "name": "rowqueue",
                            }],
                            "type": "ClusterIP",
                        },
                    }
                docs[f"{i:02d}-{stage.name}-service.yaml"] = {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": meta,
                    "spec": {
                        "selector": {"app": labels["app"]},
                        "ports": [{"port": stage.port,
                                   "targetPort": stage.port,
                                   "name": "http"}],
                        "type": "ClusterIP",
                    },
                }
                # queue-pressure autoscaling (docs/RESILIENCE.md §12-13):
                # scale serving replicas on the row-queue's OWN saturation
                # signals rather than CPU — occupancy_ratio ~1.0 means the
                # slot pool (not admission) is the backpressure boundary,
                # and wait_seconds is the whole disaggregation rendezvous
                # a request pays. Both are Pods metrics through the
                # Prometheus adapter reading the per-pod scrape
                # annotations above (wait_seconds as the adapter's p90
                # rollup of the histogram). Scale-up reacts in ~30 s;
                # scale-down waits 5 min so a retry-storm's geometric
                # tail can't flap the fleet.
                docs[f"{i:02d}-{stage.name}-hpa.yaml"] = {
                    "apiVersion": "autoscaling/v2",
                    "kind": "HorizontalPodAutoscaler",
                    "metadata": meta,
                    "spec": {
                        "scaleTargetRef": {
                            "apiVersion": "apps/v1",
                            "kind": "Deployment",
                            "name": meta["name"],
                        },
                        "minReplicas": max(stage.replicas, 1),
                        "maxReplicas": max(stage.replicas, 1) * 4,
                        "metrics": [
                            {
                                "type": "Pods",
                                "pods": {
                                    "metric": {
                                        "name": "bodywork_tpu_rowqueue"
                                                "_occupancy_ratio",
                                    },
                                    "target": {
                                        "type": "AverageValue",
                                        "averageValue": "750m",
                                    },
                                },
                            },
                            {
                                "type": "Pods",
                                "pods": {
                                    "metric": {
                                        "name": "bodywork_tpu_rowqueue"
                                                "_wait_seconds_p90",
                                    },
                                    "target": {
                                        "type": "AverageValue",
                                        "averageValue": "50m",
                                    },
                                },
                            },
                        ],
                        "behavior": {
                            "scaleUp": {
                                "stabilizationWindowSeconds": 30,
                                "policies": [{
                                    "type": "Percent", "value": 100,
                                    "periodSeconds": 30,
                                }],
                            },
                            "scaleDown": {
                                "stabilizationWindowSeconds": 300,
                                "policies": [{
                                    "type": "Pods", "value": 1,
                                    "periodSeconds": 60,
                                }],
                            },
                        },
                    },
                }
                if stage.ingress:
                    # the reference's per-service `ingress` knob
                    # (bodywork.yaml:42); Bodywork exposes the service at
                    # /<project>/<stage> behind an nginx ingress controller
                    # WITH a rewrite, so the app still sees its own routes
                    # (/score/v1, /healthz). Same here: without the
                    # rewrite-target every proxied request would reach the
                    # app prefixed and 404.
                    docs[f"{i:02d}-{stage.name}-ingress.yaml"] = {
                        "apiVersion": "networking.k8s.io/v1",
                        "kind": "Ingress",
                        "metadata": {
                            **meta,
                            "annotations": {
                                "nginx.ingress.kubernetes.io/rewrite-target":
                                    "/$2",
                            },
                        },
                        "spec": {
                            "rules": [
                                {
                                    "http": {
                                        "paths": [
                                            {
                                                # capture group 2 is the
                                                # app-relative path the
                                                # rewrite forwards
                                                "path": f"/{spec.name}/{stage.name}(/|$)(.*)",
                                                "pathType": "ImplementationSpecific",
                                                "backend": {
                                                    "service": {
                                                        "name": spec.service_dns(
                                                            stage.name
                                                        ),
                                                        "port": {
                                                            "number": stage.port
                                                        },
                                                    }
                                                },
                                            }
                                        ]
                                    }
                                }
                            ]
                        },
                    }
    if daily_schedule and multihost:
        # run-day in ONE CronJob pod cannot drive a multi-host slice (TPU
        # init needs every host of the slice to participate); the daily
        # loop for a multi-host spec is re-applying the per-stage Jobs
        # (the Indexed Job IS the multi-host path), so emitting the
        # single-pod CronJob would ship a retrain that hangs on day 1
        if daily_schedule is not DEFAULT_DAILY_SCHEDULE:
            # the caller EXPLICITLY asked for a schedule: refuse loudly
            # (consistent with the multi-host serving check above) instead
            # of shipping manifests that silently lack the daily loop
            raise ValueError(
                "daily_schedule is not materialisable for a spec with "
                "multi-host stages (tpu_hosts > 1): a single CronJob pod "
                "cannot drive the slice; pass daily_schedule=None and "
                "schedule re-application of the per-stage Jobs instead"
            )
        log.warning(
            "daily-loop CronJob omitted: spec has multi-host stages "
            "(tpu_hosts > 1); schedule re-application of the per-stage "
            "Jobs instead"
        )
    elif daily_schedule:
        first_stage = next(iter(spec.stages.values()))
        # run-day executes ALL four stages in-process — plus the model-
        # registry promotion gate between train and serve (runner.py
        # _run_registry_gate: the daily CronJob is therefore the k8s
        # materialisation of the gate too; a rejected retrain never
        # moves the production alias, and `cli registry rollback`
        # against the same store is the one-op recovery path) — so its
        # pod needs every stage's import closure: it must run the
        # PIPELINE-WIDE image, never a per-stage image whose pins cover
        # only stage-1 (a stage-1 image lacks e.g. werkzeug and the
        # deployed loop would crash at stage-2 with
        # ModuleNotFoundError). Keep stage-1's TPU resources — run-day
        # trains on-device — but drop the image/requirements overrides
        # and use an honest name.
        run_day_stage = dataclasses.replace(
            first_stage, name="daily-loop", image=None, requirements=[],
            # the train-mode knob (pipeline/stages._train_env_mode),
            # materialised like the serve Deployment's engine/admission
            # knobs: an operator flips the deployed retrain between the
            # full refit and the O(1)-per-day incremental path
            # (train/incremental.py) with one `kubectl set env` — no
            # image rebuild. The default preserves deployed behaviour.
            env={"BODYWORK_TPU_TRAIN_MODE": "full", **first_stage.env},
        )
        run_day_command = [
            "python", "-m", "bodywork_tpu.cli", "run-day",
            "--store", store_path,
            "--spec", f"{_SPEC_MOUNT}/{_SPEC_FILE}",
        ]
        if store.mode != "gcs":
            # per-day run report + Chrome trace on the shared store
            # volume ({date} substituted by cmd_run_day at run time).
            # Dotted dir: invisible to the store's prefix/date-key
            # listing protocol, like .xla-cache. gcs mode skipped — the
            # trace writer targets a filesystem path.
            run_day_command += [
                "--trace-out",
                f"{store_path}/.traces/day-{{date}}.trace.json",
            ]
        docs["99-daily-loop-cronjob.yaml"] = {
            "apiVersion": "batch/v1",
            "kind": "CronJob",
            "metadata": {
                "name": f"{spec.name}--daily-loop",
                "namespace": namespace,
                "labels": labels_base,
            },
            "spec": {
                "schedule": daily_schedule,
                # Forbid + the run lease are BOTH needed: Forbid stops
                # the scheduler from starting a second Job while one
                # runs, the CAS lease (pipeline/journal.py) stops a
                # rescheduled pod from interleaving with a still-alive
                # original the API server has lost sight of. A loser
                # exits 5 (lease lost) and the backoff retries it after
                # the holder finishes or its lease expires.
                "concurrencyPolicy": "Forbid",
                "jobTemplate": {
                    "spec": {
                        # retries ride the journal: each retry resumes
                        # from the last completed stage (verified by
                        # digest), so a transient mid-day death costs
                        # only the in-flight stage. NOTE exit 6
                        # (resumed-noop) marks a retry that found the
                        # day already complete — the Job shows Failed
                        # but the artefacts are done (runbook in
                        # docs/RESILIENCE.md).
                        "backoffLimit": 3,
                        "template": {
                            "spec": {
                                **_pod_spec(
                                    spec,
                                    run_day_stage,
                                    store,
                                    image,
                                    run_day_command,
                                    "Never",
                                    gate_on_deps=False,  # run-day sequences
                                    # and bootstraps internally; a dataset
                                    # gate here would deadlock a fresh store
                                ),
                                # must exceed utils/shutdown's graceful
                                # deadline (20 s): SIGTERM -> journal
                                # 'interrupted' mark + lease release,
                                # THEN the kubelet's SIGKILL
                                "terminationGracePeriodSeconds": 30,
                            }
                        },
                    }
                },
            },
        }
        # history COMPACTION for the one-shot-pod regime: the daily-loop
        # pod is cold, so without a consolidated snapshot every day it
        # re-reads O(days) dataset artefacts (data/snapshot.py). The
        # persistent local runner compacts on a background thread; here
        # the equivalent is a CronJob running `cli compact` 15 min after
        # each day loop. Host-side numpy/pandas work: a plain CPU
        # ResourceSpec and the pipeline-wide image, like the drift gate.
        compact_stage = dataclasses.replace(
            first_stage, name="compact-history", image=None, requirements=[],
            resources=ResourceSpec(cpu_request=0.25, memory_mb=1024),
        )
        docs["99-compact-history-cronjob.yaml"] = {
            "apiVersion": "batch/v1",
            "kind": "CronJob",
            "metadata": {
                "name": f"{spec.name}--compact-history",
                "namespace": namespace,
                "labels": labels_base,
            },
            "spec": {
                "schedule": _offset_schedule(daily_schedule, minutes=15),
                "concurrencyPolicy": "Forbid",
                "jobTemplate": {
                    "spec": {
                        "template": {
                            "spec": _pod_spec(
                                spec,
                                compact_stage,
                                store,
                                image,
                                ["python", "-m", "bodywork_tpu.cli",
                                 "compact", "--store", store_path],
                                "Never",
                                gate_on_deps=False,  # an empty store is a
                                # no-op print, exit 0
                            )
                        }
                    }
                },
            },
        }
        # the integrity SCRUB (docs/RESILIENCE.md §11): proactive fsck
        # over every store prefix 45 min after the day loop, repairing
        # the safe subset (quarantine + digest-verified restore +
        # derived rebuild). Exit 7 — actionable findings the repair
        # could not clear — fails the Job, the k8s-native alarm an
        # operator or alerting stack watches, exactly like the drift
        # gate's exit 4. Pure host-side hashing and JSON work: a plain
        # CPU ResourceSpec and the pipeline-wide image.
        fsck_stage = dataclasses.replace(
            first_stage, name="store-scrub", image=None, requirements=[],
            resources=ResourceSpec(cpu_request=0.25, memory_mb=1024),
        )
        docs["99-store-scrub-cronjob.yaml"] = {
            "apiVersion": "batch/v1",
            "kind": "CronJob",
            "metadata": {
                "name": f"{spec.name}--store-scrub",
                "namespace": namespace,
                "labels": labels_base,
            },
            "spec": {
                "schedule": _offset_schedule(daily_schedule, minutes=45),
                # Forbid: two concurrent scrubs would race each other's
                # quarantine CAS writes for no benefit
                "concurrencyPolicy": "Forbid",
                "jobTemplate": {
                    "spec": {
                        "template": {
                            "spec": _pod_spec(
                                spec,
                                fsck_stage,
                                store,
                                image,
                                ["python", "-m", "bodywork_tpu.cli",
                                 "fsck", "--store", store_path,
                                 "--repair", "--json"],
                                "Never",
                                gate_on_deps=False,  # an empty store
                                # scans zero keys and exits 0
                            )
                        }
                    }
                },
            },
        }
        # the drift GATE the verdict rule exists to feed (calibrated bias
        # rule, monitor.detect_drift): runs after each day loop, exits 4
        # on current-state drift — the failed Job is the k8s-native alarm
        # an operator or alerting stack watches. --window keeps the gate
        # on the last week instead of latching on history. `report` is a
        # pure host-side pandas job: a plain CPU ResourceSpec, never
        # stage-1's TPU chips/nodeSelectors (which would park the gate on
        # a TPU node and burn a chip on reading CSVs) — and the
        # pipeline-wide image, since the report path isn't in stage-1's
        # pin set either.
        drift_gate_stage = dataclasses.replace(
            first_stage, name="drift-gate", image=None, requirements=[],
            resources=ResourceSpec(cpu_request=0.25, memory_mb=512),
        )
        docs["99-drift-gate-cronjob.yaml"] = {
            "apiVersion": "batch/v1",
            "kind": "CronJob",
            "metadata": {
                "name": f"{spec.name}--drift-gate",
                "namespace": namespace,
                "labels": labels_base,
            },
            "spec": {
                "schedule": _offset_schedule(daily_schedule, minutes=30),
                "concurrencyPolicy": "Forbid",
                "jobTemplate": {
                    "spec": {
                        "template": {
                            "spec": _pod_spec(
                                spec,
                                drift_gate_stage,
                                store,
                                image,
                                ["python", "-m", "bodywork_tpu.cli",
                                 "report", "--store", store_path,
                                 "--fail-on-drift", "--window", "7"],
                                "Never",
                                gate_on_deps=False,  # an empty store just
                                # prints "no metric history yet", exit 0
                            )
                        }
                    }
                },
            },
        }
    # strict structural validation: a typo'd field name fails HERE, at
    # generation, not at `kubectl apply` (k8s_validate module docstring)
    from bodywork_tpu.pipeline.k8s_validate import validate_manifests

    validate_manifests(docs)
    return docs


def write_manifests(
    spec: PipelineSpec, out_dir: str | Path, **kwargs
) -> list[Path]:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    written = []
    for filename, doc in generate_manifests(spec, **kwargs).items():
        buf = io.StringIO()
        yaml.safe_dump(doc, buf, sort_keys=False)
        path = out / filename
        path.write_text(buf.getvalue())
        written.append(path)
    return written
