"""Kubernetes API schemas for the emitted manifest kinds (VERDICT r4
item 7).

``pipeline.k8s_validate`` is a fast hand-rolled whitelist — written by
the same author as the generator, so a shared misunderstanding of the
k8s API passes both. This module is the independent second opinion: JSON
Schemas transcribed from the upstream Kubernetes API types (apps/v1,
batch/v1, core/v1, networking.k8s.io/v1 — the same structures
kubeconform validates against), deliberately authored from the API
documentation rather than from this repo's generator or whitelist. No
cluster or network is needed: validation runs offline via ``jsonschema``.

Scope: the eight kinds the generator emits (Namespace, ConfigMap,
PersistentVolumeClaim, Service, Job, Deployment, Ingress, CronJob).
Schemas are STRICT (``additionalProperties: false``) at every level, so
a field the real API does not define fails here even if the whitelist's
mental model agrees with the generator's. On top of the pure structural
schemas, :func:`validate_against_k8s_schema` enforces the cross-field
rules the real API server enforces but JSON Schema cannot express
per-kind locally:

- a Job/CronJob pod template's ``restartPolicy`` must be ``Never`` or
  ``OnFailure`` (``Always`` is only valid for controllers that restart
  pods in place);
- a Deployment's ``selector.matchLabels`` must be a subset of its
  template's labels (the API server rejects the mismatch);
- a CronJob ``schedule`` must parse as 5 cron fields or a ``@``-macro.
"""
from __future__ import annotations

import re

__all__ = ["K8S_KIND_SCHEMAS", "validate_against_k8s_schema"]

# --------------------------------------------------------------------------
# shared fragments (core/v1 types)
# --------------------------------------------------------------------------

_STR = {"type": "string"}
_BOOL = {"type": "boolean"}
_INT = {"type": "integer"}
_STR_MAP = {"type": "object", "additionalProperties": {"type": "string"}}
#: resource.Quantity: "500m", "100Mi", "8", 4, 0.5 ...
_QUANTITY = {
    "oneOf": [
        {"type": "string",
         "pattern": r"^[+-]?([0-9]+|[0-9]+\.[0-9]*|\.[0-9]+)"
                    r"(m|k|Ki|Mi|Gi|Ti|Pi|Ei|K|M|G|T|P|E|n|u)?$"},
        {"type": "number"},
    ]
}
#: IntOrString (ports, maxSurge, targetPort...)
_INT_OR_STR = {"oneOf": [{"type": "integer"}, {"type": "string"}]}
_DNS1123_SUBDOMAIN = (
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)

_OBJECT_META = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "name": {"type": "string", "maxLength": 253,
                 "pattern": _DNS1123_SUBDOMAIN},
        "generateName": _STR,
        "namespace": {"type": "string", "maxLength": 63},
        "labels": _STR_MAP,
        "annotations": _STR_MAP,
        "finalizers": {"type": "array", "items": _STR},
        "ownerReferences": {"type": "array", "items": {"type": "object"}},
        # server-populated fields, legal to submit
        "uid": _STR, "resourceVersion": _STR, "generation": _INT,
        "creationTimestamp": {}, "deletionTimestamp": {},
        "deletionGracePeriodSeconds": _INT, "managedFields": {},
    },
}

_ENV_VAR = {
    "type": "object",
    "additionalProperties": False,
    "required": ["name"],
    "properties": {
        "name": _STR,
        "value": _STR,
        "valueFrom": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "fieldRef": {
                    "type": "object", "additionalProperties": False,
                    "required": ["fieldPath"],
                    "properties": {"apiVersion": _STR, "fieldPath": _STR},
                },
                "resourceFieldRef": {
                    "type": "object", "additionalProperties": False,
                    "required": ["resource"],
                    "properties": {"containerName": _STR, "resource": _STR,
                                   "divisor": _QUANTITY},
                },
                "configMapKeyRef": {
                    "type": "object", "additionalProperties": False,
                    "required": ["key"],
                    "properties": {"name": _STR, "key": _STR,
                                   "optional": _BOOL},
                },
                "secretKeyRef": {
                    "type": "object", "additionalProperties": False,
                    "required": ["key"],
                    "properties": {"name": _STR, "key": _STR,
                                   "optional": _BOOL},
                },
            },
        },
    },
}

_ENV_FROM = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "prefix": _STR,
        "configMapRef": {
            "type": "object", "additionalProperties": False,
            "properties": {"name": _STR, "optional": _BOOL},
        },
        "secretRef": {
            "type": "object", "additionalProperties": False,
            "properties": {"name": _STR, "optional": _BOOL},
        },
    },
}

_PROBE = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "httpGet": {
            "type": "object", "additionalProperties": False,
            "required": ["port"],
            "properties": {
                "path": _STR,
                "port": _INT_OR_STR,
                "host": _STR,
                "scheme": {"enum": ["HTTP", "HTTPS"]},
                "httpHeaders": {
                    "type": "array",
                    "items": {
                        "type": "object", "additionalProperties": False,
                        "required": ["name", "value"],
                        "properties": {"name": _STR, "value": _STR},
                    },
                },
            },
        },
        "exec": {
            "type": "object", "additionalProperties": False,
            "properties": {"command": {"type": "array", "items": _STR}},
        },
        "tcpSocket": {
            "type": "object", "additionalProperties": False,
            "required": ["port"],
            "properties": {"port": _INT_OR_STR, "host": _STR},
        },
        "grpc": {
            "type": "object", "additionalProperties": False,
            "required": ["port"],
            "properties": {"port": _INT, "service": _STR},
        },
        "initialDelaySeconds": _INT,
        "periodSeconds": _INT,
        "timeoutSeconds": _INT,
        "successThreshold": _INT,
        "failureThreshold": _INT,
        "terminationGracePeriodSeconds": _INT,
    },
}

_RESOURCES = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "limits": {"type": "object", "additionalProperties": _QUANTITY},
        "requests": {"type": "object", "additionalProperties": _QUANTITY},
        "claims": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "required": ["name"],
                "properties": {"name": _STR, "request": _STR},
            },
        },
    },
}

_CONTAINER = {
    "type": "object",
    "additionalProperties": False,
    "required": ["name"],
    "properties": {
        "name": {"type": "string", "maxLength": 63,
                 "pattern": r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"},
        "image": _STR,
        "command": {"type": "array", "items": _STR},
        "args": {"type": "array", "items": _STR},
        "workingDir": _STR,
        "ports": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "required": ["containerPort"],
                "properties": {
                    "containerPort": {"type": "integer",
                                      "minimum": 1, "maximum": 65535},
                    "name": {"type": "string", "maxLength": 15},
                    "protocol": {"enum": ["TCP", "UDP", "SCTP"]},
                    "hostPort": _INT,
                    "hostIP": _STR,
                },
            },
        },
        "env": {"type": "array", "items": _ENV_VAR},
        "envFrom": {"type": "array", "items": _ENV_FROM},
        "resources": _RESOURCES,
        "volumeMounts": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "required": ["name", "mountPath"],
                "properties": {
                    "name": _STR, "mountPath": _STR, "subPath": _STR,
                    "subPathExpr": _STR, "readOnly": _BOOL,
                    "mountPropagation": {
                        "enum": ["None", "HostToContainer", "Bidirectional"]
                    },
                    "recursiveReadOnly": _STR,
                },
            },
        },
        "volumeDevices": {"type": "array", "items": {"type": "object"}},
        "livenessProbe": _PROBE,
        "readinessProbe": _PROBE,
        "startupProbe": _PROBE,
        "lifecycle": {"type": "object"},
        "terminationMessagePath": _STR,
        "terminationMessagePolicy": {
            "enum": ["File", "FallbackToLogsOnError"]
        },
        "imagePullPolicy": {"enum": ["Always", "Never", "IfNotPresent"]},
        "securityContext": {"type": "object"},
        "stdin": _BOOL, "stdinOnce": _BOOL, "tty": _BOOL,
        "restartPolicy": {"enum": ["Always"]},  # sidecar initContainers
        "resizePolicy": {"type": "array", "items": {"type": "object"}},
    },
}

_VOLUME = {
    "type": "object",
    "additionalProperties": False,
    "required": ["name"],
    "properties": {
        "name": _STR,
        "configMap": {
            "type": "object", "additionalProperties": False,
            "properties": {
                "name": _STR, "optional": _BOOL, "defaultMode": _INT,
                "items": {
                    "type": "array",
                    "items": {
                        "type": "object", "additionalProperties": False,
                        "required": ["key", "path"],
                        "properties": {"key": _STR, "path": _STR,
                                       "mode": _INT},
                    },
                },
            },
        },
        "secret": {
            "type": "object", "additionalProperties": False,
            "properties": {"secretName": _STR, "optional": _BOOL,
                           "defaultMode": _INT,
                           "items": {"type": "array"}},
        },
        "emptyDir": {
            "type": "object", "additionalProperties": False,
            "properties": {"medium": {"enum": ["", "Memory"]},
                           "sizeLimit": _QUANTITY},
        },
        "hostPath": {
            "type": "object", "additionalProperties": False,
            "required": ["path"],
            "properties": {
                "path": _STR,
                "type": {
                    "enum": ["", "DirectoryOrCreate", "Directory",
                             "FileOrCreate", "File", "Socket",
                             "CharDevice", "BlockDevice"]
                },
            },
        },
        "persistentVolumeClaim": {
            "type": "object", "additionalProperties": False,
            "required": ["claimName"],
            "properties": {"claimName": _STR, "readOnly": _BOOL},
        },
        "csi": {
            "type": "object", "additionalProperties": False,
            "required": ["driver"],
            "properties": {
                "driver": _STR, "readOnly": _BOOL, "fsType": _STR,
                "volumeAttributes": _STR_MAP,
                "nodePublishSecretRef": {"type": "object"},
            },
        },
        "downwardAPI": {"type": "object"},
        "projected": {"type": "object"},
        "nfs": {"type": "object"},
    },
}

_POD_SPEC = {
    "type": "object",
    "additionalProperties": False,
    "required": ["containers"],
    "properties": {
        "containers": {"type": "array", "minItems": 1, "items": _CONTAINER},
        "initContainers": {"type": "array", "items": _CONTAINER},
        "ephemeralContainers": {"type": "array"},
        "volumes": {"type": "array", "items": _VOLUME},
        "restartPolicy": {"enum": ["Always", "OnFailure", "Never"]},
        "terminationGracePeriodSeconds": _INT,
        "activeDeadlineSeconds": _INT,
        "dnsPolicy": {
            "enum": ["ClusterFirst", "ClusterFirstWithHostNet",
                     "Default", "None"]
        },
        "nodeSelector": _STR_MAP,
        "serviceAccountName": _STR,
        "serviceAccount": _STR,
        "automountServiceAccountToken": _BOOL,
        "nodeName": _STR,
        "hostNetwork": _BOOL, "hostPID": _BOOL, "hostIPC": _BOOL,
        "shareProcessNamespace": _BOOL,
        "securityContext": {"type": "object"},
        "imagePullSecrets": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "properties": {"name": _STR},
            },
        },
        "hostname": _STR,
        "subdomain": _STR,
        "affinity": {"type": "object"},
        "schedulerName": _STR,
        "tolerations": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "properties": {
                    "key": _STR,
                    "operator": {"enum": ["Exists", "Equal"]},
                    "value": _STR,
                    "effect": {"enum": ["NoSchedule", "PreferNoSchedule",
                                        "NoExecute"]},
                    "tolerationSeconds": _INT,
                },
            },
        },
        "hostAliases": {"type": "array"},
        "priorityClassName": _STR,
        "priority": _INT,
        "dnsConfig": {"type": "object"},
        "readinessGates": {"type": "array"},
        "runtimeClassName": _STR,
        "enableServiceLinks": _BOOL,
        "preemptionPolicy": {
            "enum": ["PreemptLowerPriority", "Never"]
        },
        "overhead": {"type": "object"},
        "topologySpreadConstraints": {"type": "array"},
        "setHostnameAsFQDN": _BOOL,
        "os": {"type": "object"},
        "hostUsers": _BOOL,
        "schedulingGates": {"type": "array"},
        "resourceClaims": {"type": "array"},
    },
}

_POD_TEMPLATE = {
    "type": "object",
    "additionalProperties": False,
    "properties": {"metadata": _OBJECT_META, "spec": _POD_SPEC},
    "required": ["spec"],
}

_LABEL_SELECTOR = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "matchLabels": _STR_MAP,
        "matchExpressions": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "required": ["key", "operator"],
                "properties": {
                    "key": _STR,
                    "operator": {"enum": ["In", "NotIn", "Exists",
                                          "DoesNotExist"]},
                    "values": {"type": "array", "items": _STR},
                },
            },
        },
    },
}

_JOB_SPEC = {
    "type": "object",
    "additionalProperties": False,
    "required": ["template"],
    "properties": {
        "template": _POD_TEMPLATE,
        "parallelism": _INT,
        "completions": _INT,
        "activeDeadlineSeconds": _INT,
        "backoffLimit": _INT,
        "backoffLimitPerIndex": _INT,
        "maxFailedIndexes": _INT,
        "selector": _LABEL_SELECTOR,
        "manualSelector": _BOOL,
        "ttlSecondsAfterFinished": _INT,
        "completionMode": {"enum": ["NonIndexed", "Indexed"]},
        "suspend": _BOOL,
        "podFailurePolicy": {"type": "object"},
        "podReplacementPolicy": {
            "enum": ["TerminatingOrFailed", "Failed"]
        },
        "successPolicy": {"type": "object"},
    },
}


#: autoscaling/v2 MetricTarget: exactly one value form per target type
_HPA_METRIC_TARGET = {
    "type": "object", "additionalProperties": False,
    "required": ["type"],
    "properties": {
        "type": {"enum": ["Utilization", "Value", "AverageValue"]},
        "value": _QUANTITY,
        "averageValue": _QUANTITY,
        "averageUtilization": _INT,
    },
}

#: autoscaling/v2 HPAScalingRules (the behavior block's two arms)
_HPA_SCALING_RULES = {
    "type": "object", "additionalProperties": False,
    "properties": {
        "stabilizationWindowSeconds": _INT,
        "selectPolicy": {"enum": ["Max", "Min", "Disabled"]},
        "policies": {
            "type": "array",
            "items": {
                "type": "object", "additionalProperties": False,
                "required": ["type", "value", "periodSeconds"],
                "properties": {
                    "type": {"enum": ["Pods", "Percent"]},
                    "value": _INT,
                    "periodSeconds": _INT,
                },
            },
        },
        "tolerance": _QUANTITY,
    },
}


def _top(api_version: str, kind: str, spec, extra: dict | None = None,
         required: tuple = ("metadata",)) -> dict:
    props = {
        "apiVersion": {"const": api_version},
        "kind": {"const": kind},
        "metadata": _OBJECT_META,
    }
    if spec is not None:
        props["spec"] = spec
    props.update(extra or {})
    return {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "type": "object",
        "additionalProperties": False,
        "required": ["apiVersion", "kind", *required],
        "properties": props,
    }


K8S_KIND_SCHEMAS: dict[str, dict] = {
    "Namespace": _top(
        "v1", "Namespace",
        {"type": "object", "additionalProperties": False,
         "properties": {"finalizers": {"type": "array", "items": _STR}}},
    ),
    "ConfigMap": _top(
        "v1", "ConfigMap", None,
        extra={
            "data": _STR_MAP,
            "binaryData": _STR_MAP,
            "immutable": _BOOL,
        },
    ),
    "PersistentVolumeClaim": _top(
        "v1", "PersistentVolumeClaim",
        {
            "type": "object", "additionalProperties": False,
            "properties": {
                "accessModes": {
                    "type": "array",
                    "items": {"enum": ["ReadWriteOnce", "ReadOnlyMany",
                                       "ReadWriteMany",
                                       "ReadWriteOncePod"]},
                },
                "selector": _LABEL_SELECTOR,
                "resources": {
                    "type": "object", "additionalProperties": False,
                    "properties": {
                        "requests": {"type": "object",
                                     "additionalProperties": _QUANTITY},
                        "limits": {"type": "object",
                                   "additionalProperties": _QUANTITY},
                    },
                },
                "volumeName": _STR,
                "storageClassName": _STR,
                "volumeMode": {"enum": ["Filesystem", "Block"]},
                "dataSource": {"type": "object"},
                "dataSourceRef": {"type": "object"},
                "volumeAttributesClassName": _STR,
            },
        },
    ),
    "Service": _top(
        "v1", "Service",
        {
            "type": "object", "additionalProperties": False,
            "properties": {
                "selector": _STR_MAP,
                "ports": {
                    "type": "array",
                    "items": {
                        "type": "object", "additionalProperties": False,
                        "required": ["port"],
                        "properties": {
                            "name": _STR,
                            "protocol": {"enum": ["TCP", "UDP", "SCTP"]},
                            "appProtocol": _STR,
                            "port": {"type": "integer",
                                     "minimum": 1, "maximum": 65535},
                            "targetPort": _INT_OR_STR,
                            "nodePort": _INT,
                        },
                    },
                },
                "clusterIP": _STR,
                "clusterIPs": {"type": "array", "items": _STR},
                "type": {"enum": ["ClusterIP", "NodePort", "LoadBalancer",
                                  "ExternalName"]},
                "externalIPs": {"type": "array", "items": _STR},
                "sessionAffinity": {"enum": ["None", "ClientIP"]},
                "loadBalancerIP": _STR,
                "loadBalancerSourceRanges": {"type": "array",
                                             "items": _STR},
                "externalName": _STR,
                "externalTrafficPolicy": {"enum": ["Cluster", "Local"]},
                "healthCheckNodePort": _INT,
                "publishNotReadyAddresses": _BOOL,
                "sessionAffinityConfig": {"type": "object"},
                "ipFamilies": {"type": "array",
                               "items": {"enum": ["IPv4", "IPv6"]}},
                "ipFamilyPolicy": {
                    "enum": ["SingleStack", "PreferDualStack",
                             "RequireDualStack"]
                },
                "allocateLoadBalancerNodePorts": _BOOL,
                "loadBalancerClass": _STR,
                "internalTrafficPolicy": {"enum": ["Cluster", "Local"]},
                "trafficDistribution": _STR,
            },
        },
    ),
    "Job": _top("batch/v1", "Job", _JOB_SPEC),
    "Deployment": _top(
        "apps/v1", "Deployment",
        {
            "type": "object", "additionalProperties": False,
            "required": ["selector", "template"],
            "properties": {
                "replicas": {"type": "integer", "minimum": 0},
                "selector": _LABEL_SELECTOR,
                "template": _POD_TEMPLATE,
                "strategy": {
                    "type": "object", "additionalProperties": False,
                    "properties": {
                        "type": {"enum": ["Recreate", "RollingUpdate"]},
                        "rollingUpdate": {
                            "type": "object",
                            "additionalProperties": False,
                            "properties": {"maxSurge": _INT_OR_STR,
                                           "maxUnavailable": _INT_OR_STR},
                        },
                    },
                },
                "minReadySeconds": _INT,
                "revisionHistoryLimit": _INT,
                "paused": _BOOL,
                "progressDeadlineSeconds": _INT,
            },
        },
    ),
    "Ingress": _top(
        "networking.k8s.io/v1", "Ingress",
        {
            "type": "object", "additionalProperties": False,
            "properties": {
                "ingressClassName": _STR,
                "defaultBackend": {"type": "object"},
                "tls": {"type": "array"},
                "rules": {
                    "type": "array",
                    "items": {
                        "type": "object", "additionalProperties": False,
                        "properties": {
                            "host": _STR,
                            "http": {
                                "type": "object",
                                "additionalProperties": False,
                                "required": ["paths"],
                                "properties": {
                                    "paths": {
                                        "type": "array",
                                        "minItems": 1,
                                        "items": {
                                            "type": "object",
                                            "additionalProperties": False,
                                            "required": ["pathType",
                                                         "backend"],
                                            "properties": {
                                                "path": _STR,
                                                "pathType": {
                                                    "enum": [
                                                        "Exact", "Prefix",
                                                        "ImplementationSpecific",
                                                    ]
                                                },
                                                "backend": {
                                                    "type": "object",
                                                    "additionalProperties": False,
                                                    "properties": {
                                                        "service": {
                                                            "type": "object",
                                                            "additionalProperties": False,
                                                            "required": ["name"],
                                                            "properties": {
                                                                "name": _STR,
                                                                "port": {
                                                                    "type": "object",
                                                                    "additionalProperties": False,
                                                                    "properties": {
                                                                        "name": _STR,
                                                                        "number": _INT,
                                                                    },
                                                                },
                                                            },
                                                        },
                                                        "resource": {
                                                            "type": "object"
                                                        },
                                                    },
                                                },
                                            },
                                        },
                                    }
                                },
                            },
                        },
                    },
                },
            },
        },
    ),
    "HorizontalPodAutoscaler": _top(
        "autoscaling/v2", "HorizontalPodAutoscaler",
        {
            "type": "object", "additionalProperties": False,
            "required": ["scaleTargetRef", "maxReplicas"],
            "properties": {
                "scaleTargetRef": {
                    "type": "object", "additionalProperties": False,
                    "required": ["apiVersion", "kind", "name"],
                    "properties": {
                        "apiVersion": _STR, "kind": _STR, "name": _STR,
                    },
                },
                "minReplicas": {"type": "integer", "minimum": 1},
                "maxReplicas": {"type": "integer", "minimum": 1},
                "metrics": {
                    "type": "array",
                    "items": {
                        "type": "object", "additionalProperties": False,
                        "required": ["type"],
                        "properties": {
                            "type": {"enum": ["Pods", "Resource", "Object",
                                              "External",
                                              "ContainerResource"]},
                            "pods": {
                                "type": "object",
                                "additionalProperties": False,
                                "required": ["metric", "target"],
                                "properties": {
                                    "metric": {
                                        "type": "object",
                                        "additionalProperties": False,
                                        "required": ["name"],
                                        "properties": {
                                            "name": _STR,
                                            "selector": _LABEL_SELECTOR,
                                        },
                                    },
                                    "target": _HPA_METRIC_TARGET,
                                },
                            },
                            "resource": {
                                "type": "object",
                                "additionalProperties": False,
                                "required": ["name", "target"],
                                "properties": {
                                    "name": _STR,
                                    "target": _HPA_METRIC_TARGET,
                                },
                            },
                            "object": {"type": "object"},
                            "external": {"type": "object"},
                            "containerResource": {"type": "object"},
                        },
                    },
                },
                "behavior": {
                    "type": "object", "additionalProperties": False,
                    "properties": {
                        "scaleUp": _HPA_SCALING_RULES,
                        "scaleDown": _HPA_SCALING_RULES,
                    },
                },
            },
        },
    ),
    "CronJob": _top(
        "batch/v1", "CronJob",
        {
            "type": "object", "additionalProperties": False,
            "required": ["schedule", "jobTemplate"],
            "properties": {
                "schedule": _STR,
                "timeZone": _STR,
                "startingDeadlineSeconds": _INT,
                "concurrencyPolicy": {"enum": ["Allow", "Forbid",
                                               "Replace"]},
                "suspend": _BOOL,
                "jobTemplate": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"metadata": _OBJECT_META,
                                   "spec": _JOB_SPEC},
                },
                "successfulJobsHistoryLimit": _INT,
                "failedJobsHistoryLimit": _INT,
            },
        },
    ),
}

#: 5-field cron line or @-macro, the syntax batch/v1 accepts
_CRON_RE = re.compile(
    r"^(@(annually|yearly|monthly|weekly|daily|midnight|hourly)"
    r"|(\S+\s+){4}\S+)$"
)


def _job_template_errors(job_spec: dict, where: str) -> list[str]:
    errors = []
    rp = (job_spec.get("template", {}).get("spec", {})
          .get("restartPolicy"))
    # the API server requires an explicit Never/OnFailure for Job pods
    if rp not in ("Never", "OnFailure"):
        errors.append(
            f"{where}.template.spec.restartPolicy must be 'Never' or "
            f"'OnFailure' for Job pods, got {rp!r}"
        )
    return errors


def validate_against_k8s_schema(doc: dict, origin: str = "<doc>") -> list[str]:
    """Validate one manifest against the vendored upstream-API schemas.
    Returns a list of error strings (empty = valid). Unknown kinds are an
    error: the generator must only emit kinds this layer can check."""
    import jsonschema

    kind = doc.get("kind")
    schema = K8S_KIND_SCHEMAS.get(kind)
    if schema is None:
        return [f"{origin}: kind {kind!r} has no vendored schema"]
    validator = jsonschema.Draft7Validator(schema)
    errors = [
        f"{origin}: {'.'.join(str(p) for p in e.absolute_path) or '<root>'}"
        f": {e.message}"
        for e in validator.iter_errors(doc)
    ]

    # cross-field rules the API server enforces
    spec = doc.get("spec", {}) if isinstance(doc.get("spec"), dict) else {}
    if kind == "Job" and isinstance(spec, dict):
        errors += [f"{origin}: {m}"
                   for m in _job_template_errors(spec, "spec")]
    if kind == "CronJob" and isinstance(spec, dict):
        schedule = spec.get("schedule")
        if isinstance(schedule, str) and not _CRON_RE.match(schedule.strip()):
            errors.append(
                f"{origin}: spec.schedule {schedule!r} is not a 5-field "
                "cron line or @-macro"
            )
        jt = spec.get("jobTemplate", {}).get("spec")
        if isinstance(jt, dict):
            errors += [
                f"{origin}: {m}"
                for m in _job_template_errors(jt, "spec.jobTemplate.spec")
            ]
    if kind == "Deployment" and isinstance(spec, dict):
        match = (spec.get("selector") or {}).get("matchLabels") or {}
        tmpl_labels = ((spec.get("template") or {}).get("metadata") or {}
                       ).get("labels") or {}
        missing = {
            k: v for k, v in match.items() if tmpl_labels.get(k) != v
        }
        if missing:
            errors.append(
                f"{origin}: spec.selector.matchLabels {missing} not "
                "present in spec.template.metadata.labels — the API "
                "server rejects this Deployment"
            )
    return errors
