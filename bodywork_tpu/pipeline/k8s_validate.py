"""Strict structural validation of the emitted k8s manifests.

The manifest generator (:mod:`bodywork_tpu.pipeline.k8s`) is tested for
structure, but a structure test cannot catch a typo'd *field name* — k8s
object schemas treat unknown fields as errors only at ``kubectl apply``
(server-side validation), which is exactly the wrong time to find out.
This module is the CI-time stand-in for that server-side check: a strict
per-kind whitelist validator. Every mapping level the generator emits is
checked against the set of field names the k8s OpenAPI schema defines
there (the subset this framework can emit, plus common optional siblings),
and required fields are enforced. An unknown key — i.e. any misspelling —
fails validation.

This is deliberately NOT a vendored OpenAPI schema: the whitelists cover
the object kinds the generator emits (Namespace, ConfigMap,
PersistentVolumeClaim, Job, Deployment, Service, Ingress, CronJob) and
fail loudly on anything outside them, which is the correct behaviour for
a generator whose output surface is closed.
"""
from __future__ import annotations

from typing import Any

#: apiVersion each kind must carry (a wrong group/version also only fails
#: at apply time otherwise)
EXPECTED_API_VERSION = {
    "Namespace": "v1",
    "ConfigMap": "v1",
    "PersistentVolumeClaim": "v1",
    "Service": "v1",
    "Job": "batch/v1",
    "CronJob": "batch/v1",
    "Deployment": "apps/v1",
    "Ingress": "networking.k8s.io/v1",
    "HorizontalPodAutoscaler": "autoscaling/v2",
}


class ManifestError(ValueError):
    """One or more emitted manifests are structurally invalid."""


def _check(
    obj: Any,
    allowed: dict[str, Any],
    required: tuple[str, ...],
    path: str,
    errors: list[str],
) -> None:
    """Validate one mapping level: required keys present, no unknown keys,
    and recurse where the whitelist provides a sub-validator."""
    if not isinstance(obj, dict):
        errors.append(f"{path}: expected a mapping, got {type(obj).__name__}")
        return
    for key in required:
        if key not in obj:
            errors.append(f"{path}: missing required field {key!r}")
    for key, value in obj.items():
        if key not in allowed:
            errors.append(
                f"{path}: unknown field {key!r} (allowed: {sorted(allowed)})"
            )
            continue
        sub = allowed[key]
        if callable(sub):
            sub(value, f"{path}.{key}", errors)


def _scalar(value: Any, path: str, errors: list[str]) -> None:
    if isinstance(value, (dict, list)):
        errors.append(f"{path}: expected a scalar")


def _str_map(value: Any, path: str, errors: list[str]) -> None:
    if not isinstance(value, dict):
        errors.append(f"{path}: expected a mapping")
        return
    for k, v in value.items():
        if not isinstance(k, str):
            errors.append(f"{path}: non-string key {k!r}")
        if isinstance(v, (dict, list)):
            errors.append(f"{path}.{k}: expected a scalar value")


def _each(item_validator):
    def validate(value: Any, path: str, errors: list[str]) -> None:
        if not isinstance(value, list):
            errors.append(f"{path}: expected a list")
            return
        for i, item in enumerate(value):
            item_validator(item, f"{path}[{i}]", errors)

    return validate


def _mapping(allowed: dict[str, Any], required: tuple[str, ...] = ()):
    def validate(value: Any, path: str, errors: list[str]) -> None:
        _check(value, allowed, required, path, errors)

    return validate


_metadata = _mapping(
    {
        "name": _scalar,
        "namespace": _scalar,
        "labels": _str_map,
        "annotations": _str_map,
    },
    required=("name",),
)

_env_var = _mapping(
    {"name": _scalar, "value": _scalar},
    required=("name",),
)

_env_from = _mapping(
    {
        "secretRef": _mapping(
            {"name": _scalar, "optional": _scalar}, required=("name",)
        ),
        "configMapRef": _mapping(
            {"name": _scalar, "optional": _scalar}, required=("name",)
        ),
    },
)

_volume_mount = _mapping(
    {"name": _scalar, "mountPath": _scalar, "readOnly": _scalar,
     "subPath": _scalar},
    required=("name", "mountPath"),
)

_probe = _mapping(
    {
        "httpGet": _mapping(
            {"path": _scalar, "port": _scalar, "scheme": _scalar},
            required=("port",),
        ),
        "tcpSocket": _mapping({"port": _scalar}, required=("port",)),
        "exec": _mapping({"command": _each(_scalar)}, required=("command",)),
        "initialDelaySeconds": _scalar,
        "periodSeconds": _scalar,
        "timeoutSeconds": _scalar,
        "failureThreshold": _scalar,
        "successThreshold": _scalar,
    },
)

_container = _mapping(
    {
        "name": _scalar,
        "image": _scalar,
        "command": _each(_scalar),
        "args": _each(_scalar),
        "env": _each(_env_var),
        "envFrom": _each(_env_from),
        "volumeMounts": _each(_volume_mount),
        "resources": _mapping(
            {"requests": _str_map, "limits": _str_map},
        ),
        "ports": _each(
            _mapping(
                {"containerPort": _scalar, "name": _scalar, "protocol": _scalar},
                required=("containerPort",),
            )
        ),
        "readinessProbe": _probe,
        "livenessProbe": _probe,
        "workingDir": _scalar,
        "imagePullPolicy": _scalar,
    },
    required=("name", "image"),
)

_volume = _mapping(
    {
        "name": _scalar,
        "hostPath": _mapping(
            {"path": _scalar, "type": _scalar}, required=("path",)
        ),
        "persistentVolumeClaim": _mapping(
            {"claimName": _scalar, "readOnly": _scalar},
            required=("claimName",),
        ),
        "configMap": _mapping(
            {"name": _scalar, "items": _each(_mapping(
                {"key": _scalar, "path": _scalar}, required=("key", "path")
            ))},
            required=("name",),
        ),
        "emptyDir": _mapping({"medium": _scalar, "sizeLimit": _scalar}),
    },
    required=("name",),
)

_pod_spec = _mapping(
    {
        "containers": _each(_container),
        "initContainers": _each(_container),
        "volumes": _each(_volume),
        "restartPolicy": _scalar,
        "nodeSelector": _str_map,
        "subdomain": _scalar,
        "serviceAccountName": _scalar,
        "terminationGracePeriodSeconds": _scalar,
        "tolerations": _each(_mapping(
            {"key": _scalar, "operator": _scalar, "value": _scalar,
             "effect": _scalar},
        )),
    },
    required=("containers",),
)

_pod_template = _mapping(
    {
        "metadata": _mapping({"labels": _str_map, "annotations": _str_map}),
        "spec": _pod_spec,
    },
    required=("spec",),
)

_job_spec = _mapping(
    {
        "backoffLimit": _scalar,
        "activeDeadlineSeconds": _scalar,
        "completions": _scalar,
        "parallelism": _scalar,
        "completionMode": _scalar,
        "ttlSecondsAfterFinished": _scalar,
        "template": _pod_template,
    },
    required=("template",),
)

_hpa_metric_target = _mapping(
    {
        "type": _scalar,
        "value": _scalar,
        "averageValue": _scalar,
        "averageUtilization": _scalar,
    },
    required=("type",),
)

_hpa_scaling_rules = _mapping(
    {
        "stabilizationWindowSeconds": _scalar,
        "selectPolicy": _scalar,
        "policies": _each(_mapping(
            {"type": _scalar, "value": _scalar, "periodSeconds": _scalar},
            required=("type", "value", "periodSeconds"),
        )),
    },
)

_KIND_SPEC_VALIDATORS: dict[str, Any] = {
    "Namespace": _mapping({"metadata": _metadata}, required=("metadata",)),
    "ConfigMap": _mapping(
        {
            "metadata": _metadata,
            "data": _str_map,
            "binaryData": _str_map,
            "immutable": _scalar,
        },
        required=("metadata",),
    ),
    "PersistentVolumeClaim": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "accessModes": _each(_scalar),
                    "resources": _mapping(
                        {"requests": _str_map, "limits": _str_map},
                        required=("requests",),
                    ),
                    "storageClassName": _scalar,
                    "volumeMode": _scalar,
                },
                required=("accessModes", "resources"),
            ),
        },
        required=("metadata", "spec"),
    ),
    "Job": _mapping(
        {"metadata": _metadata, "spec": _job_spec},
        required=("metadata", "spec"),
    ),
    "Deployment": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "replicas": _scalar,
                    "selector": _mapping(
                        {"matchLabels": _str_map}, required=("matchLabels",)
                    ),
                    "template": _pod_template,
                    "strategy": _mapping(
                        {"type": _scalar, "rollingUpdate": _str_map},
                    ),
                },
                required=("selector", "template"),
            ),
        },
        required=("metadata", "spec"),
    ),
    "Service": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "selector": _str_map,
                    "ports": _each(
                        _mapping(
                            {
                                "port": _scalar,
                                "targetPort": _scalar,
                                "name": _scalar,
                                "protocol": _scalar,
                                "nodePort": _scalar,
                            },
                            required=("port",),
                        )
                    ),
                    "type": _scalar,
                    "clusterIP": _scalar,
                    "publishNotReadyAddresses": _scalar,
                },
                required=("ports",),
            ),
        },
        required=("metadata", "spec"),
    ),
    "Ingress": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "ingressClassName": _scalar,
                    "defaultBackend": _mapping(
                        {
                            "service": _mapping(
                                {
                                    "name": _scalar,
                                    "port": _mapping(
                                        {"number": _scalar, "name": _scalar},
                                    ),
                                },
                                required=("name",),
                            )
                        },
                    ),
                    "rules": _each(
                        _mapping(
                            {
                                "host": _scalar,
                                "http": _mapping(
                                    {
                                        "paths": _each(
                                            _mapping(
                                                {
                                                    "path": _scalar,
                                                    "pathType": _scalar,
                                                    "backend": _mapping(
                                                        {
                                                            "service": _mapping(
                                                                {
                                                                    "name": _scalar,
                                                                    "port": _mapping(
                                                                        {
                                                                            "number": _scalar,
                                                                            "name": _scalar,
                                                                        },
                                                                    ),
                                                                },
                                                                required=("name",),
                                                            )
                                                        },
                                                        required=("service",),
                                                    ),
                                                },
                                                required=("pathType", "backend"),
                                            )
                                        )
                                    },
                                    required=("paths",),
                                ),
                            },
                        )
                    ),
                    "tls": _each(_mapping(
                        {"hosts": _each(_scalar), "secretName": _scalar},
                    )),
                },
            ),
        },
        required=("metadata", "spec"),
    ),
    "HorizontalPodAutoscaler": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "scaleTargetRef": _mapping(
                        {"apiVersion": _scalar, "kind": _scalar,
                         "name": _scalar},
                        required=("apiVersion", "kind", "name"),
                    ),
                    "minReplicas": _scalar,
                    "maxReplicas": _scalar,
                    "metrics": _each(_mapping(
                        {
                            "type": _scalar,
                            "pods": _mapping(
                                {
                                    "metric": _mapping(
                                        {"name": _scalar},
                                        required=("name",),
                                    ),
                                    "target": _hpa_metric_target,
                                },
                                required=("metric", "target"),
                            ),
                            "resource": _mapping(
                                {"name": _scalar,
                                 "target": _hpa_metric_target},
                                required=("name", "target"),
                            ),
                        },
                        required=("type",),
                    )),
                    "behavior": _mapping(
                        {"scaleUp": _hpa_scaling_rules,
                         "scaleDown": _hpa_scaling_rules},
                    ),
                },
                required=("scaleTargetRef", "maxReplicas"),
            ),
        },
        required=("metadata", "spec"),
    ),
    "CronJob": _mapping(
        {
            "metadata": _metadata,
            "spec": _mapping(
                {
                    "schedule": _scalar,
                    "concurrencyPolicy": _scalar,
                    "startingDeadlineSeconds": _scalar,
                    "suspend": _scalar,
                    "successfulJobsHistoryLimit": _scalar,
                    "failedJobsHistoryLimit": _scalar,
                    "jobTemplate": _mapping(
                        {
                            "metadata": _mapping(
                                {"labels": _str_map, "annotations": _str_map}
                            ),
                            "spec": _job_spec,
                        },
                        required=("spec",),
                    ),
                },
                required=("schedule", "jobTemplate"),
            ),
        },
        required=("metadata", "spec"),
    ),
}


def validate_manifest(doc: dict, origin: str = "<manifest>") -> list[str]:
    """Validate one emitted k8s object; returns error strings (empty = ok)."""
    errors: list[str] = []
    if not isinstance(doc, dict):
        return [f"{origin}: manifest must be a mapping"]
    kind = doc.get("kind")
    if kind not in _KIND_SPEC_VALIDATORS:
        return [
            f"{origin}: unknown or missing kind {kind!r} "
            f"(validatable: {sorted(_KIND_SPEC_VALIDATORS)})"
        ]
    expected_version = EXPECTED_API_VERSION[kind]
    if doc.get("apiVersion") != expected_version:
        errors.append(
            f"{origin}: {kind} apiVersion must be {expected_version!r}, "
            f"got {doc.get('apiVersion')!r}"
        )
    body = {k: v for k, v in doc.items() if k not in ("apiVersion", "kind")}
    _KIND_SPEC_VALIDATORS[kind](body, f"{origin}:{kind}", errors)
    return errors


def validate_split_serving(docs: dict[str, dict]) -> list[str]:
    """Semantic checks for the cross-host disaggregated serving split
    (``--dispatcher`` Deployments emitted when a service stage declares
    the tcp row-queue transport): the generic whitelist/schema layers
    cannot see that the two Deployments and the dispatcher Service must
    agree with each other. Checks, per dispatcher Deployment: exactly
    one replica (the row-queue contract is N front-ends -> ONE scorer),
    a tcpSocket readiness probe (the dispatcher serves no HTTP), a
    dispatcher Service targeting the same app label on the probed port,
    a paired front-end Deployment running ``--role frontend`` with a
    ``--dispatcher-addr`` naming that Service, the serve env knobs
    materialised on the front-end container, and every HPA targeting
    the FRONT-END Deployment — autoscaling the singleton dispatcher
    would violate the one-scorer contract. Returns error strings.

    Replica rule (ISSUE 19): WITHOUT standby mode the dispatcher must
    run exactly 1 replica (two unfenced dispatchers would both bind
    and split the coalescer's row union); WITH standby declared
    (``--standby`` in the command or a truthy
    ``BODYWORK_TPU_SERVE_STANDBY`` env) up to 2 replicas are accepted —
    warm candidates arbitrated by the CAS lease, only the leader binds
    the probed port. More than 2 is refused either way: extra standbys
    buy no additional fault tolerance for their device cost."""
    errors: list[str] = []
    deployments = {
        doc["metadata"]["name"]: (filename, doc)
        for filename, doc in docs.items()
        if isinstance(doc, dict) and doc.get("kind") == "Deployment"
    }
    services = {
        doc["metadata"]["name"]: doc
        for filename, doc in docs.items()
        if isinstance(doc, dict) and doc.get("kind") == "Service"
    }
    hpa_targets = [
        (filename, doc["spec"]["scaleTargetRef"]["name"])
        for filename, doc in docs.items()
        if isinstance(doc, dict)
        and doc.get("kind") == "HorizontalPodAutoscaler"
    ]
    for name, (filename, doc) in deployments.items():
        if not name.endswith("--dispatcher"):
            continue
        spec = doc["spec"]
        container = spec["template"]["spec"]["containers"][0]
        env_values = {
            e.get("name"): str(e.get("value", ""))
            for e in container.get("env", [])
        }
        standby = "--standby" in container.get("command", []) or (
            env_values.get("BODYWORK_TPU_SERVE_STANDBY", "")
            .strip().lower() in ("1", "true", "yes", "on")
        )
        replicas = spec.get("replicas")
        if standby:
            if replicas not in (1, 2):
                errors.append(
                    f"{filename}: standby dispatcher Deployment {name!r} "
                    f"may run 1 or 2 replicas (the active/standby pair), "
                    f"got {replicas!r}"
                )
        elif replicas != 1:
            errors.append(
                f"{filename}: dispatcher Deployment {name!r} must run "
                f"exactly 1 replica without standby mode (scale needs "
                f"--standby: lease-fenced leadership, one serving "
                f"leader), got {replicas!r}"
            )
        probe = container.get("readinessProbe", {})
        if "tcpSocket" not in probe:
            errors.append(
                f"{filename}: dispatcher Deployment {name!r} needs a "
                "tcpSocket readinessProbe (it serves no HTTP)"
            )
        port = probe.get("tcpSocket", {}).get("port")
        svc = services.get(name)
        if svc is None:
            errors.append(
                f"{filename}: dispatcher Deployment {name!r} has no "
                "matching Service (front-ends resolve the dispatcher "
                "through it)"
            )
        else:
            app = doc["metadata"]["labels"].get("app")
            if svc["spec"].get("selector", {}).get("app") != app:
                errors.append(
                    f"{filename}: dispatcher Service {name!r} selector "
                    f"does not target app={app!r}"
                )
            svc_ports = [p.get("port") for p in svc["spec"].get("ports", [])]
            if port is not None and port not in svc_ports:
                errors.append(
                    f"{filename}: dispatcher Service {name!r} ports "
                    f"{svc_ports} do not include the probed row-queue "
                    f"port {port}"
                )
        for target in ("--role", "dispatcher"):
            if target not in container.get("command", []):
                errors.append(
                    f"{filename}: dispatcher Deployment {name!r} command "
                    f"must run `cli serve --role dispatcher` "
                    f"(missing {target!r})"
                )
        # the paired front-end Deployment keeps the stage's standard
        # name (= this name minus the suffix) so Service/Ingress/HPA
        # keep targeting it
        fe_name = name[: -len("--dispatcher")]
        fe = deployments.get(fe_name)
        if fe is None:
            errors.append(
                f"{filename}: dispatcher {name!r} has no paired "
                f"front-end Deployment {fe_name!r}"
            )
        else:
            fe_filename, fe_doc = fe
            fe_container = (
                fe_doc["spec"]["template"]["spec"]["containers"][0]
            )
            fe_cmd = fe_container.get("command", [])
            if "frontend" not in fe_cmd or "--role" not in fe_cmd:
                errors.append(
                    f"{fe_filename}: front-end Deployment {fe_name!r} "
                    "command must run `cli serve --role frontend`"
                )
            addr = None
            for flag, value in zip(fe_cmd, fe_cmd[1:]):
                if flag == "--dispatcher-addr":
                    addr = value
            if addr is None or not addr.startswith(f"{name}:"):
                errors.append(
                    f"{fe_filename}: front-end Deployment {fe_name!r} "
                    f"--dispatcher-addr {addr!r} does not name the "
                    f"dispatcher Service {name!r}"
                )
            env_names = {
                e.get("name") for e in fe_container.get("env", [])
            }
            for knob in ("BODYWORK_TPU_SERVE_TRANSPORT",
                         "BODYWORK_TPU_SERVER_ENGINE",
                         "BODYWORK_TPU_FRONTENDS",
                         "BODYWORK_TPU_MAX_PENDING"):
                if knob not in env_names:
                    errors.append(
                        f"{fe_filename}: front-end Deployment "
                        f"{fe_name!r} must materialise the {knob} env "
                        "knob"
                    )
        for hpa_filename, target in hpa_targets:
            if target == name:
                errors.append(
                    f"{hpa_filename}: HPA must target the front-end "
                    f"Deployment, not the singleton dispatcher {name!r}"
                )
    return errors


def validate_manifests(docs: dict[str, dict]) -> None:
    """Validate every generated manifest; raise :class:`ManifestError`
    listing ALL problems (not just the first) on any failure.

    Two independent layers run on every emit: this module's fast
    whitelist (unknown-field typo class) AND the vendored upstream-API
    schemas (``pipeline.k8s_schema`` — types, required fields, enums,
    and the cross-field rules the API server enforces). The second layer
    exists because the whitelist shares its author's mental model with
    the generator; the schemas are transcribed from the Kubernetes API
    types instead (VERDICT r4 item 7)."""
    from bodywork_tpu.pipeline.k8s_schema import validate_against_k8s_schema

    errors: list[str] = []
    for filename, doc in docs.items():
        errors.extend(validate_manifest(doc, filename))
        if isinstance(doc, dict):
            errors.extend(validate_against_k8s_schema(doc, filename))
    errors.extend(validate_split_serving(docs))
    if errors:
        raise ManifestError(
            "invalid generated manifests:\n  " + "\n  ".join(errors)
        )
